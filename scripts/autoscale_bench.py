"""Predictive-autoscaler diurnal replay (ISSUE 15): sense -> decide ->
actuate on REAL engines, scored against static provisioning at equal
chip-seconds.

The trace is a seeded multi-tenant day — one sinusoidal diurnal rate
per QoS class (gold/silver/bronze tenants peak at different hours) plus
seeded traffic bursts — compressed ~1000-5000x so a 24h cycle replays
in tens of wall seconds (--compress; 1000 reproduces the paper-scale
trace).  The autoscaled run drives a :class:`ClusterAutoscaler` over a
fleet of tiny paged ContinuousEngines: scale-up builds + pre-warms a
replica before it takes traffic (the measured COLD START fed back via
``note_cold_start`` — that EWMA is the scale-to-zero budget), scale-down
drains the least-loaded victim losslessly through
``migrate_live_sequences``.  The static baseline replays the SAME
arrivals on ``round(chip_seconds_auto / duration)`` fixed replicas —
equal chips, so the score isolates WHEN capacity exists, not how much.

Scored per class: SLO attainment (fraction of requests finishing inside
the class SLO).  Hard invariants asserted, not just reported: every
scale-down drain moves every sequence (failed == 0), every request
completes with its full token budget, ``kv_blocks_leaked_total == 0``
and ``jit_recompiles_total == 0`` across every engine that ever served.

The scorer/trace helpers (`diurnal_arrivals`, `chip_seconds`,
`static_replicas_for`, `slo_attainment`) are pure module-level
functions — ``tests/test_autoscale.py`` imports them (this module
defers jax imports into the bench bodies for exactly that reason).

Prints one JSON row per metric (the perf_sweep.py driver schema).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

sys.path.insert(0, ".")

PROBE_TIMEOUT_S = 120.0

# -- pure trace + scoring helpers --------------------------------------
# Moved to kubeflow_tpu/sim/traces.py (ISSUE 20) so the digital twin
# replays the SAME trace through the SAME scorer; re-exported here
# because tests/test_autoscale.py (and downstream users) import them
# from this module.
from kubeflow_tpu.sim.traces import (  # noqa: E402
    CLASSES,
    chip_seconds,
    diurnal_arrivals,
    diurnal_policy,
    slo_attainment,
    static_replicas_for,
)

# -- the fleet under test -------------------------------------------------

class MiniFleet:
    """A handful of tiny paged ContinuousEngines behind least-loaded
    dispatch — the smallest real fleet the autoscaler's actuators can
    move: add_replica builds + pre-warms (one compiled generation)
    before the replica takes traffic; remove_replica drains the
    lightest victim through migrate_live_sequences (lossless or it
    raises).  Retired engines' stats are folded into the leak and
    recompile audit, so a drained replica cannot hide a leak."""

    def __init__(self, cfg, params, *, max_replicas: int = 4,
                 slots_per_replica: int = 4, aot_root: str = None):
        self.cfg, self.params = cfg, params
        self.max_replicas = max_replicas
        self.slots = slots_per_replica
        self.engines = []
        self._lock = threading.Lock()
        #: replicas being built+warmed (counted as capacity-to-be in
        #: ``signals`` so the loop doesn't storm scale-up while one is
        #: in flight, but taking NO traffic until warm)
        self.pending = 0
        self.cold_starts = []
        self.scale_downs = 0
        self.migrated = 0
        self._retired_stats = []
        #: shared AOT program-artifact cache (ISSUE 17): replicas after
        #: the first load their warmup ladder from disk instead of
        #: compiling it; per-replica hit counts recorded at add time
        self.program_cache = None
        self.aot_prewarm_hits = []
        if aot_root is not None:
            from kubeflow_tpu.serving.programs import ProgramArtifactCache
            self.program_cache = ProgramArtifactCache(aot_root)

    def _build(self):
        from kubeflow_tpu.serving.continuous import ContinuousEngine

        return ContinuousEngine(
            self.cfg, self.params, num_slots=self.slots, decode_chunk=2,
            prefix_cache=False, block_size=16,
            program_cache=self.program_cache)

    def add_replica(self) -> float:
        """Build + pre-warm one replica; returns the measured cold
        start (build -> first compiled generation done) in seconds.
        With a shared artifact cache the pre-warm runs the full warmup
        ladder (cache consults happen pre-seal only), so a later
        replica fetches artifacts instead of compiling."""
        with self._lock:
            if len(self.engines) + self.pending >= self.max_replicas:
                raise RuntimeError("at max replicas")
            self.pending += 1
        try:
            before = (self.program_cache.stats()["aot_cache_hits_total"]
                      if self.program_cache is not None else 0)
            t0 = time.perf_counter()
            eng = self._build()
            if self.program_cache is not None:
                eng.warmup()
            eng.generate([1, 2, 3, 4], max_new_tokens=4, timeout=120.0)
            cold = time.perf_counter() - t0
            if self.program_cache is not None:
                self.aot_prewarm_hits.append(
                    self.program_cache.stats()["aot_cache_hits_total"]
                    - before)
            with self._lock:
                self.engines.append(eng)
        finally:
            with self._lock:
                self.pending -= 1
        self.cold_starts.append(cold)
        return cold

    def add_replica_async(self, on_cold_start=None) -> None:
        """The scale-up actuator shape the controller uses: the replica
        warms OFF the decision path and joins the fleet only when its
        first generation has compiled — the loop keeps ticking, and
        ``signals`` counts the build as pending capacity meanwhile."""
        def work():
            try:
                cold = self.add_replica()
            except RuntimeError:
                return
            if on_cold_start is not None:
                # tag the sample with the cache outcome so the EWMA
                # tracks warm wakes separately (ISSUE 17)
                warm = bool(self.aot_prewarm_hits
                            and self.aot_prewarm_hits[-1] > 0)
                on_cold_start(cold, warm=warm)
        threading.Thread(target=work, name="fleet-prewarm",
                         daemon=True).start()

    @staticmethod
    def _load(eng) -> int:
        return eng._queue.qsize() + int(eng._active.sum())

    def remove_replica(self) -> int:
        """Retire the least-loaded replica: drain every live sequence
        onto the survivors (copy-then-cutover), then stop it.  Raises
        if any sequence fails to move — a lossy scale-down is a bench
        FAILURE, not a data point."""
        from kubeflow_tpu.serving.continuous import migrate_live_sequences

        with self._lock:
            if len(self.engines) <= 1:
                raise RuntimeError("at replica floor")
            victim = min(self.engines, key=self._load)
            self.engines.remove(victim)
            survivors = list(self.engines)
        moved = 0
        dst = max(survivors, key=lambda e: e._alloc.free_blocks)
        m, failed = migrate_live_sequences(victim, dst)
        moved += m
        if failed:
            with self._lock:  # put it back — never lose conversations
                self.engines.append(victim)
            raise RuntimeError(
                f"scale-down NOT lossless: {failed} sequences stranded")
        self._retired_stats.append(victim.stats())
        victim.stop()
        self.scale_downs += 1
        self.migrated += moved
        return moved

    def submit(self, prompt, priority: int, max_new: int):
        with self._lock:
            eng = min(self.engines, key=self._load)
        return eng.submit(prompt, max_new_tokens=max_new,
                          priority=priority)

    def n(self) -> int:
        with self._lock:
            return len(self.engines)

    def n_billed(self) -> int:
        """Serving + building replicas — a pre-warming replica bills
        chips from the moment the build starts, so the equal-chip
        comparison cannot hide cold starts in free capacity."""
        with self._lock:
            return len(self.engines) + self.pending

    def signals(self, target_concurrency: float) -> dict:
        with self._lock:
            engines = list(self.engines)
            pending = self.pending
        live = sum(self._load(e) for e in engines)
        frees = []
        for e in engines:
            s = e.stats()
            total = s.get("kv_blocks_total", 0)
            if total:
                frees.append(s.get("kv_blocks_free", 0) / total)
        return {
            "replicas": len(engines) + pending, "min_replicas": 1,
            "max_replicas": self.max_replicas,
            "util": live / max(len(engines), 1)
            / max(target_concurrency, 1e-9),
            "free_block_ratio": min(frees) if frees else 1.0,
            "live": float(live),
        }

    def audit_and_stop(self) -> dict:
        """Fold every engine that EVER served (live + retired) into the
        leak/recompile audit, then stop the fleet."""
        with self._lock:
            engines = list(self.engines)
            self.engines = []
        stats = self._retired_stats + [e.stats() for e in engines]
        for e in engines:
            e.stop()
        return {
            "kv_blocks_leaked_total": sum(
                int(s.get("kv_blocks_leaked_total", 0)) for s in stats),
            "jit_recompiles_total": sum(
                int(s.get("jit_recompiles_total", 0)) for s in stats),
            "engines_audited": len(stats),
        }


# -- replay ---------------------------------------------------------------

def _replay(arrivals, fleet, auto, *, duration_s: float,
            max_new: int = 16) -> tuple:
    """Pace the arrival trace in wall time, ticking the autoscaler (if
    any) between submissions; returns (latencies_by_class,
    replica_trace, end_s, drops)."""
    t0 = time.perf_counter()
    trace = [(0.0, fleet.n_billed())]
    pending = []  # (cls, submit_wall, req)
    lats = {cls: [] for cls in CLASSES}
    drops = 0
    next_tick = 0.0
    i = 0

    def reap_done():
        nonlocal drops
        now_w = time.perf_counter()
        for item in pending[:]:
            cls, t_sub, req = item
            if req.done.is_set():
                pending.remove(item)
                if req.error is not None or len(req.tokens) != max_new:
                    drops += 1
                    lats[cls].append(float("inf"))
                else:
                    lats[cls].append(now_w - t_sub)

    while i < len(arrivals):
        now = time.perf_counter() - t0
        while i < len(arrivals) and arrivals[i][0] <= now:
            _, cls = arrivals[i]
            i += 1
            spec = CLASSES[cls]
            prompt = [spec["priority"] + 2] * 8
            pending.append((cls, time.perf_counter(),
                            fleet.submit(prompt, spec["priority"],
                                         max_new)))
        if auto is not None and now >= next_tick:
            dec = auto.tick()
            if dec.action != "none":
                print(f"# t={now:6.2f}s {dec.action}: {dec.reason}",
                      file=sys.stderr)
            next_tick = now + auto.policy.loop_s
        reap_done()
        n = fleet.n_billed()
        if n != trace[-1][1]:  # async pre-warms join between ticks
            trace.append((time.perf_counter() - t0, n))
        time.sleep(0.004)
    deadline = time.perf_counter() + 120.0
    while pending and time.perf_counter() < deadline:
        reap_done()
        time.sleep(0.01)
    for cls, _t, _req in pending:  # timed out = dropped
        drops += 1
        lats[cls].append(float("inf"))
    end_s = max(time.perf_counter() - t0, duration_s)
    return lats, trace, end_s, drops


def bench_diurnal(seed: int, duration_s: float, compress: float) -> list:
    """The headline: autoscaled vs static-at-equal-chip-seconds on the
    same seeded diurnal trace; emits one row per class plus the
    invariant rows."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as llamalib
    from kubeflow_tpu.serving.autoscale import ClusterAutoscaler

    day_s = 86400.0 / compress
    arrivals = diurnal_arrivals(seed, duration_s, day_s)
    cfg = llamalib.tiny()
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    # the shared diurnal policy (sim/traces.py): the twin's parity test
    # pins that both sides construct the identical bands — see the
    # cold-start budget methodology in README "Cluster autoscaling"
    policy = diurnal_policy()

    # both fleets share one AOT artifact root (ISSUE 17): the very
    # first replica seeds it, every later pre-warm loads from disk
    import shutil
    import tempfile
    aot_root = tempfile.mkdtemp(prefix="kft-autoscale-aot-")

    # -- autoscaled run --
    fleet = MiniFleet(cfg, params, aot_root=aot_root)
    fleet.add_replica()
    auto = ClusterAutoscaler(
        policy, sensors=lambda: fleet.signals(policy.target_concurrency),
        actuators={
            "replica_up": lambda dec: fleet.add_replica_async(
                auto.note_cold_start),
            "replica_down": lambda dec: fleet.remove_replica(),
        })
    lats_a, trace_a, end_a, drops_a = _replay(
        arrivals, fleet, auto, duration_s=duration_s)
    audit_a = fleet.audit_and_stop()
    chips_a = chip_seconds(trace_a, end_a)
    att_a = slo_attainment(lats_a)

    # -- static baseline at EQUAL chip-seconds --
    r_static = min(static_replicas_for(chips_a, end_a),
                   fleet.max_replicas)
    fleet_s = MiniFleet(cfg, params, aot_root=aot_root)
    for _ in range(r_static):
        fleet_s.add_replica()
    lats_s, trace_s, end_s, drops_s = _replay(
        arrivals, fleet_s, None, duration_s=duration_s)
    audit_s = fleet_s.audit_and_stop()
    att_s = slo_attainment(lats_s)
    shutil.rmtree(aot_root, ignore_errors=True)

    # hard invariants — a violation is a bench failure, not a row
    assert drops_a == 0, f"autoscaled run dropped {drops_a} requests"
    assert drops_s == 0, f"static run dropped {drops_s} requests"
    for audit, name in ((audit_a, "autoscaled"), (audit_s, "static")):
        assert audit["kv_blocks_leaked_total"] == 0, (name, audit)
        assert audit["jit_recompiles_total"] == 0, (name, audit)
    # the pre-warm path must serve its ladder from the artifact cache:
    # every static-fleet add runs against the seeded root (adds are
    # serial, so per-replica deltas are exact), and any autoscaled
    # scale-up after the seeding replica must have loaded artifacts too
    assert fleet_s.aot_prewarm_hits and all(
        h > 0 for h in fleet_s.aot_prewarm_hits), (
        f"static pre-warm never hit the AOT cache: "
        f"{fleet_s.aot_prewarm_hits}")
    assert len(fleet.aot_prewarm_hits) <= 1 or sum(
        fleet.aot_prewarm_hits[1:]) > 0, (
        f"scale-up pre-warm never hit the AOT cache: "
        f"{fleet.aot_prewarm_hits}")

    rows = []
    for cls in CLASSES:
        rows.append({
            "metric": f"autoscale_diurnal_{cls}_slo_attainment",
            "value": round(att_a[cls], 4),
            "static_value": round(att_s[cls], 4),
            "slo_s": CLASSES[cls]["slo_s"],
            "requests": len(lats_a[cls]),
        })
    rows.append({
        "metric": "autoscale_diurnal_chip_seconds",
        "value": round(chips_a, 2),
        "static_replicas": r_static,
        "static_chip_seconds": round(chip_seconds(trace_s, end_s), 2),
        "duration_s": round(end_a, 2), "compress": compress,
        "arrivals": len(arrivals),
    })
    rows.append({
        "metric": "autoscale_scale_down_lossless",
        "value": 1.0,
        "scale_downs": fleet.scale_downs,
        "sequences_migrated": fleet.migrated,
    })
    rows.append({
        "metric": "autoscale_cold_start_s",
        "value": round(auto.cold_start_s or (sum(fleet.cold_starts)
                                             / len(fleet.cold_starts)), 3),
        "samples": len(fleet.cold_starts),
        "max_s": round(max(fleet.cold_starts), 3),
    })
    rows.append({
        "metric": "autoscale_prewarm_aot_hits_total",
        "value": float(sum(fleet.aot_prewarm_hits)
                       + sum(fleet_s.aot_prewarm_hits)),
        "replicas_warmed": (len(fleet.aot_prewarm_hits)
                            + len(fleet_s.aot_prewarm_hits)),
        "cold_start_warm_s": round(auto.cold_start_warm_s, 3),
    })
    rows.append({
        "metric": "autoscale_kv_blocks_leaked_total", "value": 0.0,
        "engines_audited": (audit_a["engines_audited"]
                            + audit_s["engines_audited"]),
    })
    rows.append({
        "metric": "autoscale_jit_recompiles_total", "value": 0.0,
        "engines_audited": (audit_a["engines_audited"]
                            + audit_s["engines_audited"]),
    })
    return rows


def _backend_or_skip(metric: str) -> None:
    """PR 2 convention (bench.py::_devices_or_skip): probe the default
    backend in a BOUNDED subprocess so a registered-but-dead axon/TPU
    plugin costs a timeout, not a hang; fall back to CPU; and if even
    CPU is unusable, print ONE parseable skipped row in the driver's
    schema and exit 0 — a bench that cannot run records that fact, not
    a stack trace."""
    import os
    import subprocess

    import jax

    err = "default backend probe failed"
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, timeout=PROBE_TIMEOUT_S, text=True)
            ok = probe.returncode == 0
            err = (probe.stderr or "").strip().splitlines()[-1:] or [err]
            err = err[0]
        except subprocess.TimeoutExpired:
            ok = False
            err = f"backend init exceeded {PROBE_TIMEOUT_S:.0f}s"
        if not ok:
            jax.config.update("jax_platforms", "cpu")
    try:
        jax.devices()
    except Exception:  # noqa: BLE001 — no backend at all
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": f"skipped: no usable jax backend ({err})"[:200],
            "skipped": True,
        }), flush=True)
        raise SystemExit(0)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=20.0,
                    help="compressed replay window per run, seconds")
    ap.add_argument("--compress", type=float, default=4320.0,
                    help="time compression: 86400/compress = the "
                         "replayed day length (1000 reproduces the "
                         "paper-scale trace; the default fits one "
                         "diurnal cycle in --duration)")
    args = ap.parse_args()
    _backend_or_skip("autoscale_diurnal_gold_slo_attainment")
    for row in bench_diurnal(args.seed, args.duration, args.compress):
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
