"""AOT-compile the Llama-7B train step for a v5e-16 topology — no hardware.

The BASELINE headline metric is "JAXJob Llama-7B tokens/sec/chip on v5e-16"
(SURVEY.md §6), but multi-chip hardware cannot be attached to this machine.
JAX's topology AOT path closes the gap: ``jax.experimental.topologies`` hands
back 16 abstract v5e devices, the sharded train step lowers and compiles
against them exactly as it would on the real slice, and the compiled
executable reports XLA's per-chip memory breakdown and FLOP count.  That is
the strongest multi-chip evidence available without chips:

- the full FSDP/TP-sharded 7B step *compiles* for the real target (every
  collective, layout, and remat decision is the real one);
- XLA's memory analysis proves the step *fits* v5e HBM (16 GiB/chip);
- the FLOP count + the MFU measured on the one real chip at 271M/1.1B scale
  give a defensible tokens/sec/chip projection.

Usage:  python scripts/aot_7b_v5e16.py [--fast]
Writes: artifacts/aot_7b_v5e16.json (one entry per mesh candidate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")  # host side traces on CPU

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.experimental import topologies  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.models import llama  # noqa: E402
from kubeflow_tpu.parallel import sharding as shardlib  # noqa: E402
from kubeflow_tpu.train import trainer as trainlib  # noqa: E402

V5E_HBM_BYTES = 16 * 1024**3          # 16 GiB per v5e chip
V5E_PEAK_FLOPS = 197e12               # bf16


def compile_candidate(devs, mesh_axes, *, global_batch, seq_len, accum_steps,
                      model_cfg, num_slices=1, num_microbatches=None,
                      pipeline_schedule="gpipe"):
    cfg = trainlib.TrainConfig(
        model=model_cfg,
        mesh_axes=mesh_axes,
        global_batch=global_batch,
        seq_len=seq_len,
        accum_steps=accum_steps,
        num_slices=num_slices,
        num_microbatches=num_microbatches,
        pipeline_schedule=pipeline_schedule,
    )
    t = trainlib.Trainer(cfg, devices=devs)
    state = t.abstract_state()
    batch = {"tokens": jax.ShapeDtypeStruct(
        (global_batch, seq_len + 1), np.int32, sharding=t.batch_sharding)}
    t0 = time.perf_counter()
    with shardlib.shard_context(t.mesh):
        compiled = t.compiled_step().lower(state, batch).compile()
    compile_s = time.perf_counter() - t0

    mem = compiled.memory_analysis()
    cost = compiled.cost_analysis()
    n_chips = len(devs)
    # donated state aliases its output, so the live set per chip is
    # arguments (state + batch) + temps; outputs reuse the state's bytes
    peak_bytes = mem.argument_size_in_bytes + mem.temp_size_in_bytes
    # analytic FLOPs, not XLA's cost_analysis: XLA counts each while-loop
    # body ONCE, so the scanned layer stack (and the grad-accum scan)
    # under-report by ~num_layers x.  6N + attention-quadratic per token,
    # x3 for fwd+bwd is already folded into flops_per_token's factor.
    flops_per_step_chip = (
        llama.flops_per_token(model_cfg, seq_len)
        * global_batch * seq_len / n_chips)
    tokens_per_step = global_batch * seq_len
    # projection: chip-seconds per step at an MFU, tokens/s/chip = tokens /
    # (n_chips * step_time); collective overlap and host gaps land inside
    # the assumed MFU, which is why we quote the measured single-chip MFU
    proj = {}
    for mfu in (0.4, 0.5, 0.56):
        step_s = flops_per_step_chip / (V5E_PEAK_FLOPS * mfu)
        proj[f"tokens_per_sec_per_chip@mfu{mfu}"] = round(
            tokens_per_step / (n_chips * step_s), 1)
    return {
        "mesh_axes": mesh_axes,
        "num_slices": num_slices,
        "num_microbatches": num_microbatches,
        "pipeline_schedule": pipeline_schedule if "pipeline" in mesh_axes else None,
        "global_batch": global_batch,
        "seq_len": seq_len,
        "accum_steps": accum_steps,
        "compile_seconds": round(compile_s, 1),
        "argument_bytes_per_chip": mem.argument_size_in_bytes,
        "temp_bytes_per_chip": mem.temp_size_in_bytes,
        "output_bytes_per_chip": mem.output_size_in_bytes,
        "peak_live_bytes_per_chip": peak_bytes,
        "hbm_bytes": V5E_HBM_BYTES,
        "fits_hbm": bool(peak_bytes <= V5E_HBM_BYTES),
        "hbm_utilization": round(peak_bytes / V5E_HBM_BYTES, 3),
        "flops_per_step_per_chip": flops_per_step_chip,
        "xla_reported_flops": float(cost.get("flops", 0.0)),
        "projection": proj,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="compile only the primary candidate")
    ap.add_argument("--multislice-only", action="store_true",
                    help="compile only the v5e-32 two-slice candidates")
    ap.add_argument("--topology", default="v5e:4x4")
    args = ap.parse_args()

    topo = topologies.get_topology_desc(args.topology, platform="tpu")
    devs = list(topo.devices)
    # 32L / 4096h / 32 heads / 11008 ffn.  Full-recompute remat (only the
    # per-layer carry survives the forward scan) + the Pallas flash kernel
    # (no materialized 4096^2 score matrix) are what fit 7B training into
    # v5e's 16 GiB; the "dots" policy alone holds ~2.7 GB of saved ffn
    # activations per chip and OOMs by ~1.5 GB.
    model_cfg = llama.llama2_7b(remat_policy="nothing", attention_impl="flash")
    n_params = llama.num_params(model_cfg)
    print(f"topology {args.topology}: {len(devs)} x {devs[0].device_kind}; "
          f"model params {n_params/1e9:.2f}B", file=sys.stderr)

    candidates = [
        # primary: FSDP over all 16 chips, grad-accum for effective batch
        dict(mesh_axes={"fsdp": 16}, global_batch=16, seq_len=4096,
             accum_steps=1),
        dict(mesh_axes={"fsdp": 8, "model": 2}, global_batch=16, seq_len=4096,
             accum_steps=2),
        dict(mesh_axes={"fsdp": 4, "model": 4}, global_batch=16, seq_len=4096,
             accum_steps=4),
    ]
    if args.fast:
        candidates = candidates[:1]
    if args.multislice_only:
        candidates = []

    results = []
    for cand in candidates:
        print(f"compiling {cand} ...", file=sys.stderr)
        try:
            r = compile_candidate(devs, model_cfg=model_cfg, **cand)
        except Exception as e:  # keep the sweep going; record the failure
            r = {**cand, "error": f"{type(e).__name__}: {e}"}
        results.append(r)
        print(json.dumps(r), file=sys.stderr)

    if not args.fast or args.multislice_only:
        # scale-out leg: TWO v5e-16 slices (32 chips) with the pipeline
        # axis over DCN — the SURVEY §7 "PP over DCN" configuration, AOT-
        # compiled with real stage shardings.  Activations cross the slice
        # boundary once per microbatch per stage; fsdp stays intra-slice.
        topo32 = topologies.get_topology_desc("v5e:4x8", platform="tpu")
        devs32 = list(topo32.devices)
        for cand in (
            dict(mesh_axes={"fsdp": 32}, global_batch=32, seq_len=4096,
                 accum_steps=1),
            # GPipe at 7B/seq-4096 OOMs (all-M microbatch activation
            # buffers, measured 19.3 GB); 1F1B's ~P-bounded stash is the
            # schedule that fits — exactly what it exists for
            dict(mesh_axes={"pipeline": 2, "fsdp": 16}, global_batch=32,
                 seq_len=4096, accum_steps=1, num_slices=2,
                 num_microbatches=8, pipeline_schedule="1f1b"),
        ):
            print(f"compiling v5e-32 {cand} ...", file=sys.stderr)
            try:
                r = compile_candidate(devs32, model_cfg=model_cfg, **cand)
            except Exception as e:
                r = {**cand, "error": f"{type(e).__name__}: {e}"}
            r["topology"] = "v5e:4x8 (2 slices over DCN)"
            results.append(r)
            print(json.dumps(r), file=sys.stderr)

    out = {
        "topology": ("v5e:4x8 (2 slices)" if args.multislice_only
                     else args.topology),
        "n_chips": 32 if args.multislice_only else len(devs),
        "model": "llama2_7b",
        "n_params": n_params,
        "results": results,
    }
    name = ("aot_7b_v5e32.json" if args.multislice_only
            else "aot_7b_v5e16.json")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "metric": "aot_7b_v5e16_fits_hbm",
        "value": sum(1 for r in results if r.get("fits_hbm")),
        "unit": f"of {len(results)} shardings",
    }))


if __name__ == "__main__":
    main()
