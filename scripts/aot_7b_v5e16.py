"""AOT-compile the Llama-7B train step for a v5e-16 topology — no hardware.

The BASELINE headline metric is "JAXJob Llama-7B tokens/sec/chip on v5e-16"
(SURVEY.md §6), but multi-chip hardware cannot be attached to this machine.
JAX's topology AOT path closes the gap: ``jax.experimental.topologies`` hands
back 16 abstract v5e devices, the sharded train step lowers and compiles
against them exactly as it would on the real slice, and the compiled
executable reports XLA's per-chip memory breakdown and FLOP count.  That is
the strongest multi-chip evidence available without chips:

- the full FSDP/TP-sharded 7B step *compiles* for the real target (every
  collective, layout, and remat decision is the real one);
- XLA's memory analysis proves the step *fits* v5e HBM (16 GiB/chip);
- the FLOP count + the MFU measured on the one real chip at 271M/1.1B scale
  give a defensible tokens/sec/chip projection.

Usage:  python scripts/aot_7b_v5e16.py [--fast]
Writes: artifacts/aot_7b_v5e16.json (one entry per mesh candidate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")  # host side traces on CPU

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.experimental import topologies  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.models import llama  # noqa: E402
from kubeflow_tpu.parallel import sharding as shardlib  # noqa: E402
from kubeflow_tpu.train import trainer as trainlib  # noqa: E402

V5E_HBM_BYTES = 16 * 1024**3          # 16 GiB per v5e chip
V5E_PEAK_FLOPS = 197e12               # bf16
#: v5e ICI: 2D torus, 4.5e10 B/s/link each direction; a ring collective
#: over one mesh axis streams both directions of one link pair
#: -> 9e10 B/s usable per chip per axis (scaling-book numbers).
ICI_AXIS_BW = 9.0e10
#: DCN egress per chip (per-host NIC / 4 chips), the inter-slice pipe.
DCN_BW_PER_CHIP = 6.25e9
#: measured single-chip MFU at 271M/1.19B scale (PERF.md) — the compute
#: term's efficiency; collective/bubble costs are modeled EXPLICITLY per
#: mesh below instead of being buried in a per-mesh "assumed MFU".
MEASURED_MFU = 0.50


def projection_for(mesh_axes, *, model_cfg, global_batch, seq_len,
                   accum_steps, num_microbatches, pipeline_schedule,
                   num_slices, n_chips):
    """Mesh-aware tokens/sec/chip projection (r3 verdict weak #1 fix).

    compute_s   = analytic FLOPs / (peak * measured single-chip MFU)
    fsdp_s      = {all-gather params fwd + bwd re-gather (remat) +
                   reduce-scatter grads} ~ 3 * param_bytes * (F-1)/F
                   over the axis's ICI bandwidth (DCN if the fsdp axis
                   crosses slices — mesh.py forbids that, so ICI)
    tp_s        = 4 per-layer all-reduces of the [B,S,H] activation
                  (attn-out + mlp-out, fwd and bwd): 2*bytes*(T-1)/T per
                  all-reduce over ICI
    pipeline    = step stretched by the schedule's useful fraction
                  (GPipe m/(m+p-1); 1F1B m/(m+2(p-1))) + per-boundary
                  microbatch activation ppermute over DCN
    Collectives are charged FULLY EXPOSED (no overlap credit) — a lower
    bound on throughput; the compute term alone reproduces the old
    constant-MFU number, so the gap between meshes is the model's signal.
    """
    import math

    h = model_cfg.hidden_size
    layers = model_cfg.num_layers
    param_bytes = llama.num_params(model_cfg) * 4  # f32 master params
    act_bytes = 2  # bf16 activations
    tokens_per_step = global_batch * seq_len
    flops_chip = (llama.flops_per_token(model_cfg, seq_len)
                  * tokens_per_step / n_chips)
    compute_s = flops_chip / (V5E_PEAK_FLOPS * MEASURED_MFU)

    F = mesh_axes.get("fsdp", 1)
    T = mesh_axes.get("model", 1)
    Pp = mesh_axes.get("pipeline", 1)
    # microbatch count per pipeline round; accum multiplies rounds
    m = num_microbatches or Pp

    fsdp_s = 0.0
    if F > 1:
        # params live sharded; each accum microstep re-gathers for fwd and
        # (under full-recompute remat) again for bwd, grads reduce-scatter
        shard_frac = (F - 1) / F
        fsdp_s = 3 * param_bytes / max(Pp, 1) * shard_frac / ICI_AXIS_BW
        fsdp_s *= max(accum_steps, 1)

    tp_s = 0.0
    if T > 1:
        per_ar = 2 * (tokens_per_step // max(
            F * mesh_axes.get("data", 1) * Pp, 1)) * h * act_bytes
        # 4 all-reduces per layer (attn+mlp, fwd+bwd), ring cost 2x(T-1)/T
        tp_s = (4 * (layers // max(Pp, 1)) * 2 * per_ar * (T - 1) / T
                / ICI_AXIS_BW)

    bubble_stretch = 1.0
    pp_comm_s = 0.0
    if Pp > 1:
        if pipeline_schedule == "1f1b":
            useful = m / (m + 2 * (Pp - 1))
        else:
            useful = m / (m + Pp - 1)
        bubble_stretch = 1.0 / useful
        # per microbatch per stage boundary: [B_mb, S, H] bf16 activation
        # + its cotangent back; boundaries cross DCN when slices > 1
        mb_act = (global_batch // m) * seq_len * h * act_bytes
        bw = DCN_BW_PER_CHIP if num_slices > 1 else ICI_AXIS_BW
        pp_comm_s = 2 * m * mb_act / bw / max(n_chips // Pp, 1)

    step_s = compute_s * bubble_stretch + fsdp_s + tp_s + pp_comm_s
    return {
        "compute_s": round(compute_s, 4),
        "fsdp_collective_s": round(fsdp_s, 4),
        "tp_collective_s": round(tp_s, 4),
        "pipeline_bubble_stretch": round(bubble_stretch, 3),
        "pipeline_dcn_s": round(pp_comm_s, 4),
        "step_s": round(step_s, 4),
        "tokens_per_sec_per_chip": round(
            tokens_per_step / (n_chips * step_s), 1),
        "assumptions": "measured-MFU compute; collectives fully exposed "
                       "(no overlap credit); ICI 9e10 B/s/axis, DCN "
                       "6.25e9 B/s/chip",
    }


def compile_candidate(devs, mesh_axes, *, global_batch, seq_len, accum_steps,
                      model_cfg, num_slices=1, num_microbatches=None,
                      pipeline_schedule="gpipe"):
    cfg = trainlib.TrainConfig(
        model=model_cfg,
        mesh_axes=mesh_axes,
        global_batch=global_batch,
        seq_len=seq_len,
        accum_steps=accum_steps,
        num_slices=num_slices,
        num_microbatches=num_microbatches,
        pipeline_schedule=pipeline_schedule,
    )
    t = trainlib.Trainer(cfg, devices=devs)
    state = t.abstract_state()
    batch = {"tokens": jax.ShapeDtypeStruct(
        (global_batch, seq_len + 1), np.int32, sharding=t.batch_sharding)}
    t0 = time.perf_counter()
    with shardlib.shard_context(t.mesh):
        compiled = t.compiled_step().lower(state, batch).compile()
    compile_s = time.perf_counter() - t0

    mem = compiled.memory_analysis()
    cost = compiled.cost_analysis()
    n_chips = len(devs)
    # donated state aliases its output, so the live set per chip is
    # arguments (state + batch) + temps; outputs reuse the state's bytes
    peak_bytes = mem.argument_size_in_bytes + mem.temp_size_in_bytes
    # analytic FLOPs, not XLA's cost_analysis: XLA counts each while-loop
    # body ONCE, so the scanned layer stack (and the grad-accum scan)
    # under-report by ~num_layers x.  6N + attention-quadratic per token,
    # x3 for fwd+bwd is already folded into flops_per_token's factor.
    flops_per_step_chip = (
        llama.flops_per_token(model_cfg, seq_len)
        * global_batch * seq_len / n_chips)
    tokens_per_step = global_batch * seq_len
    # mesh-aware projection: explicit per-mesh collective + bubble model
    # (BASELINE.md "projection formula"); per-mesh numbers DIFFER.
    proj = projection_for(
        mesh_axes, model_cfg=model_cfg, global_batch=global_batch,
        seq_len=seq_len, accum_steps=accum_steps,
        num_microbatches=num_microbatches,
        pipeline_schedule=pipeline_schedule, num_slices=num_slices,
        n_chips=n_chips)
    return {
        "mesh_axes": mesh_axes,
        "num_slices": num_slices,
        "num_microbatches": num_microbatches,
        "pipeline_schedule": pipeline_schedule if "pipeline" in mesh_axes else None,
        "global_batch": global_batch,
        "seq_len": seq_len,
        "accum_steps": accum_steps,
        "compile_seconds": round(compile_s, 1),
        "argument_bytes_per_chip": mem.argument_size_in_bytes,
        "temp_bytes_per_chip": mem.temp_size_in_bytes,
        "output_bytes_per_chip": mem.output_size_in_bytes,
        "peak_live_bytes_per_chip": peak_bytes,
        "hbm_bytes": V5E_HBM_BYTES,
        "fits_hbm": bool(peak_bytes <= V5E_HBM_BYTES),
        "hbm_utilization": round(peak_bytes / V5E_HBM_BYTES, 3),
        "flops_per_step_per_chip": flops_per_step_chip,
        "xla_reported_flops": float(cost.get("flops", 0.0)),
        "projection": proj,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="compile only the primary candidate")
    ap.add_argument("--multislice-only", action="store_true",
                    help="compile only the v5e-32 two-slice candidates")
    ap.add_argument("--topology", default="v5e:4x4")
    args = ap.parse_args()

    topo = topologies.get_topology_desc(args.topology, platform="tpu")
    devs = list(topo.devices)
    # 32L / 4096h / 32 heads / 11008 ffn.  Full-recompute remat (only the
    # per-layer carry survives the forward scan) + the Pallas flash kernel
    # (no materialized 4096^2 score matrix) are what fit 7B training into
    # v5e's 16 GiB; the "dots" policy alone holds ~2.7 GB of saved ffn
    # activations per chip and OOMs by ~1.5 GB.
    model_cfg = llama.llama2_7b(remat_policy="nothing", attention_impl="flash")
    n_params = llama.num_params(model_cfg)
    print(f"topology {args.topology}: {len(devs)} x {devs[0].device_kind}; "
          f"model params {n_params/1e9:.2f}B", file=sys.stderr)

    candidates = [
        # primary: FSDP over all 16 chips, grad-accum for effective batch
        dict(mesh_axes={"fsdp": 16}, global_batch=16, seq_len=4096,
             accum_steps=1),
        dict(mesh_axes={"fsdp": 8, "model": 2}, global_batch=16, seq_len=4096,
             accum_steps=2),
        dict(mesh_axes={"fsdp": 4, "model": 4}, global_batch=16, seq_len=4096,
             accum_steps=4),
    ]
    if args.fast:
        candidates = candidates[:1]
    if args.multislice_only:
        candidates = []

    results = []
    for cand in candidates:
        print(f"compiling {cand} ...", file=sys.stderr)
        try:
            r = compile_candidate(devs, model_cfg=model_cfg, **cand)
        except Exception as e:  # noqa: BLE001 — keep the sweep going;
            # the failure is recorded in the result row, not swallowed
            r = {**cand, "error": f"{type(e).__name__}: {e}"}
        results.append(r)
        print(json.dumps(r), file=sys.stderr)

    if not args.fast or args.multislice_only:
        # scale-out leg: TWO v5e-16 slices (32 chips) with the pipeline
        # axis over DCN — the SURVEY §7 "PP over DCN" configuration, AOT-
        # compiled with real stage shardings.  Activations cross the slice
        # boundary once per microbatch per stage; fsdp stays intra-slice.
        topo32 = topologies.get_topology_desc("v5e:4x8", platform="tpu")
        devs32 = list(topo32.devices)
        for cand in (
            dict(mesh_axes={"fsdp": 32}, global_batch=32, seq_len=4096,
                 accum_steps=1),
            # GPipe at 7B/seq-4096 OOMs (all-M microbatch activation
            # buffers, measured 19.3 GB); 1F1B's ~P-bounded stash is the
            # schedule that fits — exactly what it exists for
            dict(mesh_axes={"pipeline": 2, "fsdp": 16}, global_batch=32,
                 seq_len=4096, accum_steps=1, num_slices=2,
                 num_microbatches=8, pipeline_schedule="1f1b"),
        ):
            print(f"compiling v5e-32 {cand} ...", file=sys.stderr)
            try:
                r = compile_candidate(devs32, model_cfg=model_cfg, **cand)
            except Exception as e:  # noqa: BLE001 — keep the sweep
                # going; the failure is recorded in the result row
                r = {**cand, "error": f"{type(e).__name__}: {e}"}
            r["topology"] = "v5e:4x8 (2 slices over DCN)"
            results.append(r)
            print(json.dumps(r), file=sys.stderr)

    out = {
        "topology": ("v5e:4x8 (2 slices)" if args.multislice_only
                     else args.topology),
        "n_chips": 32 if args.multislice_only else len(devs),
        "model": "llama2_7b",
        "n_params": n_params,
        "results": results,
    }
    name = ("aot_7b_v5e32.json" if args.multislice_only
            else "aot_7b_v5e16.json")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "metric": "aot_7b_v5e16_fits_hbm",
        "value": sum(1 for r in results if r.get("fits_hbm")),
        "unit": f"of {len(results)} shardings",
    }))


if __name__ == "__main__":
    main()
