"""Gang recovery latency p50 — restart -> RUNNING, phase-decomposed.

The recovery counterpart of scripts/gang_startup_bench.py: a seeded
:class:`~kubeflow_tpu.chaos.FaultPlan` kills a random gang member
mid-run; the JaxJob controller detects the failure, tears the gang down,
holds the jittered restart backoff, re-schedules, and the gang returns
to RUNNING.  Each trial decomposes that into:

- ``detect_s``     pod crash -> Restarting decision (event timestamp)
- ``backoff_s``    the jittered hold the controller actually applied
- ``respawn_s``    hold expiry -> first new pod running
- ``reform_s``     first new pod running -> every worker running

``restart_to_running_s`` (the sum, as measured end-to-end by the
controller's ``status.last_recovery_seconds`` + detection) is the
headline; the controller also stamps it on the job, so production jobs
report the same number this bench tracks.

Runs against the in-process cluster + FakeKubelet (no real processes) —
this measures CONTROLLER recovery machinery, deterministically;
gang_startup_bench.py's restart leg measures the full process-runtime
path on top.

The second row (ISSUE 5) is **cold restart**: kill -9 the control plane
of a durable cluster holding a ≥200-object store with a gang mid-run,
then time the restarted plane's

- ``replay_s``      Store.open: snapshot + WAL replay back into memory
- ``reconverge_s``  controllers start -> every worker Running again
  (kubelet resync, orphan adoption, expectations rebuild)

``cold_restart_recovery_s`` (the sum) is that row's headline.

The third row (ISSUE 8) is **replica drain by live KV migration**: a
serving replica with N live conversations drains onto a peer via
``migrate_live_sequences`` (export -> kv import -> cutover per
sequence), and the row times drain-start -> every conversation decoding
again on the destination (first post-migration token observed).
``drain_resume_s`` p50 is the headline — the retire path that used to
race a 5 s deadline (or cut long conversations) now completes lossless
in migration time.

The fourth row (ISSUE 10) is **elastic gang resize**: a live paged
engine at TP=2 shrinks to the surviving degree with N live
conversations aboard (``GangResizer``: quiesce -> export -> weight
repartition + new-degree rebuild/warmup -> held imports -> cutover),
and the row times resize-start -> every conversation decoding again on
the new-degree engine, phase-decomposed as ``drain_s`` / ``reshard_s``
/ ``resume_s``.  ``gang_resize_s`` p50 is the headline — the failure
mode that used to park an ISvc in Degraded forever is now a bounded
recovery; the live-conversation count is swept to show how the drain
phase scales.

The fifth row (ISSUE 17) is **cold start vs warm artifact cache**: one
replica boot (engine build -> warmup -> first token) timed twice — with
no AOT program-artifact cache (every rung compiles) and against a warm
``ProgramArtifactCache`` root (every rung loads a verified artifact).
``cold_start_warm_cache_p50_seconds`` is the headline, with the
cold-cache p50 and the speedup attached; the companion
``gang_resize_warm_cache_p50_seconds`` row re-runs the resize trial
with a warm cache and splits the compile wall out of the disruption
window (``prebuild_s`` overlaps live serving; disruption = drain +
reshard + resume).

Usage: python scripts/recovery_bench.py [trials] [workers] [seed]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")

# the resize row needs >= 2 virtual devices for its TP=2 source engine
# (set before any jax import; every other row is meshless or jax-free)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()


from kubeflow_tpu.utils.stats import percentiles as _percentiles  # noqa: E402


class _CrashWatcher:
    """Polls pod statuses to timestamp the crash: the failed pod is
    deleted by the gang restart, so its finish_time must be caught live."""

    def __init__(self, store, job_name: str):
        import threading

        self.store = store
        self.job = job_name
        self.crash_t = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        from kubeflow_tpu.controlplane.objects import KIND_POD, PodPhase

        while not self._stop.is_set() and self.crash_t is None:
            for p in self.store.list(KIND_POD):
                if (p.metadata.name.startswith(self.job + "-")
                        and p.status.phase == PodPhase.FAILED):
                    self.crash_t = p.status.finish_time or time.time()
                    break
            self._stop.wait(0.005)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)


def run_trial(i: int, workers: int, seed: int) -> dict:
    from kubeflow_tpu.api import (
        Container,
        JaxJob,
        ObjectMeta,
        ReplicaSpec,
        Resources,
    )
    from kubeflow_tpu.api.common import RestartPolicy
    from kubeflow_tpu.api.jaxjob import KIND_JAXJOB
    from kubeflow_tpu.chaos import FaultPlan
    from kubeflow_tpu.controlplane import (
        Cluster,
        FakeKubelet,
        KIND_POD,
        PodScript,
        events_for,
    )
    from kubeflow_tpu.controlplane.objects import PodPhase

    name = f"recover-{i}"
    plan = FaultPlan(seed=seed + i).crash_random_member(world=workers, at=0.2)
    c = Cluster()
    c.add_tpu_slice("s0", num_hosts=workers, chips_per_host=4)
    kubelet = FakeKubelet(
        c.store,
        plan.script_fn(default=lambda pod: PodScript(run_seconds=30.0)),
        chaos=plan)
    with c:
        kubelet.start()
        watcher = _CrashWatcher(c.store, name)
        try:
            c.store.create(JaxJob(
                metadata=ObjectMeta(name=name),
                spec={
                    "replica_specs": {
                        "worker": ReplicaSpec(
                            replicas=workers,
                            restart_policy=RestartPolicy.ON_FAILURE,
                            template=Container(
                                resources=Resources(cpu=1, memory_gb=1, tpu=4)),
                        )
                    },
                    "run_policy": {"backoff_limit": 3,
                                   "restart_backoff_seconds": 0.1},
                },
            ))
            deadline = time.time() + 60
            job = None
            while time.time() < deadline:
                job = c.store.get(KIND_JAXJOB, name)
                if job.status.last_recovery_seconds is not None:
                    break
                time.sleep(0.02)
            assert job is not None and job.status.last_recovery_seconds is not None, (
                f"{name} never recovered: {job.status if job else None}")

            watcher.stop()
            crash_t = watcher.crash_t
            restart_ev = next(
                e for e in events_for(c.store, KIND_JAXJOB, name)
                if e.reason == "Restarting")
            backoff = json.loads(restart_ev.message)["backoff_seconds"]
            restart_t = job.status.last_restart_time
            first_new_running = min(
                (p.status.start_time for p in c.store.list(KIND_POD)
                 if p.metadata.name.startswith(name + "-")
                 and p.status.phase == PodPhase.RUNNING
                 and p.status.start_time),
                default=None)
            recovered_t = restart_t + job.status.last_recovery_seconds
            detect = (restart_t - crash_t) if crash_t else None
            respawn = (first_new_running - (restart_t + backoff)
                       if first_new_running else None)
            reform = (recovered_t - first_new_running
                      if first_new_running else None)
            return {
                "restart_to_running_s": job.status.last_recovery_seconds,
                "detect_s": detect,
                "backoff_s": backoff,
                "respawn_s": respawn,
                "reform_s": reform,
            }
        finally:
            watcher.stop()
            kubelet.stop()


def run_restart_trial(i: int, workers: int, seed: int,
                      n_objects: int = 200) -> dict:
    """One cold-restart cycle: durable cluster, ≥n_objects store, gang
    running, kill -9 at a seeded WAL offset past the warm state, restart,
    reconverge."""
    import shutil
    import tempfile

    from kubeflow_tpu.api import (
        Container,
        JaxJob,
        ObjectMeta,
        ReplicaSpec,
        Resources,
    )
    from kubeflow_tpu.api.common import RestartPolicy
    from kubeflow_tpu.chaos import FaultPlan
    from kubeflow_tpu.controlplane import Cluster, FakeKubelet, KIND_POD, PodScript
    from kubeflow_tpu.controlplane.objects import PodPhase, Service

    name = f"cold-{i}"
    data_dir = tempfile.mkdtemp(prefix="kft-recovery-bench-")
    plan = FaultPlan(seed=seed + i).control_plane_crash(
        after_records=10 ** 9, torn_bytes=13)
    cp = plan.wal_crashpoint()
    c = Cluster(data_dir=data_dir, wal_crashpoint=cp)
    c.add_tpu_slice("s0", num_hosts=workers, chips_per_host=4)
    kubelet = FakeKubelet(
        c.store, lambda pod: PodScript(run_seconds=120.0), chaos=plan)
    try:
        c.start()
        kubelet.start()
        # the object-count ballast the replay has to chew through
        for j in range(n_objects):
            c.store.create(Service(metadata=ObjectMeta(name=f"ballast-{j}")))
        c.store.create(JaxJob(
            metadata=ObjectMeta(name=name),
            spec={
                "replica_specs": {
                    "worker": ReplicaSpec(
                        replicas=workers,
                        restart_policy=RestartPolicy.ON_FAILURE,
                        template=Container(
                            resources=Resources(cpu=1, memory_gb=1, tpu=4)),
                    )
                },
                "run_policy": {"backoff_limit": 3,
                               "restart_backoff_seconds": 0.05},
            },
        ))

        def all_running():
            return sum(
                p.status.phase == PodPhase.RUNNING
                for p in c.store.list(KIND_POD)
                if p.metadata.name.startswith(name + "-")) == workers

        deadline = time.time() + 60
        while time.time() < deadline and not all_running():
            time.sleep(0.02)
        assert all_running(), f"{name}: gang never warmed up"
        # kill -9 at the next WAL append (seeded torn tail included)
        cp.after_records = c.store.wal.appended_records
        c.store.create(Service(metadata=ObjectMeta(name="the-last-write")))
        assert cp.fired.wait(10), "crashpoint never fired"
        kubelet.stop()
        c.stop()

        # Cluster construction is dominated by Store.open's replay
        t0 = time.perf_counter()
        c2 = Cluster(data_dir=data_dir)
        replay_s = time.perf_counter() - t0
        recovered = sum(len(c2.store.list(k))
                        for k in ("JaxJob", "Pod", "Node", "Service"))

        t1 = time.perf_counter()
        kubelet.attach_store(c2.store)
        kubelet.start()
        c2.start()
        try:
            def reconverged():
                return sum(
                    p.status.phase == PodPhase.RUNNING
                    for p in c2.store.list(KIND_POD)
                    if p.metadata.name.startswith(name + "-")) == workers

            deadline = time.time() + 60
            while time.time() < deadline and not reconverged():
                time.sleep(0.005)
            assert reconverged(), f"{name}: never reconverged"
            reconverge_s = time.perf_counter() - t1
        finally:
            kubelet.stop()
            c2.stop()
        return {
            "cold_restart_recovery_s": replay_s + reconverge_s,
            "replay_s": replay_s,
            "reconverge_s": reconverge_s,
            "objects_recovered": recovered,
        }
    finally:
        kubelet.stop()
        shutil.rmtree(data_dir, ignore_errors=True)


def run_drain_trial(i: int, conversations: int = 4) -> dict:
    """One lossless replica drain: N live conversations mid-decode
    migrate to a fresh peer; measured = drain start -> every migrated
    conversation has produced a token ON the destination."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as llamalib
    from kubeflow_tpu.serving.continuous import (
        ContinuousEngine,
        migrate_live_sequences,
    )

    cfg = llamalib.tiny()
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    kw = dict(num_slots=conversations, decode_chunk=2,
              prefix_cache=False, block_size=16)
    src = ContinuousEngine(cfg, params, **kw)
    dst = ContinuousEngine(cfg, params, **kw)
    try:
        src.warmup()
        dst.warmup()
        reqs = [src.submit([7 + i, 8, 9, j + 1], max_new_tokens=96)
                for j in range(conversations)]
        while any(len(r.tokens) < 2 for r in reqs):
            time.sleep(0.002)
        counts = [len(r.tokens) for r in reqs]
        t0 = time.perf_counter()
        moved, failed = migrate_live_sequences(src, dst)
        while any(len(r.tokens) <= c for r, c in zip(reqs, counts)
                  if not r.done.is_set()):
            time.sleep(0.001)
        resumed_s = time.perf_counter() - t0
        for r in reqs:
            r.cancel()
        return {"drain_resume_s": resumed_s, "moved": moved,
                "failed": failed, "conversations": conversations}
    finally:
        src.stop()
        dst.stop()


def run_hibernate_trial(i: int, conversations: int = 4) -> dict:
    """One session hibernate/resume cycle (ISSUE 12): N live
    conversations spill to the manifest-verified storage tier (spill =
    export -> atomic write -> release), their replica DIES, and a
    fresh replica thaws every session from storage alone.  Measured:
    spill and thaw wall per session, plus the HBM blocks recovered
    while the sessions sleep (the free-list headroom hibernation
    buys)."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as llamalib
    from kubeflow_tpu.serving.continuous import ContinuousEngine
    from kubeflow_tpu.serving.storage import KvSpillStore

    cfg = llamalib.tiny()
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    kw = dict(num_slots=conversations, decode_chunk=2,
              prefix_cache=False, block_size=16)
    store = KvSpillStore(tempfile.mkdtemp(prefix="kvspill-bench-"))
    src = ContinuousEngine(cfg, params, **kw)
    dst = None
    try:
        src.warmup()
        reqs = [src.submit([7 + i, 8, 9, j + 1] * 8, max_new_tokens=64)
                for j in range(conversations)]
        while any(len(r.tokens) < 2 for r in reqs):
            time.sleep(0.002)
        free_before = src.stats()["kv_blocks_free"]
        spill_t0 = time.perf_counter()
        for j, r in enumerate(reqs):
            src.hibernate_sequence(r, f"sess-{i}-{j}", store=store)
        spill_s = time.perf_counter() - spill_t0
        freed = src.stats()["kv_blocks_free"] - free_before
        src.stop()  # replica death: storage is all that survives

        dst = ContinuousEngine(cfg, params, **kw)
        dst.warmup()
        counts = [len(r.tokens) for r in reqs]
        thaw_t0 = time.perf_counter()
        thawed = [dst.thaw_sequence(f"sess-{i}-{j}", store=store,
                                    req=reqs[j])[0]
                  for j in range(conversations)]
        while any(len(r.tokens) <= c for r, c in zip(thawed, counts)
                  if not r.done.is_set()):
            time.sleep(0.001)
        thaw_s = time.perf_counter() - thaw_t0
        for r in thawed:
            r.cancel()
        return {"spill_s": spill_s, "thaw_resume_s": thaw_s,
                "hbm_blocks_recovered": freed,
                "conversations": conversations,
                "recompiles": dst.stats()["jit_recompiles_total"],
                "verify_failures": store.verify_failures_total}
    finally:
        src.stop()
        if dst is not None:
            dst.stop()


def run_resize_trial(i: int, conversations: int,
                     aot_root: str | None = None) -> dict:
    """One elastic shrink: a TP=2 paged engine with N live
    conversations resizes to the surviving degree; measured = resize
    start -> every conversation has produced a token on the new-degree
    engine, with the resizer's own phase decomposition attached.  With
    ``aot_root`` the engines share an AOT artifact cache, so the
    destination-degree ladder prebuilds from disk while the old degree
    still serves — the timings then include ``prebuild_s``."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as llamalib
    from kubeflow_tpu.serving.continuous import ContinuousEngine
    from kubeflow_tpu.serving.resize import GangResizer

    cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    kw = dict(num_slots=conversations, decode_chunk=2,
              prefix_cache=False, block_size=16, seq_buckets=[32])
    if aot_root is not None:
        from kubeflow_tpu.serving.programs import ProgramArtifactCache
        kw["program_cache"] = ProgramArtifactCache(aot_root)
    src = ContinuousEngine(cfg, params, mesh_axes={"model": 2}, **kw)
    new = None
    try:
        src.warmup()
        reqs = [src.submit([7 + i, 8, 9, j + 1], max_new_tokens=96)
                for j in range(conversations)]
        while any(len(r.tokens) < 2 for r in reqs):
            time.sleep(0.002)
        counts = [len(r.tokens) for r in reqs]
        rz = GangResizer(src)
        t0 = time.perf_counter()
        new = rz.resize({"model": 1})
        while any(len(r.tokens) <= c for r, c in zip(reqs, counts)
                  if not r.done.is_set()):
            time.sleep(0.001)
        total = time.perf_counter() - t0
        for r in reqs:
            r.cancel()
        st = new.stats()
        return {"gang_resize_s": total, "conversations": conversations,
                **{k: v for k, v in rz.last_timings.items()
                   if k != "total_s"},
                "recompiles": st["jit_recompiles_total"],
                "aot_hits": st["aot_cache_hits_total"]}
    finally:
        (new if new is not None else src).stop()


def run_cold_start_trial(i: int, root: str | None) -> dict:
    """One replica boot (ISSUE 17): engine build -> warmup -> first
    token, either against a warm AOT artifact cache at ``root`` or with
    the cache disabled (``root=None``, every rung compiles)."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as llamalib
    from kubeflow_tpu.serving.continuous import ContinuousEngine
    from kubeflow_tpu.serving.programs import ProgramArtifactCache

    cfg = llamalib.tiny()
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    kw = dict(num_slots=2, decode_chunk=2, prefix_cache=False,
              block_size=16)
    if root is not None:
        kw["program_cache"] = ProgramArtifactCache(root)
    t0 = time.perf_counter()
    eng = ContinuousEngine(cfg, params, **kw)
    try:
        eng.warmup()
        warmup_s = time.perf_counter() - t0
        r = eng.submit([7, 8, 9, i + 1], max_new_tokens=4)
        r.done.wait(60)
        total = time.perf_counter() - t0
        st = eng.stats()
        return {"cold_start_s": total, "warmup_s": warmup_s,
                "aot_hits": st["aot_cache_hits_total"],
                "aot_misses": st["aot_cache_misses_total"],
                "recompiles": st["jit_recompiles_total"]}
    finally:
        eng.stop()


class _StubReplica:
    """A minimal always-answers backend for the outage row: the row
    measures ROUTING recovery (circuits, retry budget, mass-forget),
    so the data plane is a constant-latency JSON responder — no jax,
    no model, trials stay sub-second."""

    def __init__(self, latency_s: float = 0.005):
        import threading
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        stub = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0) or 0))
                time.sleep(latency_s)
                stub.requests += 1
                body = b'{"choices": [{"text": "ok"}]}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.requests = 0
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def run_outage_trial(i: int, seed: int, per_domain: int = 2,
                     storm_s: float = 3.0, workers: int = 8) -> dict:
    """One seeded domain outage mid open-loop storm (ISSUE 16): two
    failure domains of ``per_domain`` stub replicas behind the real
    Router, a 2x storm, and ``FaultPlan.domain_outage`` kills every
    replica of the seeded victim domain at once.  Scored:

    - ``reroute_s``        outage -> first 200 served by a survivor
    - ``slo_recovery_s``   outage -> 10 consecutive requests all 200
                           under the latency SLO (back under SLO)
    - ``retry_amplification``  (client requests + granted retries) /
                           client requests — the budget contract caps
                           it at 1 + ratio (+ the burst transient)
    - ``hung``             requests that never completed (must be 0)
    """
    import threading
    import urllib.error
    import urllib.request

    from kubeflow_tpu.chaos import FaultPlan
    from kubeflow_tpu.serving.controller import Router
    from kubeflow_tpu.serving.traffic import TrafficPlane

    domains = ("d0", "d1")
    stubs = {d: [_StubReplica() for _ in range(per_domain)]
             for d in domains}
    router = Router(activate=lambda: None)
    router.set_backends([s.url for d in domains for s in stubs[d]])
    router.set_traffic(TrafficPlane({}))
    router.set_domains({s.url: d for d in domains for s in stubs[d]})
    plan = FaultPlan(seed=seed + i).domain_outage(
        list(domains), min_at=0.3, max_at=0.6)
    plan.activate()
    url = router.url + "/openai/v1/completions"
    body = json.dumps({"model": "m", "prompt": "storm",
                       "max_tokens": 2}).encode()
    records: list = []
    rec_lock = threading.Lock()
    outage = {"t": None, "domain": None}
    stop_evt = threading.Event()
    slo_s = 0.75

    def actuate():
        for d in plan.due_domain_outages():
            outage["t"] = time.perf_counter()
            outage["domain"] = d
            for s in stubs[d]:
                s.stop()

    def storm():
        while not stop_evt.is_set():
            if outage["t"] is None:
                actuate()
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=20) as r:
                    r.read()
                    code = r.status
            except urllib.error.HTTPError as e:
                e.read()
                code = e.code
            except OSError:
                code = 0  # timeout/conn failure = a hang candidate
            with rec_lock:
                records.append((t0, time.perf_counter(), code))
            time.sleep(0.002)

    threads = [threading.Thread(target=storm, daemon=True)
               for _ in range(workers)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    while time.perf_counter() - t_start < storm_s:
        time.sleep(0.01)
    stop_evt.set()
    hung = 0
    for t in threads:
        t.join(timeout=30)
        hung += 1 if t.is_alive() else 0
    try:
        assert outage["t"] is not None, "seeded outage never fired"
        out_t = outage["t"]
        after = sorted([r for r in records if r[0] >= out_t])
        ok_after = [r for r in after if r[2] == 200]
        reroute = (ok_after[0][1] - out_t) if ok_after else None
        slo_recovery = None
        run = 0
        for r in after:
            run = run + 1 if (r[2] == 200
                              and r[1] - r[0] <= slo_s) else 0
            if run >= 10:
                slo_recovery = r[1] - out_t
                break
        rb = router.retry_budget.stats()
        n = len(records)
        amp = (n + rb["retries_granted_total"]) / max(n, 1)
        return {
            "reroute_s": reroute,
            "slo_recovery_s": slo_recovery,
            "retry_amplification": round(amp, 4),
            "retries_granted": rb["retries_granted_total"],
            "retries_denied": rb["retries_denied_total"],
            "requests": n,
            "failed_after_outage": sum(
                1 for r in after if r[2] != 200),
            "hung": hung,
            "circuit_opens": router.health.stats()[
                "circuit_opens_total"],
            "domain_outages_detected": router.domain_outages_total,
        }
    finally:
        router.stop()
        for d in domains:
            for s in stubs[d]:
                try:
                    s.stop()
                except Exception:  # noqa: BLE001 — the victim domain's
                    # stubs are already stopped by the actuator; a
                    # double-shutdown OSError here is the expected case
                    pass


def main() -> None:
    trials = int(sys.argv[1]) if len(sys.argv) > 1 else 12
    workers = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 0

    rows = []
    for i in range(trials):
        row = run_trial(i, workers, seed)
        rows.append(row)
        print("# trial", i, json.dumps({
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in row.items()}), file=sys.stderr)

    phase_p50 = {}
    for key in rows[0]:
        vals = sorted(v for r in rows for v in [r[key]] if v is not None)
        phase_p50[key] = round(vals[len(vals) // 2], 3) if vals else None
    print(json.dumps({
        "metric": "restart_to_running_p50_seconds",
        "unit": (f"s (seeded chaos kill -> all workers Running, "
                 f"n={trials}, workers={workers}, FakeKubelet cluster)"),
        **_percentiles([r["restart_to_running_s"] for r in rows]),
        "phase_p50": phase_p50,
    }))

    # cold restart: control-plane kill -9 -> WAL replay -> reconverged
    n_objects = 200
    restart_trials = max(3, trials // 3)
    restart_rows = []
    for i in range(restart_trials):
        row = run_restart_trial(i, workers, seed, n_objects=n_objects)
        restart_rows.append(row)
        print("# cold-restart trial", i, json.dumps({
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in row.items()}), file=sys.stderr)
    restart_p50 = {}
    for key in ("replay_s", "reconverge_s"):
        vals = sorted(r[key] for r in restart_rows)
        restart_p50[key] = round(vals[len(vals) // 2], 3)
    print(json.dumps({
        "metric": "cold_restart_recovery_p50_seconds",
        "unit": (f"s (control-plane kill -9 -> WAL/snapshot replay of "
                 f">={n_objects}-object store -> all workers Running, "
                 f"n={restart_trials}, workers={workers}, "
                 "FakeKubelet cluster)"),
        **_percentiles(
            [r["cold_restart_recovery_s"] for r in restart_rows]),
        "phase_p50": restart_p50,
        "objects_recovered": restart_rows[0]["objects_recovered"],
    }))

    # replica drain by live KV migration (ISSUE 8): lossless retire
    drain_trials = max(3, trials // 3)
    drain_rows = []
    for i in range(drain_trials):
        row = run_drain_trial(i)
        drain_rows.append(row)
        print("# drain trial", i, json.dumps({
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in row.items()}), file=sys.stderr)
    print(json.dumps({
        "metric": "replica_drain_resume_p50_seconds",
        "unit": (f"s (drain -> all {drain_rows[0]['conversations']} live "
                 "conversations decoding on the destination, live "
                 "paged-KV migration, n="
                 f"{drain_trials}, tiny model CPU stand-in)"),
        **_percentiles([r["drain_resume_s"] for r in drain_rows]),
        "moved_total": sum(r["moved"] for r in drain_rows),
        "failed_total": sum(r["failed"] for r in drain_rows),
    }))

    # session hibernate/resume (ISSUE 12): spill to storage, replica
    # dies, every session thaws on a fresh replica
    hib_trials = max(3, trials // 3)
    hib_rows = []
    for i in range(hib_trials):
        row = run_hibernate_trial(i)
        hib_rows.append(row)
        print("# hibernate trial", i, json.dumps({
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in row.items()}), file=sys.stderr)
    phase_p50 = {}
    for key in ("spill_s", "thaw_resume_s"):
        vals = sorted(r[key] for r in hib_rows)
        phase_p50[key] = round(vals[len(vals) // 2], 3)
    print(json.dumps({
        "metric": "session_hibernate_resume_p50_seconds",
        "unit": (f"s (spill {hib_rows[0]['conversations']} live "
                 "conversations to storage -> replica death -> all "
                 "thawed and decoding on a FRESH replica, manifest-"
                 f"verified KvSpillStore, n={hib_trials}, tiny model "
                 "CPU stand-in)"),
        **_percentiles([r["spill_s"] + r["thaw_resume_s"]
                        for r in hib_rows]),
        "phase_p50": phase_p50,
        "hbm_blocks_recovered_p50": sorted(
            r["hbm_blocks_recovered"]
            for r in hib_rows)[len(hib_rows) // 2],
        "recompiles_total": sum(r["recompiles"] for r in hib_rows),
        "verify_failures_total": sum(
            r["verify_failures"] for r in hib_rows),
    }))

    # elastic gang resize (ISSUE 10): TP shrink with live conversations,
    # live-conversation count swept
    resize_trials = max(3, trials // 4)
    resize_rows = []
    for convs in (2, 6):
        for i in range(resize_trials):
            row = run_resize_trial(i, conversations=convs)
            resize_rows.append(row)
            print("# resize trial", i, json.dumps({
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in row.items()}), file=sys.stderr)
    phase_p50 = {}
    for key in ("drain_s", "reshard_s", "resume_s"):
        vals = sorted(r[key] for r in resize_rows)
        phase_p50[key] = round(vals[len(vals) // 2], 3)
    per_count = {
        str(c): _percentiles([r["gang_resize_s"] for r in resize_rows
                              if r["conversations"] == c])["value"]
        for c in (2, 6)}
    print(json.dumps({
        "metric": "gang_resize_p50_seconds",
        "unit": (f"s (TP 2 -> 1 shrink -> all live conversations "
                 f"decoding at the new degree, n={resize_trials} per "
                 "count, tiny model CPU stand-in)"),
        **_percentiles([r["gang_resize_s"] for r in resize_rows]),
        "phase_p50": phase_p50,
        "p50_by_conversations": per_count,
        "recompiles_total": sum(r["recompiles"] for r in resize_rows),
    }))

    # AOT program-artifact cache (ISSUE 17): cold start warm vs cold,
    # then the resize compile-wall split against a warm cache
    import shutil
    import tempfile

    aot_trials = max(3, trials // 4)
    aot_root = tempfile.mkdtemp(prefix="kft-aot-bench-")
    try:
        run_cold_start_trial(-1, aot_root)  # seeding pass: publishes
        cold_rows, warm_rows = [], []
        for i in range(aot_trials):
            cold_rows.append(run_cold_start_trial(i, None))
            warm_rows.append(run_cold_start_trial(i, aot_root))
            print("# cold-start trial", i, json.dumps({
                "cold": round(cold_rows[-1]["cold_start_s"], 3),
                "warm": round(warm_rows[-1]["cold_start_s"], 3),
                "aot_hits": warm_rows[-1]["aot_hits"],
                "aot_misses_warm": warm_rows[-1]["aot_misses"],
            }), file=sys.stderr)
        cold_p = _percentiles([r["cold_start_s"] for r in cold_rows])
        warm_p = _percentiles([r["cold_start_s"] for r in warm_rows])
        print(json.dumps({
            "metric": "cold_start_warm_cache_p50_seconds",
            "unit": ("s (engine build -> warmup -> first token against "
                     "a warm ProgramArtifactCache root, "
                     f"n={aot_trials}, tiny model CPU stand-in)"),
            **warm_p,
            "cold_cache_p50_s": cold_p["value"],
            "speedup_x": round(cold_p["value"] / warm_p["value"], 2),
            "aot_hits_total": sum(r["aot_hits"] for r in warm_rows),
            "aot_misses_warm_total": sum(
                r["aot_misses"] for r in warm_rows),
            "recompiles_total": sum(
                r["recompiles"] for r in cold_rows + warm_rows),
        }))

        # warm-cache resize: the first pass seeds both ladders (TP=2
        # warmup + TP=1 prebuild publish); scored passes load from disk
        rz_warm_rows = []
        for i in range(aot_trials + 1):
            row = run_resize_trial(i, conversations=2, aot_root=aot_root)
            if i == 0:
                continue
            rz_warm_rows.append(row)
            print("# warm-resize trial", i, json.dumps({
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in row.items()}), file=sys.stderr)
        phase_p50 = {}
        for key in ("prebuild_s", "drain_s", "reshard_s", "resume_s"):
            vals = sorted(r.get(key, 0.0) for r in rz_warm_rows)
            phase_p50[key] = round(vals[len(vals) // 2], 3)
        disruption = [r["drain_s"] + r["reshard_s"] + r["resume_s"]
                      for r in rz_warm_rows]
        print(json.dumps({
            "metric": "gang_resize_warm_cache_p50_seconds",
            "unit": ("s (TP 2 -> 1 shrink with a warm "
                     "ProgramArtifactCache: prebuild overlaps live "
                     "serving, disruption = drain+reshard+resume, "
                     f"n={aot_trials}, tiny model CPU stand-in)"),
            **_percentiles([r["gang_resize_s"] for r in rz_warm_rows]),
            "phase_p50": phase_p50,
            "disruption_p50_s": round(
                sorted(disruption)[len(disruption) // 2], 3),
            "aot_hits_total": sum(r["aot_hits"] for r in rz_warm_rows),
            "recompiles_total": sum(
                r["recompiles"] for r in rz_warm_rows),
        }))
    finally:
        shutil.rmtree(aot_root, ignore_errors=True)

    # seeded domain outage mid storm (ISSUE 16): circuits + retry
    # budget + mass-forget — time-to-reroute, retry amplification,
    # time-back-under-SLO
    outage_trials = max(3, trials // 3)
    outage_rows = []
    for i in range(outage_trials):
        row = run_outage_trial(i, seed)
        outage_rows.append(row)
        print("# domain-outage trial", i, json.dumps({
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in row.items()}), file=sys.stderr)
    reroutes = [r["reroute_s"] for r in outage_rows
                if r["reroute_s"] is not None]
    slo_recoveries = [r["slo_recovery_s"] for r in outage_rows
                      if r["slo_recovery_s"] is not None]
    print(json.dumps({
        "metric": "domain_outage_reroute_p50_seconds",
        "unit": (f"s (seeded whole-domain kill mid 2x storm -> first "
                 f"survivor 200; n={outage_trials}, 2 domains x 2 "
                 "stub replicas, real Router circuits + retry "
                 "budget)"),
        **_percentiles(reroutes or [0.0]),
        "slo_recovery_p50_s": (round(sorted(slo_recoveries)[
            len(slo_recoveries) // 2], 3) if slo_recoveries else None),
        "retry_amplification_max": max(
            r["retry_amplification"] for r in outage_rows),
        "retries_denied_total": sum(
            r["retries_denied"] for r in outage_rows),
        "hung_total": sum(r["hung"] for r in outage_rows),
        "domain_outages_detected": sum(
            r["domain_outages_detected"] for r in outage_rows),
    }))


if __name__ == "__main__":
    main()
