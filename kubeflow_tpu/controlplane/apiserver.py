"""REST API server over the object store — the kube-apiserver analog.

SURVEY §1 L0 names the cluster substrate's public interface "the k8s REST
API"; in-process callers use the Store directly, and this server gives the
same objects an HTTP surface so out-of-process clients (the kft CLI,
curl, CI scripts) get the kubectl-equivalent UX [upstream: the reference's
CRDs are served by kube-apiserver; every kubectl verb in SURVEY §3's call
stacks starts here].

Routes (JSON bodies; YAML accepted on writes):

    GET    /healthz
    GET    /apis                          -> served kinds
    GET    /apis/<kind>?namespace=ns      -> list (all namespaces if omitted)
    POST   /apis/<kind>                   -> create (manifest body)
    GET    /apis/<kind>/<ns>/<name>       -> object
    PUT    /apis/<kind>/<ns>/<name>       -> update (optimistic concurrency:
                                             resource_version must match)
    DELETE /apis/<kind>/<ns>/<name>
    GET    /apis/<kind>/<ns>/<name>/events -> events for the object
    GET    /apis/Pod/<ns>/<name>/logs      -> pod stdout (when a log source
                                              is attached)

Error mapping follows the apiserver conventions: 404 NotFound, 409
AlreadyExists/Conflict, 422 admission-rejected.

Authn/authz: a cluster-admin bearer token (``token=`` / $KFT_API_TOKEN)
plus PER-PROFILE tokens (``profile_tokens=`` / $KFT_API_TOKENS
"alice=t1,bob=t2" / ``Profile.spec.api_token``) — the reference's
Profile-controller multi-tenancy [upstream: kubeflow/kubeflow ->
profile-controller RBAC bindings; SURVEY §2.4] mapped onto this plane:
a profile token authenticates as that profile, whose name IS its tenant
namespace (ux/profiles.py), and mutating routes (POST/PUT/DELETE) are
scoped to that namespace — 403 Forbidden elsewhere, which also stops
tenants from editing Profile/PodDefault objects (those live in
kft-profiles).  Reads stay cluster-wide (the dashboard surface).  With
any token configured, every route except ``/healthz`` requires
``Authorization: Bearer <t>``, else 401.  Default (nothing configured)
preserves the open local-dev surface.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

import yaml

from ..api.yaml_io import KIND_REGISTRY, from_dict, to_dict
from ..utils.net import allocate_port
from .controller import events_for
from .store import TOO_OLD, AlreadyExists, Conflict, NotFound, Rejected, Store

#: largest request body accepted on writes — the server must not allocate
#: whatever a client's Content-Length header claims (413 past this)
MAX_BODY_BYTES = 4 * 1024 * 1024


class BodyTooLarge(Exception):
    def __init__(self, n: int) -> None:
        super().__init__(
            f"request body {n} bytes exceeds limit {MAX_BODY_BYTES}")

#: case-insensitive kind aliases (kubectl-style shortnames + plurals)
KIND_ALIASES = {
    "jaxjobs": "JaxJob", "jj": "JaxJob",
    "pods": "Pod", "po": "Pod",
    "nodes": "Node", "no": "Node",
    "services": "Service", "svc": "Service",
    "podgroups": "PodGroup", "pg": "PodGroup",
    "events": "Event", "ev": "Event",
    "experiments": "Experiment", "exp": "Experiment",
    "suggestions": "Suggestion",
    "trials": "Trial",
    "inferenceservices": "InferenceService", "isvc": "InferenceService",
    "servingruntimes": "ServingRuntime",
    "inferencegraphs": "InferenceGraph", "ig": "InferenceGraph",
    "notebooks": "Notebook", "nb": "Notebook",
    "profiles": "Profile",
    "poddefaults": "PodDefault",
}


def resolve_kind(token: str) -> str:
    """kubectl-ish kind resolution: exact, alias, lowercase, or
    lowercase-plural."""
    if token in KIND_REGISTRY:
        return token
    low = token.lower()
    if low in KIND_ALIASES:
        return KIND_ALIASES[low]
    for kind in KIND_REGISTRY:
        if low in (kind.lower(), kind.lower() + "s"):
            return kind
    raise KeyError(token)


#: what a scrubbed credential reads back as (a write round-tripping this
#: sentinel preserves the stored secret — the kubectl-apply-a-GET flow)
REDACTED = "**redacted**"


def redact_for_read(d: dict) -> dict:
    """Scrub credential material from an object dict before it leaves on
    a READ.  Reads are cluster-wide (the dashboard surface), so without
    this any profile-token holder could lift every other tenant's bearer
    token from ``GET /apis/profiles`` and impersonate it (ADVICE r5
    high), or a legacy inline gang token from a JaxJob env.  Mutates and
    returns ``d`` (the dict is already a per-response copy)."""
    kind = d.get("kind")
    if kind == "Profile":
        spec = d.get("spec") or {}
        if spec.get("api_token"):
            spec["api_token"] = REDACTED
    elif kind in ("JaxJob", "Pod"):
        for env in _env_blocks(d):
            raw = env.get("KFT_SERVE_CONFIG")
            if not isinstance(raw, str) or "gang_token" not in raw:
                continue
            try:
                conf = json.loads(raw)
                if conf.pop("gang_token", None) is not None:
                    env["KFT_SERVE_CONFIG"] = json.dumps(conf)
            except (TypeError, ValueError):
                continue
    return d


def _env_blocks(d: dict) -> list[dict]:
    """Container env dicts reachable in a JaxJob/Pod manifest."""
    spec = d.get("spec") or {}
    out = []
    container = spec.get("container")
    if isinstance(container, dict) and isinstance(container.get("env"), dict):
        out.append(container["env"])
    for rspec in (spec.get("replica_specs") or {}).values():
        tmpl = rspec.get("template") if isinstance(rspec, dict) else None
        if isinstance(tmpl, dict) and isinstance(tmpl.get("env"), dict):
            out.append(tmpl["env"])
    return out


def _typed_env_blocks(obj) -> dict[str, dict]:
    """Keyed container env dicts on a TYPED JaxJob/Pod (for pairing an
    incoming write against the stored object)."""
    out: dict[str, dict] = {}
    spec = getattr(obj, "spec", None)
    container = getattr(spec, "container", None)
    if container is not None and isinstance(getattr(container, "env", None), dict):
        out["container"] = container.env
    for rtype, rspec in (getattr(spec, "replica_specs", None) or {}).items():
        tmpl = getattr(rspec, "template", None)
        if tmpl is not None and isinstance(getattr(tmpl, "env", None), dict):
            out[f"replica:{rtype}"] = tmpl.env
    return out


def restore_redacted_on_write(kind: str, obj, cur) -> None:
    """A write round-tripping a redacted READ must not destroy the stored
    credential: Profile.api_token carrying the sentinel keeps the stored
    token, and a JaxJob/Pod env whose KFT_SERVE_CONFIG lost its (legacy
    inline) gang_token to redact_for_read gets it re-attached from the
    stored object.  ``cur`` is the stored object (may be None)."""
    if kind == "Profile":
        if getattr(obj.spec, "api_token", None) == REDACTED:
            obj.spec.api_token = (
                getattr(cur.spec, "api_token", None) if cur else None)
        return
    if kind not in ("JaxJob", "Pod") or cur is None:
        return
    stored = _typed_env_blocks(cur)
    for key, env in _typed_env_blocks(obj).items():
        raw, raw_cur = env.get("KFT_SERVE_CONFIG"), stored.get(key, {}).get(
            "KFT_SERVE_CONFIG")
        if not raw or not raw_cur or "gang_token" not in raw_cur:
            continue
        try:
            conf, conf_cur = json.loads(raw), json.loads(raw_cur)
        except (TypeError, ValueError):
            continue
        if "gang_token" not in conf and "gang_token" in conf_cur:
            conf["gang_token"] = conf_cur["gang_token"]
            env["KFT_SERVE_CONFIG"] = json.dumps(conf)


class ApiServer:
    """HTTP facade over a Store (one per cluster)."""

    def __init__(self, store: Optional[Store] = None,
                 port: Optional[int] = None,
                 log_path_for: Optional[Callable[[str, str], str]] = None,
                 token: Optional[str] = None,
                 profile_tokens: Optional[dict[str, str]] = None,
                 data_dir: Optional[str] = None):
        import os

        if store is None:
            if data_dir is None:
                raise ValueError("ApiServer needs a store or a data_dir")
            # standalone durable mode: the server owns (and closes) a
            # WAL-backed store recovered from data_dir — with the same
            # admission webhooks a Cluster registers, or writes through
            # this surface would persist un-defaulted/unvalidated specs
            from .cluster import register_default_admission

            store = Store.open(data_dir)
            register_default_admission(store)
            self._owns_store = True
        else:
            self._owns_store = False
        self.store = store
        self.log_path_for = log_path_for
        self.port = port or allocate_port()
        self.token = token if token is not None else os.environ.get(
            "KFT_API_TOKEN") or None
        #: profile name -> bearer token (per-tenant identity; also fed by
        #: Profile.spec.api_token).  $KFT_API_TOKENS: "alice=t1,bob=t2".
        self.profile_tokens = dict(profile_tokens or {})
        env_tokens = os.environ.get("KFT_API_TOKENS", "")
        for pair in env_tokens.split(","):
            name, _, tok = pair.strip().partition("=")
            if name and tok:
                self.profile_tokens.setdefault(name, tok)
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, payload, raw: Optional[bytes] = None,
                      ctype: str = "application/json") -> None:
                body = raw if raw is not None else json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", "0"))
                if n > MAX_BODY_BYTES:
                    # reject BEFORE reading: the header is client-
                    # controlled and must not size an allocation
                    self.close_connection = True  # unread body poisons keep-alive
                    raise BodyTooLarge(n)
                raw = self.rfile.read(n) if n > 0 else b"{}"
                text = raw.decode()
                if self.headers.get("Content-Type", "").startswith(
                        "application/yaml") or not text.lstrip().startswith("{"):
                    return yaml.safe_load(text) or {}
                return json.loads(text)

            def do_GET(self):
                api._handle(self, "GET")

            def do_POST(self):
                api._handle(self, "POST")

            def do_PUT(self):
                api._handle(self, "PUT")

            def do_DELETE(self):
                api._handle(self, "DELETE")

        # one persistent store watch feeds a bounded event buffer; watch
        # long-polls resume from a resource_version against this buffer,
        # so events BETWEEN polls are not lost (a per-request store watch
        # would drop anything that happened while no poll was in flight)
        self._events: "deque[tuple[int, object]]" = deque(maxlen=2048)
        self._events_cond = threading.Condition()
        self._event_seq = 0
        #: highest seq EVICTED from the bounded buffer (0 = nothing yet):
        #: a watch cursor at or below this has lost events and must relist
        #: — signalled with 410 Gone, kube-apiserver style, instead of
        #: silently skipping the gap
        self._evicted_seq = 0
        self._stopping = False
        self._store_watch = store.watch(list(KIND_REGISTRY))
        self._pump = threading.Thread(
            target=self._pump_events, name="apiserver-watch-pump", daemon=True)
        self._pump.start()

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"apiserver-{self.port}",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._stopping = True
        self.store.stop_watch(self._store_watch)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)
        with self._events_cond:  # release any parked long-polls
            self._events_cond.notify_all()
        self._pump.join(timeout=2)
        if self._owns_store:
            self.store.close()

    # -- request handling --------------------------------------------------

    #: authenticated-as-cluster-admin sentinel (single-token mode, or no
    #: authn configured at all — the open local-dev surface)
    ADMIN = "__cluster_admin__"

    def _profile_object_tokens(self) -> dict[str, str]:
        """Profile.spec.api_token credentials (the object-driven half of
        per-profile identity; env/ctor tokens need no store objects)."""
        out = {}
        try:
            for prof in self.store.list("Profile"):
                tok = getattr(prof.spec, "api_token", None)
                if tok:
                    out[prof.metadata.name] = tok
        except KeyError:
            pass
        return out

    def _authenticate(self, h) -> Optional[str]:
        """Identity for this request: ADMIN, a profile name (== the
        tenant namespace the identity may mutate), or None (rejected).
        Constant-time compares throughout — a plain != short-circuits at
        the first differing byte, a timing oracle on the credential."""
        import hmac

        tenant_tokens = dict(self.profile_tokens)
        tenant_tokens.update(self._profile_object_tokens())
        if not self.token and not tenant_tokens:
            return self.ADMIN  # no authn configured: open local dev
        got = h.headers.get("Authorization", "")
        if self.token and hmac.compare_digest(got, f"Bearer {self.token}"):
            return self.ADMIN
        for name, tok in sorted(tenant_tokens.items()):
            if hmac.compare_digest(got, f"Bearer {tok}"):
                return name
        return None

    def _handle(self, h, method: str) -> None:
        # errors carry a structured ``reason`` (kube-apiserver Status.reason
        # analog) so clients branch on it, never on message text — substring
        # matching misclassified a 422 whose message contained "exists"
        identity = self.ADMIN
        if urlparse(h.path).path != "/healthz":
            identity = self._authenticate(h)
            if identity is None:
                h._send(401, {"error": "missing or invalid bearer token",
                              "reason": "Unauthorized"})
                return
        try:
            self._route(h, method, identity)
        except NotFound as e:
            h._send(404, {"error": str(e), "reason": "NotFound"})
        except AlreadyExists as e:
            h._send(409, {"error": str(e), "reason": "AlreadyExists"})
        except Conflict as e:
            h._send(409, {"error": str(e), "reason": "Conflict"})
        except Rejected as e:
            h._send(422, {"error": str(e), "reason": "Invalid"})
        except BodyTooLarge as e:
            h._send(413, {"error": str(e), "reason": "RequestEntityTooLarge"})
        except KeyError as e:
            h._send(404, {"error": f"unknown kind {e}", "reason": "NotFound"})
        except Exception as e:  # noqa: BLE001 — surface as 400
            h._send(400, {"error": f"{type(e).__name__}: {e}",
                          "reason": "BadRequest"})

    def _pump_events(self) -> None:
        import queue as queuelib

        while True:
            try:
                ev = self._store_watch.q.get(timeout=0.5)
            except queuelib.Empty:
                if getattr(self._store_watch, "closed", False):
                    return
                continue
            if ev.type == TOO_OLD:
                if self._stopping:
                    return
                # the store-side watch overflowed: re-subscribe, then
                # expire EVERY outstanding cursor — events were dropped
                # before they ever got a seq, so any resume would have a
                # silent hole; clients get 410 and relist
                self._store_watch = self.store.watch(list(KIND_REGISTRY))
                with self._events_cond:
                    self._event_seq += 1
                    self._evicted_seq = self._event_seq
                    self._events.clear()
                    self._events_cond.notify_all()
                continue
            with self._events_cond:
                self._event_seq += 1
                if len(self._events) == self._events.maxlen:
                    self._evicted_seq = self._events[0][0]
                self._events.append((self._event_seq, ev))
                self._events_cond.notify_all()

    def _watch(self, h, kind: str, ns: Optional[str], timeout: float,
               after: Optional[int]) -> None:
        """Long-poll against the buffered event stream.

        ``after`` is the cursor (the ``seq`` of the last event the client
        saw); ABSENT means "only future events".  A cursor of 0 is a real
        resume point (a first poll before any event legitimately returns
        cursor 0), so absence is None, not a 0 sentinel.  Each response
        carries ``seq`` per item and ``cursor`` to pass back — re-polling
        with the cursor recovers everything that happened between polls
        (up to the buffer's retention)."""
        deadline = time.monotonic() + min(max(timeout, 0.0), 300.0)
        expired = None
        with self._events_cond:
            if after is None:
                after = self._event_seq  # "now": only future events
            elif after < self._evicted_seq:
                # the buffer (shared across kinds) rolled past the
                # client's cursor: some events are GONE — tell the client
                # (kube-apiserver's 410 Gone) rather than silently
                # resuming with a hole.  The resync cursor is the
                # EVICTION BOUNDARY, not the head: re-polling with it
                # still delivers the whole retained window.
                expired = {
                    "error": "watch cursor expired: events up to "
                             f"seq {self._evicted_seq} were evicted",
                    "reason": "Expired",
                    "cursor": self._evicted_seq,
                }
        if expired is not None:
            # socket write happens OUTSIDE the condition: a slow client
            # must not stall the pump and every other watcher
            h._send(410, expired)
            return

        def collect():
            return [
                (seq, ev) for seq, ev in self._events
                if seq > after and ev.obj.kind == kind
                and (ns is None or ev.obj.metadata.namespace == ns)
            ]

        with self._events_cond:
            matched = collect()
            while not matched and after >= self._evicted_seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._events_cond.wait(timeout=remaining)
                matched = collect()
            if after < self._evicted_seq:
                # eviction can also happen DURING the wait (a burst rolls
                # the buffer past our cursor while we park, with or
                # without retained matches left) — same 410 contract as
                # at entry; returning retained events here would silently
                # skip the evicted gap
                expired = {
                    "error": "watch cursor expired during poll: events "
                             f"up to seq {self._evicted_seq} were evicted",
                    "reason": "Expired",
                    "cursor": self._evicted_seq,
                }
            cursor = matched[-1][0] if matched else after
        if expired is not None:
            h._send(410, expired)
            return
        h._send(200, {
            "cursor": cursor,
            "items": [
                {"type": ev.type, "seq": seq,
                 "object": redact_for_read(to_dict(ev.obj))}
                for seq, ev in matched
            ],
        })

    def _route(self, h, method: str, identity: Optional[str] = None) -> None:
        identity = self.ADMIN if identity is None else identity
        u = urlparse(h.path)
        parts = [p for p in u.path.split("/") if p]
        q = parse_qs(u.query)

        def forbidden(ns: str) -> bool:
            """Mutations scope to the identity's tenant namespace (a
            Profile's name IS its namespace, ux/profiles.py) — this also
            blocks tenants from mutating Profiles/PodDefaults themselves,
            which live in the kft-profiles namespace."""
            if identity != self.ADMIN and ns != identity:
                h._send(403, {
                    "error": f"profile {identity!r} may not modify "
                             f"namespace {ns!r}",
                    "reason": "Forbidden"})
                return True
            return False
        if u.path == "/healthz":
            h._send(200, {"ok": True})
            return
        if not parts or parts[0] != "apis":
            h._send(404, {"error": f"unknown path {u.path}"})
            return
        if len(parts) == 1:
            h._send(200, {"kinds": sorted(KIND_REGISTRY)})
            return
        kind = resolve_kind(parts[1])
        if len(parts) == 2:
            if method == "POST":
                manifest = h._body()
                manifest.setdefault("kind", kind)
                obj = from_dict(manifest)
                if forbidden(obj.metadata.namespace):
                    return
                if (kind == "Profile"
                        and getattr(obj.spec, "api_token", None) == REDACTED):
                    # a DELETE+POST replace of a redacted GET would store
                    # the PUBLIC sentinel as a live bearer token; with no
                    # stored object left to restore from, reject loudly
                    h._send(422, {
                        "error": "spec.api_token is the redaction "
                                 "sentinel; supply the real credential",
                        "reason": "Invalid"})
                    return
                created = self.store.create(obj)
                h._send(201, redact_for_read(to_dict(created)))
                return
            ns = q.get("namespace", [None])[0]
            if (method == "GET"
                    and q.get("watch", ["false"])[0] in ("true", "1")):
                # kubectl -w analog: long-poll the buffered event stream;
                # pass back the returned ``cursor`` to resume without
                # losing events that land between polls
                cur = q.get("cursor", [None])[0]
                self._watch(h, kind, ns,
                            float(q.get("timeout", ["30"])[0]),
                            int(cur) if cur is not None else None)
                return
            objs = self.store.list(kind, ns)
            h._send(200, {"items": [redact_for_read(to_dict(o)) for o in objs]})
            return
        if len(parts) == 3:
            # /apis/<kind>/<ns> — namespace-scoped list (also the natural
            # exploratory URL; must not 400 on a missing name segment)
            objs = self.store.list(kind, parts[2])
            h._send(200, {"items": [redact_for_read(to_dict(o)) for o in objs]})
            return
        ns, name = parts[2], parts[3]
        if len(parts) == 5 and parts[4] == "events":
            h._send(200, {"items": [to_dict(e) for e in events_for(
                self.store, kind, name) if e.metadata.namespace == ns]})
            return
        if len(parts) == 5 and parts[4] == "logs" and kind == "Pod":
            if self.log_path_for is None:
                h._send(404, {"error": "no log source attached"})
                return
            try:
                with open(self.log_path_for(ns, name)) as f:
                    h._send(200, None, raw=f.read().encode(),
                            ctype="text/plain")
            except OSError as e:
                h._send(404, {"error": f"no logs: {e}"})
            return
        if method == "GET":
            h._send(200, redact_for_read(to_dict(self.store.get(kind, name, ns))))
            return
        if method == "PUT":
            if forbidden(ns):
                return
            manifest = h._body()
            manifest.setdefault("kind", kind)
            obj = from_dict(manifest)
            obj.metadata.name, obj.metadata.namespace = name, ns
            if kind in ("Profile", "JaxJob", "Pod"):
                # GET -> edit -> PUT round-trip: redacted credentials mean
                # "keep the stored secret", never clobber it
                restore_redacted_on_write(
                    kind, obj, self.store.try_get(kind, name, ns))
            h._send(200, redact_for_read(to_dict(self.store.update(obj))))
            return
        if method == "DELETE":
            if forbidden(ns):
                return
            self.store.delete(kind, name, ns)
            h._send(200, {"deleted": f"{kind}/{ns}/{name}"})
            return
        h._send(405, {"error": f"{method} not supported on {u.path}"})
