"""In-process API server: typed object store with watches and admission.

The envtest analog from SURVEY.md §4: a real state store + watch semantics so
reconcilers run deterministically without Kubernetes.  Semantics kept from
the real API server because the reference's controllers depend on them:

- optimistic concurrency (``resource_version`` bump per write; stale updates
  raise ``Conflict``) — the races the reference's expectations cache exists
  to tame happen here too, on purpose;
- admission hooks per kind (mutating defaulting then validating), the webhook
  layer [upstream: training-operator -> pkg/webhooks/];
- watch streams with ADDED/MODIFIED/DELETED events fanned out to subscriber
  queues (the informer analog).
"""

from __future__ import annotations

import copy
import itertools
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from ..api.common import TypedObject, object_key


class ApiError(Exception):
    pass


class NotFound(ApiError):
    pass


class AlreadyExists(ApiError):
    pass


class Conflict(ApiError):
    """resource_version mismatch — caller must re-read and retry."""


class Rejected(ApiError):
    """Admission (validating webhook) rejection."""


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: TypedObject


@dataclass
class _Watch:
    kinds: frozenset[str]
    q: "queue.Queue[WatchEvent]" = field(default_factory=queue.Queue)
    closed: bool = False


MutatingHook = Callable[[TypedObject], TypedObject]
ValidatingHook = Callable[[TypedObject], None]


class Store:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objs: dict[tuple[str, str], TypedObject] = {}  # (kind, ns/name)
        self._rv = itertools.count(1)
        self._watches: list[_Watch] = []
        self._mutators: dict[str, list[MutatingHook]] = {}
        self._validators: dict[str, list[ValidatingHook]] = {}

    # -- admission registration ------------------------------------------------

    def register_admission(
        self,
        kind: str,
        mutate: Optional[MutatingHook] = None,
        validate: Optional[ValidatingHook] = None,
    ) -> None:
        if mutate:
            self._mutators.setdefault(kind, []).append(mutate)
        if validate:
            self._validators.setdefault(kind, []).append(validate)

    def _admit(self, obj: TypedObject) -> TypedObject:
        for m in self._mutators.get(obj.kind, []):
            obj = m(obj) or obj
        for v in self._validators.get(obj.kind, []):
            try:
                v(obj)
            except Exception as e:  # noqa: BLE001 — admission wraps any failure
                raise Rejected(str(e)) from e
        return obj

    # -- CRUD ------------------------------------------------------------------

    def create(self, obj: TypedObject) -> TypedObject:
        obj = copy.deepcopy(obj)
        obj = self._admit(obj)
        with self._lock:
            k = (obj.kind, obj.key)
            if k in self._objs:
                raise AlreadyExists(f"{obj.kind} {obj.key} exists")
            obj.metadata.uid = obj.metadata.uid or uuid.uuid4().hex[:12]
            obj.metadata.resource_version = next(self._rv)
            obj.metadata.creation_timestamp = (
                obj.metadata.creation_timestamp or time.time()
            )
            self._objs[k] = obj
            self._notify(WatchEvent(ADDED, copy.deepcopy(obj)))
        return copy.deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> TypedObject:
        with self._lock:
            k = (kind, object_key(namespace, name))
            if k not in self._objs:
                raise NotFound(f"{kind} {namespace}/{name}")
            return copy.deepcopy(self._objs[k])

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, obj: TypedObject) -> TypedObject:
        obj = copy.deepcopy(obj)
        obj = self._admit(obj)  # webhooks run on UPDATE too, like the real apiserver
        with self._lock:
            k = (obj.kind, obj.key)
            cur = self._objs.get(k)
            if cur is None:
                raise NotFound(f"{obj.kind} {obj.key}")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {obj.key}: rv {obj.metadata.resource_version} "
                    f"!= {cur.metadata.resource_version}"
                )
            if obj == cur:
                # no-op write: like the real apiserver, don't bump the rv or
                # fire MODIFIED — otherwise every reconcile's unchanged
                # status write would requeue its own key in a hot loop
                return copy.deepcopy(cur)
            obj.metadata.resource_version = next(self._rv)
            self._objs[k] = obj
            self._notify(WatchEvent(MODIFIED, copy.deepcopy(obj)))
        return copy.deepcopy(obj)

    def update_with_retry(
        self, kind: str, name: str, namespace: str, fn: Callable[[TypedObject], None],
        attempts: int = 8,
    ) -> TypedObject:
        """Read-modify-write with conflict retry (client-go UpdateStatus idiom)."""
        last: Optional[Exception] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace)
            fn(obj)
            try:
                return self.update(obj)
            except Conflict as e:
                last = e
        raise last  # type: ignore[misc]

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            k = (kind, object_key(namespace, name))
            obj = self._objs.pop(k, None)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            self._notify(WatchEvent(DELETED, copy.deepcopy(obj)))

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        labels: Optional[dict[str, str]] = None,
    ) -> list[TypedObject]:
        with self._lock:
            out = []
            for (k, _), obj in self._objs.items():
                if k != kind:
                    continue
                if namespace and obj.metadata.namespace != namespace:
                    continue
                if labels and any(
                    obj.metadata.labels.get(lk) != lv for lk, lv in labels.items()
                ):
                    continue
                out.append(copy.deepcopy(obj))
            return sorted(out, key=lambda o: o.metadata.name)

    # -- watches ---------------------------------------------------------------

    def watch(self, kinds: Iterable[str]) -> "_Watch":
        w = _Watch(kinds=frozenset(kinds))
        with self._lock:
            self._watches.append(w)
        return w

    def stop_watch(self, w: "_Watch") -> None:
        with self._lock:
            w.closed = True
            if w in self._watches:
                self._watches.remove(w)

    def _notify(self, ev: WatchEvent) -> None:
        for w in self._watches:
            if not w.closed and ev.obj.kind in w.kinds:
                w.q.put(ev)
