"""In-process API server: typed object store with watches and admission.

The envtest analog from SURVEY.md §4: a real state store + watch semantics so
reconcilers run deterministically without Kubernetes.  Semantics kept from
the real API server because the reference's controllers depend on them:

- optimistic concurrency (``resource_version`` bump per write; stale updates
  raise ``Conflict``) — the races the reference's expectations cache exists
  to tame happen here too, on purpose;
- admission hooks per kind (mutating defaulting then validating), the webhook
  layer [upstream: training-operator -> pkg/webhooks/];
- watch streams with ADDED/MODIFIED/DELETED events fanned out to BOUNDED
  subscriber queues (the informer analog; an overflowed subscriber gets a
  TOO_OLD marker and must relist, kube-apiserver's 410 Gone contract);
- optional etcd-style durability: ``Store.open(data_dir)`` attaches a
  write-ahead log + snapshot (wal.py) so a control-plane kill -9 recovers
  every object and resumes the resourceVersion counter.
"""

from __future__ import annotations

import copy
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from ..api.common import TypedObject, object_key
from .wal import OP_DEL, OP_PUT, Wal, WalCrashPoint  # noqa: F401 (re-export)


class ApiError(Exception):
    pass


class NotFound(ApiError):
    pass


class AlreadyExists(ApiError):
    pass


class Conflict(ApiError):
    """resource_version mismatch — caller must re-read and retry."""


class Rejected(ApiError):
    """Admission (validating webhook) rejection."""


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
#: Marker event closing an overflowed watch: the subscriber was too slow,
#: events were dropped, and the ONLY correct response is to re-watch and
#: relist (kube-apiserver's 410 Gone / client-go relist contract).  The
#: marker's ``obj`` is None.
TOO_OLD = "TOO_OLD"


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED | TOO_OLD
    obj: Optional[TypedObject]


@dataclass
class _Watch:
    kinds: frozenset[str]
    q: "queue.Queue[WatchEvent]" = field(default_factory=queue.Queue)
    closed: bool = False
    #: set when the watch was closed for falling behind (queue overflow)
    too_old: bool = False


MutatingHook = Callable[[TypedObject], TypedObject]
ValidatingHook = Callable[[TypedObject], None]


class Store:
    #: default per-watch queue bound — one slow watcher must not grow
    #: memory without limit; on overflow the watch closes with a TOO_OLD
    #: marker and the subscriber relists (never silently misses events)
    watch_maxsize: int = 4096

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objs: dict[tuple[str, str], TypedObject] = {}  # (kind, ns/name)
        self._last_rv = 0
        self._watches: list[_Watch] = []
        self._mutators: dict[str, list[MutatingHook]] = {}
        self._validators: dict[str, list[ValidatingHook]] = {}
        #: durability (None = classic in-memory store)
        self._wal: Optional[Wal] = None

    def _next_rv(self) -> int:
        self._last_rv += 1
        return self._last_rv

    # -- durability ------------------------------------------------------------

    @classmethod
    def open(
        cls,
        data_dir: str,
        fsync_every: int = 64,
        fsync_interval_s: float = 0.05,
        snapshot_every: int = 1024,
        crashpoint: Optional[WalCrashPoint] = None,
    ) -> "Store":
        """Open (or create) a durable store at ``data_dir``: replay
        snapshot + WAL into memory, resume the ``resourceVersion``
        counter past everything recovered (so optimistic-concurrency
        semantics hold across restarts), and keep logging.

        Replay bypasses admission — every recovered object was admitted
        when it was first written."""
        # late import: yaml_io pulls in every api kind module; importing
        # objects registers the cluster-substrate kinds (Pod/Node/...)
        from ..api.yaml_io import from_dict
        from . import objects  # noqa: F401 — KIND_REGISTRY side effect

        store = cls()
        wal = Wal(data_dir, fsync_every=fsync_every,
                  fsync_interval_s=fsync_interval_s,
                  snapshot_every=snapshot_every, crashpoint=crashpoint)
        snap_rv, snap_objs, records = wal.recover()
        max_rv = snap_rv
        for d in snap_objs:
            obj = from_dict(d)
            store._objs[(obj.kind, obj.key)] = obj
            max_rv = max(max_rv, obj.metadata.resource_version)
        for rec in records:
            rv = int(rec["rv"])
            if rv <= snap_rv:
                # a crash between snapshot rename and log truncation
                # leaves already-snapshotted records behind — skip them
                continue
            if rec["op"] == OP_PUT:
                obj = from_dict(rec["obj"])
                store._objs[(obj.kind, obj.key)] = obj
            else:
                store._objs.pop(
                    (rec["kind"], object_key(rec["ns"], rec["name"])), None)
            max_rv = max(max_rv, rv)
        store._last_rv = max_rv
        store._wal = wal
        return store

    @property
    def wal(self) -> Optional[Wal]:
        return self._wal

    def close(self) -> None:
        """Flush and detach the WAL (no-op for in-memory stores)."""
        with self._lock:
            wal, self._wal = self._wal, None
        if wal is not None:
            wal.close()

    def _persist_put(self, obj: TypedObject) -> None:
        """Called under ``_lock`` after a successful create/update."""
        if self._wal is None:
            return
        from ..api.yaml_io import to_dict

        self._wal.append({"rv": obj.metadata.resource_version,
                          "op": OP_PUT, "obj": to_dict(obj)})
        self._maybe_snapshot()

    def _persist_del(self, kind: str, namespace: str, name: str) -> None:
        """Called under ``_lock`` after a successful delete.  Deletes
        draw their own rv so WAL replay order is total (etcd bumps its
        revision on delete for the same reason)."""
        if self._wal is None:
            return
        self._wal.append({"rv": self._next_rv(), "op": OP_DEL,
                          "kind": kind, "ns": namespace, "name": name})
        self._maybe_snapshot()

    def _maybe_snapshot(self) -> None:
        wal = self._wal
        if wal is None or wal.records_since_snapshot < wal.snapshot_every:
            return
        from ..api.yaml_io import to_dict

        # under _lock: the dump is consistent with every appended record
        wal.write_snapshot(
            self._last_rv, [to_dict(o) for o in self._objs.values()])

    # -- admission registration ------------------------------------------------

    def register_admission(
        self,
        kind: str,
        mutate: Optional[MutatingHook] = None,
        validate: Optional[ValidatingHook] = None,
    ) -> None:
        if mutate:
            self._mutators.setdefault(kind, []).append(mutate)
        if validate:
            self._validators.setdefault(kind, []).append(validate)

    def _admit(self, obj: TypedObject) -> TypedObject:
        for m in self._mutators.get(obj.kind, []):
            obj = m(obj) or obj
        for v in self._validators.get(obj.kind, []):
            try:
                v(obj)
            except Exception as e:  # noqa: BLE001 — admission wraps any failure
                raise Rejected(str(e)) from e
        return obj

    # -- CRUD ------------------------------------------------------------------

    def create(self, obj: TypedObject) -> TypedObject:
        obj = copy.deepcopy(obj)
        obj = self._admit(obj)
        with self._lock:
            k = (obj.kind, obj.key)
            if k in self._objs:
                raise AlreadyExists(f"{obj.kind} {obj.key} exists")
            obj.metadata.uid = obj.metadata.uid or uuid.uuid4().hex[:12]
            obj.metadata.resource_version = self._next_rv()
            obj.metadata.creation_timestamp = (
                obj.metadata.creation_timestamp or time.time()
            )
            self._objs[k] = obj
            self._persist_put(obj)
            self._notify(WatchEvent(ADDED, copy.deepcopy(obj)))
        return copy.deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> TypedObject:
        with self._lock:
            k = (kind, object_key(namespace, name))
            if k not in self._objs:
                raise NotFound(f"{kind} {namespace}/{name}")
            return copy.deepcopy(self._objs[k])

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, obj: TypedObject) -> TypedObject:
        obj = copy.deepcopy(obj)
        obj = self._admit(obj)  # webhooks run on UPDATE too, like the real apiserver
        with self._lock:
            k = (obj.kind, obj.key)
            cur = self._objs.get(k)
            if cur is None:
                raise NotFound(f"{obj.kind} {obj.key}")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {obj.key}: rv {obj.metadata.resource_version} "
                    f"!= {cur.metadata.resource_version}"
                )
            if obj == cur:
                # no-op write: like the real apiserver, don't bump the rv or
                # fire MODIFIED — otherwise every reconcile's unchanged
                # status write would requeue its own key in a hot loop
                return copy.deepcopy(cur)
            obj.metadata.resource_version = self._next_rv()
            self._objs[k] = obj
            self._persist_put(obj)
            self._notify(WatchEvent(MODIFIED, copy.deepcopy(obj)))
        return copy.deepcopy(obj)

    def update_with_retry(
        self, kind: str, name: str, namespace: str, fn: Callable[[TypedObject], None],
        attempts: int = 8,
    ) -> TypedObject:
        """Read-modify-write with conflict retry (client-go UpdateStatus idiom)."""
        last: Optional[Exception] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace)
            fn(obj)
            try:
                return self.update(obj)
            except Conflict as e:
                last = e
        raise last  # type: ignore[misc]

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            k = (kind, object_key(namespace, name))
            obj = self._objs.pop(k, None)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            self._persist_del(kind, namespace, name)
            self._notify(WatchEvent(DELETED, copy.deepcopy(obj)))

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        labels: Optional[dict[str, str]] = None,
    ) -> list[TypedObject]:
        with self._lock:
            out = []
            for (k, _), obj in self._objs.items():
                if k != kind:
                    continue
                if namespace and obj.metadata.namespace != namespace:
                    continue
                if labels and any(
                    obj.metadata.labels.get(lk) != lv for lk, lv in labels.items()
                ):
                    continue
                out.append(copy.deepcopy(obj))
            return sorted(out, key=lambda o: o.metadata.name)

    # -- watches ---------------------------------------------------------------

    def watch(self, kinds: Iterable[str],
              maxsize: Optional[int] = None) -> "_Watch":
        w = _Watch(kinds=frozenset(kinds),
                   q=queue.Queue(maxsize=maxsize or self.watch_maxsize))
        with self._lock:
            self._watches.append(w)
        return w

    def stop_watch(self, w: "_Watch") -> None:
        with self._lock:
            w.closed = True
            if w in self._watches:
                self._watches.remove(w)

    def _notify(self, ev: WatchEvent) -> None:
        assert ev.obj is not None
        for w in list(self._watches):
            if w.closed or ev.obj.kind not in w.kinds:
                continue
            try:
                w.q.put_nowait(ev)
            except queue.Full:
                # slow subscriber: close the watch with a TOO_OLD marker
                # instead of growing without bound OR dropping silently —
                # the subscriber must re-watch + relist.  Evicting one
                # queued event guarantees room for the marker (this is
                # the only producer, under _lock).
                w.closed = True
                w.too_old = True
                self._watches.remove(w)
                try:
                    w.q.get_nowait()
                except queue.Empty:
                    pass
                w.q.put_nowait(WatchEvent(TOO_OLD, None))
