"""Cluster facade: wire the store, admission webhooks, scheduler, controllers.

The ``cmd/training-operator.v1/main.go`` analog [upstream:
kubeflow/training-operator]: one manager that registers schemes/webhooks and
starts every reconciler, plus (unlike the reference, which assumes a real
cluster underneath) the substrate itself — Nodes and a gang scheduler.
"""

from __future__ import annotations

from typing import Optional

from ..api import (
    default_experiment,
    default_inference_service,
    default_jaxjob,
    validate_experiment,
    validate_inference_service,
    validate_jaxjob,
)
from ..api.common import ObjectMeta
from ..api.experiment import KIND_EXPERIMENT
from ..api.inference import KIND_INFERENCE_SERVICE
from ..api.jaxjob import KIND_JAXJOB
from .controller import Controller
from .jaxjob_controller import JaxJobController
from .objects import KIND_NODE, Node, NodeSpec
from .scheduler import GangScheduler
from .store import Store


def register_default_admission(store: Store) -> None:
    """The platform's webhook set — defaulting + validation per kind.
    Every store that accepts user writes (Cluster-owned OR a standalone
    durable ApiServer's) must register these, or un-defaulted/invalid
    specs get WAL-persisted and replayed admission-free forever."""
    store.register_admission(
        KIND_JAXJOB, mutate=default_jaxjob, validate=validate_jaxjob)
    store.register_admission(
        KIND_EXPERIMENT, mutate=default_experiment,
        validate=validate_experiment)
    store.register_admission(
        KIND_INFERENCE_SERVICE,
        mutate=default_inference_service,
        validate=validate_inference_service,
    )


class Cluster:
    def __init__(self, data_dir: Optional[str] = None,
                 wal_crashpoint=None) -> None:
        """``data_dir`` turns on control-plane durability: the store is
        recovered from (and keeps logging to) a WAL + snapshot there, so
        a crash-restarted Cluster resumes every JaxJob/ISvc/pod where
        the log left them.  ``wal_crashpoint`` is the chaos harness's
        kill switch (``FaultPlan.wal_crashpoint()``).

        Crash-restart order matters: re-attach surviving kubelets
        (``FakeKubelet.attach_store``) BEFORE ``start()`` so controllers
        adopt the pods that outlived the crash instead of re-creating
        them — the informer-cache-sync-before-reconcile contract."""
        self.store = (
            Store.open(data_dir, crashpoint=wal_crashpoint)
            if data_dir is not None else Store())
        self._register_admission()
        self.scheduler = GangScheduler(self.store)
        self.controllers: list[Controller] = [JaxJobController(self.store)]
        self._started = False

    def _register_admission(self) -> None:
        register_default_admission(self.store)

    def add_controller(self, c: Controller) -> None:
        self.controllers.append(c)
        if self._started:
            c.start()

    def enable_serving(self) -> None:
        """Register the KServe-tier reconciler + the builtin ``tpu``
        ServingRuntimes (the north star's JAX/XLA runtime replacing the
        Triton/GPU path [local: BASELINE.json])."""
        from ..api.inference import ServingRuntime, ServingRuntimeSpec, SupportedModelFormat
        from ..serving.controller import InferenceServiceController

        for name, formats, server_class in (
            ("kft-echo", ["echo"], "kubeflow_tpu.serving.runtimes:EchoModel"),
            ("kft-jax", ["jax", "flax"], "kubeflow_tpu.serving.runtimes:JaxFunctionModel"),
            ("kft-llama", ["llama", "llm"], "kubeflow_tpu.serving.runtimes:LlamaGenerator"),
            ("kft-llama-continuous", ["llama-continuous"],
             "kubeflow_tpu.serving.continuous:ContinuousLlamaGenerator"),
            ("kft-text-llm", ["text-llm"],
             "kubeflow_tpu.serving.text:TextGenerator"),
            ("kft-bert", ["bert"], "kubeflow_tpu.serving.runtimes:BertClassifierModel"),
        ):
            try:
                self.store.create(
                    ServingRuntime(
                        metadata=ObjectMeta(name=name),
                        spec=ServingRuntimeSpec(
                            supported_model_formats=[
                                SupportedModelFormat(name=f) for f in formats
                            ],
                            server_class=server_class,
                        ),
                    )
                )
            except Exception:  # noqa: BLE001 — already registered
                pass
        self.add_controller(InferenceServiceController(self.store))
        from ..serving.graph import InferenceGraphController

        self.add_controller(InferenceGraphController(self.store))

    def enable_platform_ux(self) -> None:
        """Register the L7 shell tier (SURVEY.md §2.4): Profile multi-
        tenancy (quota enforced by the gang scheduler), Notebook workbenches,
        PodDefault injection.  The dashboard is ``serve_dashboard``."""
        from ..controlplane.objects import KIND_POD
        from ..ux.notebooks import NotebookController
        from ..ux.poddefaults import pod_default_mutator
        from ..ux.profiles import ProfileController

        self.store.register_admission(KIND_POD, mutate=pod_default_mutator(self.store))
        self.add_controller(ProfileController(self.store))
        self.add_controller(NotebookController(self.store))

    def serve_api(self, port: int = 0, token: "str | None" = None,
                  profile_tokens: "dict[str, str] | None" = None) -> str:
        """Start the REST API server (kube-apiserver analog) over this
        cluster's store; returns its URL for the kft CLI ($KFT_SERVER).
        Stopped with the cluster.  ``token`` (or $KFT_API_TOKEN) turns on
        admin bearer-token authn; ``profile_tokens`` (or $KFT_API_TOKENS,
        or Profile.spec.api_token) adds per-tenant identities whose
        mutations scope to their profile namespace (apiserver.py
        docstring)."""
        from .apiserver import ApiServer

        self._apiserver = ApiServer(
            self.store, port=port or None,
            profile_tokens=profile_tokens,
            log_path_for=getattr(self, "_log_path_for", None),
            token=token)
        return self._apiserver.url

    def serve_dashboard(self, port: int = 0) -> str:
        """Start the central dashboard over this cluster's store; returns
        its URL.  Stopped with the cluster.  When HPO is enabled the
        dashboard also gets the observation DB (experiment curves) and
        the pod-log resolver (log views)."""
        from ..ux.dashboard import Dashboard

        self._dashboard = Dashboard(
            self.store, port=port or None,
            db=getattr(self, "_db_client", None),
            log_path_for=getattr(self, "_log_path_for", None))
        return self._dashboard.url

    def enable_hpo(
        self,
        metrics_root: Optional[str] = None,
        log_path_for=None,
        db_path: Optional[str] = None,
    ) -> None:
        """Register the Katib-tier reconcilers (SURVEY.md §2.3).  Separate
        from __init__ because the trial metrics collector needs the kubelet's
        filesystem layout, which only the platform knows.

        ``db_path`` (defaulting to ``<metrics_root>/observations.sqlite``
        when a metrics root exists) stands up the durable observation store
        behind its gRPC facade (hpo/db.py, the katib-db-manager analog):
        completed-trial history then survives control-plane restarts."""
        import os

        from ..hpo.controllers import (
            ExperimentController,
            SuggestionController,
            TrialController,
        )
        from ..hpo.db import DbManagerClient, DbManagerServer

        self._log_path_for = log_path_for  # also feeds dashboard + apiserver
        dashboard = getattr(self, "_dashboard", None)
        if dashboard is not None:
            # dashboard started before HPO: hand it the log source now
            dashboard.log_path_for = log_path_for
        apiserver = getattr(self, "_apiserver", None)
        if apiserver is not None:
            apiserver.log_path_for = log_path_for  # kft logs
        if db_path is None and metrics_root is not None:
            db_path = os.path.join(metrics_root, "observations.sqlite")
        db_client = None
        if db_path is not None:
            self._db_server = DbManagerServer(db_path).start()
            db_client = self._db_client = DbManagerClient(self._db_server.address)
            if dashboard is not None:
                dashboard.db = db_client

        self.add_controller(ExperimentController(self.store, db=db_client))
        self.add_controller(SuggestionController(self.store, db=db_client))
        self.add_controller(
            TrialController(
                self.store, metrics_root=metrics_root,
                log_path_for=log_path_for, db=db_client,
            )
        )

    def add_node(
        self,
        name: str,
        cpu: float = 64.0,
        memory_gb: float = 128.0,
        tpu: int = 0,
        slice_id: str = "slice-0",
    ) -> Node:
        node = Node(
            metadata=ObjectMeta(name=name),
            spec=NodeSpec(
                capacity={"cpu": cpu, "memory_gb": memory_gb, "tpu": float(tpu)},
                slice_id=slice_id,
            ),
        )
        created = self.store.create(node)
        assert isinstance(created, Node)
        return created

    def add_tpu_slice(
        self, slice_id: str, num_hosts: int, chips_per_host: int = 4
    ) -> list[Node]:
        """Model a TPU pod slice: ``num_hosts`` VMs sharing ICI, each exposing
        ``chips_per_host`` chips (v5e default: 4 chips/VM, so v5e-16 = 4 hosts)."""
        return [
            self.add_node(
                f"{slice_id}-host-{i}",
                tpu=chips_per_host,
                slice_id=slice_id,
            )
            for i in range(num_hosts)
        ]

    def metrics_text(self) -> str:
        """Prometheus exposition for every reconciler (the manager's
        ``--metrics-bind-address`` surface [upstream: training-operator
        cmd/training-operator.v1/main.go])."""
        parts = [
            "# TYPE kft_reconcile_total counter",
            "# TYPE kft_reconcile_errors_total counter",
            "# TYPE kft_reconcile_time_seconds histogram",
            "# TYPE kft_workqueue_depth gauge",
        ]
        for c in self.controllers:
            parts.append(c.metrics.prometheus(len(c.queue)).rstrip("\n"))
        return "\n".join(parts) + "\n"

    def serve_metrics(self, port: int = 0) -> str:
        """Expose ``/metrics`` over HTTP; returns the bound URL."""
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ..utils.net import allocate_port

        cluster = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                if self.path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = cluster.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        port = port or allocate_port()
        self._metrics_httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._metrics_httpd.daemon_threads = True
        threading.Thread(
            target=self._metrics_httpd.serve_forever,
            name="cluster-metrics", daemon=True,
        ).start()
        return f"http://127.0.0.1:{port}/metrics"

    def start(self) -> None:
        self.scheduler.start()
        for c in self.controllers:
            c.start()
        self._started = True

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()
        self.scheduler.stop()
        if getattr(self, "_metrics_httpd", None) is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd.server_close()
            self._metrics_httpd = None
        if getattr(self, "_dashboard", None) is not None:
            self._dashboard.stop()
            self._dashboard = None
        if getattr(self, "_apiserver", None) is not None:
            self._apiserver.stop()
            self._apiserver = None
        if getattr(self, "_db_client", None) is not None:
            self._db_client.close()
            self._db_client = None
        if getattr(self, "_db_server", None) is not None:
            self._db_server.stop()
            self._db_server = None
        self.store.close()  # flush + detach the WAL (no-op in-memory)
        self._started = False

    def __enter__(self) -> "Cluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
