"""Gang scheduler: all-or-nothing admission of PodGroups onto Nodes.

The Volcano analog [upstream: volcano-sh/volcano; SURVEY.md §1 L1]: pods
carrying a ``group-name`` annotation stay Pending until *every* member of
their PodGroup (>= ``min_member``) fits the cluster simultaneously; then the
whole gang binds atomically.  Non-gang pods (``scheduler_name: default``)
bind individually, best-fit.  This is where gang-startup latency is born
(SURVEY.md §3.1 step 4), so admission timestamps feed the baseline metric.

TPU-specific placement rule: pods requesting ``tpu`` chips are packed
slice-first — all members of one gang land on nodes of as few slices as
possible (ICI before DCN), recorded on the PodGroup for the mesh planner.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

log = logging.getLogger("kubeflow_tpu.scheduler")

from .objects import (
    GROUP_NAME_ANNOTATION,
    KIND_NODE,
    KIND_POD,
    KIND_PODGROUP,
    Node,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    pod_resources,
)
from .store import NotFound, Store

RESOURCE_KEYS = ("cpu", "memory_gb", "tpu")


def _fits(need: dict[str, float], free: dict[str, float]) -> bool:
    return all(need.get(k, 0.0) <= free.get(k, 0.0) + 1e-9 for k in RESOURCE_KEYS)


def _quota_fits(need: dict[str, float], quota: dict[str, float]) -> bool:
    """A quota constrains only the resources it names (upstream
    ResourceQuota semantics)."""
    return all(need.get(k, 0.0) <= v + 1e-9 for k, v in quota.items())


def _quota_sub(quota: dict[str, float], need: dict[str, float]) -> None:
    """Subtract usage from the resources the quota names — ONLY those, or
    unnamed resources would accumulate negative phantom limits."""
    for k in list(quota):
        quota[k] -= need.get(k, 0.0)


def _sub(free: dict[str, float], need: dict[str, float]) -> None:
    for k in RESOURCE_KEYS:
        free[k] = free.get(k, 0.0) - need.get(k, 0.0)


class GangScheduler:
    """One scheduling pass = ``schedule_once``; ``run`` loops it in a thread."""

    def __init__(self, store: Store, interval: float = 0.02) -> None:
        self.store = store
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="gang-scheduler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.schedule_once()
            except Exception:  # noqa: BLE001 — scheduler must survive races
                log.exception("scheduling pass failed")
            self._stop.wait(self.interval)

    # -- core ------------------------------------------------------------------

    def _free_by_node(self) -> dict[str, dict[str, float]]:
        nodes = {n.metadata.name: dict(n.spec.capacity) for n in self.store.list(KIND_NODE)}
        for pod in self.store.list(KIND_POD):
            assert isinstance(pod, Pod)
            if pod.spec.node_name and not pod.terminal:
                if pod.spec.node_name in nodes:
                    _sub(nodes[pod.spec.node_name], pod_resources(pod))
        return nodes

    def _node_order(self, nodes: dict[str, dict[str, float]]) -> list[str]:
        """Slice-first order: group node names by slice so a gang packs one
        slice before spilling to the next (ICI-before-DCN placement)."""
        slice_of: dict[str, str] = {}
        for n in self.store.list(KIND_NODE):
            assert isinstance(n, Node)
            slice_of[n.metadata.name] = n.spec.slice_id
        return sorted(nodes, key=lambda name: (slice_of.get(name, ""), name))

    def _quota_left(self) -> dict[str, dict[str, float]]:
        """Tenant namespace -> remaining Profile quota (SURVEY §2.4: the
        ResourceQuota capability, enforced here so gangs stay atomic)."""
        from ..api.platform import KIND_PROFILE, Profile

        left: dict[str, dict[str, float]] = {}
        for prof in self.store.list(KIND_PROFILE):
            if isinstance(prof, Profile) and prof.spec.resource_quota:
                left[prof.metadata.name] = dict(prof.spec.resource_quota)
        if not left:
            return left
        for pod in self.store.list(KIND_POD):
            assert isinstance(pod, Pod)
            if (
                pod.spec.node_name
                and not pod.terminal
                and pod.metadata.namespace in left
            ):
                _quota_sub(left[pod.metadata.namespace], pod_resources(pod))
        return left

    def _bind(self, pod: Pod, node_name: str) -> None:
        def mut(o):
            assert isinstance(o, Pod)
            o.spec.node_name = node_name

        self.store.update_with_retry(KIND_POD, pod.metadata.name, pod.metadata.namespace, mut)

    def schedule_once(self) -> int:
        """Returns the number of pods bound this pass."""
        free = self._free_by_node()
        order = self._node_order(free)
        quota = self._quota_left()
        bound = 0

        all_pods = [p for p in self.store.list(KIND_POD) if isinstance(p, Pod)]
        pending = [
            p for p in all_pods if p.status.phase == PodPhase.PENDING and not p.spec.node_name
        ]
        # live gang membership counts bound members too, so a single
        # recreated member of an already-admitted gang still schedules
        live_members: dict[str, int] = {}
        for p in all_pods:
            group = p.metadata.annotations.get(GROUP_NAME_ANNOTATION)
            if group and not p.terminal:
                key = f"{p.metadata.namespace}/{group}"
                live_members[key] = live_members.get(key, 0) + 1

        # --- gang pods, grouped -------------------------------------------------
        gangs: dict[str, list[Pod]] = {}
        singles: list[Pod] = []
        for p in pending:
            group = p.metadata.annotations.get(GROUP_NAME_ANNOTATION)
            if p.spec.scheduler_name == "gang" and group:
                gangs.setdefault(f"{p.metadata.namespace}/{group}", []).append(p)
            else:
                singles.append(p)

        for group_key, pods in sorted(gangs.items()):
            ns, gname = group_key.split("/", 1)
            try:
                pg = self.store.get(KIND_PODGROUP, gname, ns)
            except NotFound:
                continue  # controller hasn't created the group yet
            assert isinstance(pg, PodGroup)
            if live_members.get(group_key, 0) < pg.spec.min_member:
                continue  # gang not fully materialized yet
            if ns in quota:
                need_total: dict[str, float] = {}
                for p in pods:
                    for k, v in pod_resources(p).items():
                        need_total[k] = need_total.get(k, 0.0) + v
                if not _quota_fits(need_total, quota[ns]):
                    self._set_group_phase(
                        pg, PodGroupPhase.PENDING,
                        f"profile quota exceeded in namespace {ns}")
                    continue
            placement = self._plan_gang(pods, free, order)
            if placement is None:
                self._set_group_phase(pg, PodGroupPhase.PENDING, "insufficient capacity")
                continue
            for pod, node_name in placement:
                self._bind(pod, node_name)
                _sub(free[node_name], pod_resources(pod))
                if ns in quota:
                    _quota_sub(quota[ns], pod_resources(pod))
                bound += 1
            self._set_group_phase(pg, PodGroupPhase.RUNNING, "gang admitted")

        # --- singles ------------------------------------------------------------
        for pod in singles:
            need = pod_resources(pod)
            ns = pod.metadata.namespace
            if ns in quota and not _quota_fits(need, quota[ns]):
                continue  # over profile quota: stays Pending
            for node_name in order:
                if _fits(need, free[node_name]):
                    self._bind(pod, node_name)
                    _sub(free[node_name], need)
                    if ns in quota:
                        _quota_sub(quota[ns], need)
                    bound += 1
                    break
        return bound

    def _plan_gang(
        self,
        pods: list[Pod],
        free: dict[str, dict[str, float]],
        order: list[str],
    ) -> Optional[list[tuple[Pod, str]]]:
        """All-or-nothing placement over a *copy* of the free map."""
        trial = {n: dict(f) for n, f in free.items()}
        placement: list[tuple[Pod, str]] = []
        for pod in sorted(pods, key=lambda p: p.metadata.name):
            need = pod_resources(pod)
            target = next((n for n in order if _fits(need, trial[n])), None)
            if target is None:
                return None
            _sub(trial[target], need)
            placement.append((pod, target))
        return placement

    def _set_group_phase(self, pg: PodGroup, phase: PodGroupPhase, msg: str) -> None:
        if pg.status.phase == phase and pg.status.message == msg:
            return

        def mut(o):
            assert isinstance(o, PodGroup)
            o.status.phase = phase
            o.status.message = msg
            if phase == PodGroupPhase.RUNNING and o.status.admitted_time is None:
                o.status.admitted_time = time.time()

        try:
            self.store.update_with_retry(
                KIND_PODGROUP, pg.metadata.name, pg.metadata.namespace, mut
            )
        except NotFound:
            pass
