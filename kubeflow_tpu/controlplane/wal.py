"""Write-ahead log + snapshot persistence for the object store.

The etcd analog (ISSUE 5 tentpole): every store mutation appends one
CRC-tagged JSONL record keyed by ``resourceVersion`` before it is
visible to watchers, so a control-plane kill -9 recovers to a
consistent recent state instead of total amnesia.  Recovery semantics
follow the etcd/raft-log playbook:

- **torn tail tolerated**: a record cut mid-write by the crash (bad
  CRC, truncated line, missing newline) at the very END of the log is
  dropped and the file truncated back to the last good record — that
  write was never acknowledged as durable;
- **mid-log corruption is fatal**: a bad CRC with valid records AFTER
  it means the medium lied, not that a write was interrupted; replay
  raises :class:`WalCorrupt` loudly rather than silently skipping
  committed history;
- **batched fsync**: appends buffer and fsync every ``fsync_every``
  records or ``fsync_interval_s`` seconds, whichever first — the
  durability window is bounded and explicit (records inside it are the
  ones a crash may lose);
- **snapshot + compaction**: every ``snapshot_every`` records the full
  object set is written to ``snapshot.json`` (tmp-file + fsync +
  atomic rename) and the log truncated; replay = snapshot + records
  with ``rv`` greater than the snapshot's.

The :class:`WalCrashPoint` seam is the chaos layer's kill switch
(:meth:`~kubeflow_tpu.chaos.FaultPlan.control_plane_crash`): once
``after_records`` records have been appended, the WAL behaves like the
machine died at that exact offset — nothing later reaches disk, and at
most ``torn_bytes`` of the next record do (a torn tail for recovery to
chew on).  The in-process threads keep running until the harness tears
them down, exactly like in-flight work on a node that lost its API
server; none of it persists.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger("kubeflow_tpu.wal")

LOG_NAME = "wal.jsonl"
SNAP_NAME = "snapshot.json"

OP_PUT = "put"
OP_DEL = "del"


class WalError(Exception):
    pass


class WalCorrupt(WalError):
    """A record that is NOT the tail failed its CRC/format check —
    committed history is damaged and replay must not guess around it."""


@dataclass
class WalCrashPoint:
    """Simulated kill -9 at a WAL offset (see module docstring)."""

    after_records: int
    torn_bytes: int = 0
    fired: threading.Event = field(default_factory=threading.Event)


def _encode(payload: dict) -> bytes:
    body = json.dumps(payload, separators=(",", ":"), sort_keys=True)
    return f"{zlib.crc32(body.encode()):08x} {body}\n".encode()


def _decode(raw: bytes) -> Optional[dict]:
    """Parse one CRC-tagged record line (without newline); None if the
    bytes do not form a complete valid record."""
    try:
        text = raw.decode()
        crc_hex, _, body = text.partition(" ")
        if len(crc_hex) != 8 or not body:
            return None
        if int(crc_hex, 16) != zlib.crc32(body.encode()):
            return None
        rec = json.loads(body)
        return rec if isinstance(rec, dict) else None
    except (ValueError, UnicodeDecodeError):
        return None


class Wal:
    """Append-only JSONL log + snapshot for one :class:`Store`.

    Thread-safe; the store appends under its own lock, and the WAL's
    ``Wal._lock`` serializes the file write + batched fsync (the
    acquisition order is always ``Store._lock`` -> ``Wal._lock``; the
    WAL never calls back into the store)."""

    def __init__(
        self,
        data_dir: str,
        fsync_every: int = 64,
        fsync_interval_s: float = 0.05,
        snapshot_every: int = 1024,
        crashpoint: Optional[WalCrashPoint] = None,
    ) -> None:
        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        self.log_path = os.path.join(data_dir, LOG_NAME)
        self.snap_path = os.path.join(data_dir, SNAP_NAME)
        self.fsync_every = max(1, fsync_every)
        self.fsync_interval_s = fsync_interval_s
        self.snapshot_every = max(1, snapshot_every)
        self.crashpoint = crashpoint
        self._lock = threading.Lock()
        self._f: Optional[Any] = None
        self._unsynced = 0
        self._last_fsync = time.monotonic()
        #: records appended since the last snapshot (compaction trigger)
        self.records_since_snapshot = 0
        #: records appended this incarnation (the crashpoint's clock)
        self.appended_records = 0
        #: the simulated machine death happened: drop every later write
        self.crashed = False
        self.closed = False

    # -- recovery ----------------------------------------------------------

    def recover(self) -> tuple[int, list[dict], list[dict]]:
        """Read snapshot + log, truncate a torn tail, open for append.

        Returns ``(snapshot_rv, snapshot_objs, records)`` where
        ``records`` are the valid log records (the caller filters to
        ``rv > snapshot_rv`` — a crash between snapshot rename and log
        truncation legitimately leaves older records behind)."""
        # a crash mid-snapshot leaves only the tmp file; the rename is
        # atomic, so snapshot.json is either the old complete one or the
        # new complete one — tmp leftovers are garbage
        tmp = self.snap_path + ".tmp"
        if os.path.exists(tmp):
            os.unlink(tmp)
        snap_rv, snap_objs = 0, []
        if os.path.exists(self.snap_path):
            with open(self.snap_path, encoding="utf-8") as f:
                try:
                    snap = json.load(f)
                except ValueError as e:
                    # snapshots are written atomically; a half snapshot
                    # cannot exist, so a bad one is real corruption
                    raise WalCorrupt(f"snapshot {self.snap_path}: {e}") from e
            snap_rv = int(snap.get("rv", 0))
            snap_objs = snap.get("objs", [])
        records = self._read_log()
        # the reopened log's backlog counts toward the next compaction —
        # otherwise a plane restarted every < snapshot_every writes never
        # snapshots and the log grows without bound across incarnations
        self.records_since_snapshot = len(records)
        self._open_for_append()
        return snap_rv, snap_objs, records

    def _read_log(self) -> list[dict]:
        if not os.path.exists(self.log_path):
            return []
        with open(self.log_path, "rb") as f:
            data = f.read()
        records: list[dict] = []
        offset = 0  # byte offset of the first unparsed record
        good_end = 0  # byte offset just past the last valid record
        while offset < len(data):
            nl = data.find(b"\n", offset)
            chunk = data[offset:nl] if nl >= 0 else data[offset:]
            rec = _decode(chunk) if nl >= 0 else None  # no newline = torn
            if rec is None:
                # bad record: tolerable ONLY as the file's tail (a write
                # the crash cut short was never acknowledged durable)
                rest = data[offset:] if nl < 0 else data[nl + 1:]
                if nl >= 0 and rest.strip(b"\n"):
                    raise WalCorrupt(
                        f"{self.log_path}: corrupt record at byte {offset} "
                        "with committed records after it")
                log.warning("wal %s: dropping torn tail record (%d bytes)",
                            self.log_path, len(data) - offset)
                with open(self.log_path, "r+b") as f:
                    f.truncate(good_end)
                    f.flush()
                    os.fsync(f.fileno())
                break
            records.append(rec)
            offset = nl + 1
            good_end = offset
        return records

    def _open_for_append(self) -> None:
        # append-only log: a torn tail is the DESIGNED crash artifact —
        # replay() truncates to the last newline-complete record (the
        # repair path the chaos tests pin)
        # analysis: ok torn-write — torn tail repaired on replay
        self._f = open(self.log_path, "ab")

    # -- append path -------------------------------------------------------

    def append(self, payload: dict) -> None:
        """Append one record; fsync per the batch policy.  After a
        simulated crash this silently drops writes (the process is
        'dead'; its survivors stop at teardown)."""
        line = _encode(payload)
        with self._lock:
            if self.crashed or self.closed or self._f is None:
                return
            cp = self.crashpoint
            if cp is not None and self.appended_records >= cp.after_records:
                # the machine dies HERE: at most torn_bytes of this
                # record reach the platter, nothing ever again — clamped
                # below the record length, or a generous torn_bytes would
                # persist the COMPLETE record the model says died in flight
                if cp.torn_bytes > 0:
                    self._f.write(line[: min(cp.torn_bytes, len(line) - 1)])
                    self._f.flush()
                    os.fsync(self._f.fileno())
                self.crashed = True
                cp.fired.set()
                return
            self._f.write(line)
            self.appended_records += 1
            self.records_since_snapshot += 1
            self._unsynced += 1
            now = time.monotonic()
            if (self._unsynced >= self.fsync_every
                    or now - self._last_fsync >= self.fsync_interval_s):
                # the batched fsync under the append lock IS the
                # durability contract: no writer may observe an append
                # as accepted before its batch boundary is on disk
                # analysis: ok lock-blocking-call — batched-fsync contract
                self._fsync_locked(now)

    def _fsync_locked(self, now: Optional[float] = None) -> None:
        assert self._f is not None
        self._f.flush()
        os.fsync(self._f.fileno())
        self._unsynced = 0
        self._last_fsync = time.monotonic() if now is None else now

    def sync(self) -> None:
        """Force the batched fsync (clean shutdown / test determinism)."""
        with self._lock:
            if self._f is not None and not self.crashed and self._unsynced:
                # analysis: ok lock-blocking-call — forced flush of the batched-fsync contract
                self._fsync_locked()

    # -- snapshot + compaction ---------------------------------------------

    def write_snapshot(self, rv: int, objs: list[dict]) -> None:
        """Write the full object set and truncate the log.  The caller
        (the store, under its lock) guarantees ``objs`` is consistent
        with every record appended so far."""
        with self._lock:
            if self.crashed or self.closed or self._f is None:
                return
            tmp = self.snap_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"rv": rv, "objs": objs}, f,
                          separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            # log truncation AFTER the snapshot is durable: a crash
            # between the two leaves snapshot + stale records, which
            # replay filters by rv
            self._f.close()
            # analysis: ok torn-write — truncate after durable snapshot; replay filters stale records by rv
            self._f = open(self.log_path, "wb")
            self._unsynced = 0
            self.records_since_snapshot = 0

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            self.closed = True
            if self._f is None:
                return
            if not self.crashed:
                self._f.flush()
                os.fsync(self._f.fileno())
            self._f.close()
            self._f = None
