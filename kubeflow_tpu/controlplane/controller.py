"""Controller runtime: watch-driven workqueue + reconcile loop.

The controller-runtime analog [upstream: kubernetes-sigs/controller-runtime,
as consumed by kubeflow/training-operator]: watch events enqueue object keys
into a deduplicating workqueue; worker threads pop keys and call
``reconcile(key)``; a reconcile may request requeue-after; errors requeue
with exponential backoff.  Controllers also watch *owned* kinds (pods,
services) and map those events back to the owner's key, exactly the
``Owns(...)`` wiring in the reference's ``SetupWithManager``.
"""

from __future__ import annotations

import heapq
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from ..api.common import TypedObject
from .objects import Event, KIND_EVENT
from .store import DELETED, TOO_OLD, Store, WatchEvent

log = logging.getLogger("kubeflow_tpu.controlplane")


@dataclass(order=True)
class _QueueItem:
    at: float
    key: str = field(compare=False)


class WorkQueue:
    """Deduplicating delay queue (client-go workqueue analog).

    In-flight dedup, client-go style: a key handed to a worker is
    *processing* until ``done(key)``; adds for it meanwhile are parked
    (dirty-set) and re-queued at ``done``.  Without this, two workers of
    one controller can reconcile the SAME key concurrently and double-
    apply a transition (e.g. two restart_count bumps for one gang
    failure — the storm chaos testing surfaced, ISSUE 1)."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._heap: list[_QueueItem] = []
        #: key -> earliest scheduled fire time among queued entries; an add
        #: only dedups against an entry that would fire sooner-or-equal, so
        #: an immediate add always tightens a far-future TTL requeue
        #: (client-go Add vs AddAfter semantics).
        self._queued: dict[str, float] = {}
        #: keys currently held by a worker (get() .. done())
        self._processing: set[str] = set()
        #: key -> earliest re-add time requested while processing
        self._dirty: dict[str, float] = {}

    def add(self, key: str, delay: float = 0.0) -> None:
        at = time.time() + delay
        with self._lock:
            if key in self._processing:
                cur = self._dirty.get(key)
                if cur is None or at < cur:
                    self._dirty[key] = at
                return
            earliest = self._queued.get(key)
            if earliest is not None and earliest <= at:
                return
            heapq.heappush(self._heap, _QueueItem(at, key))
            self._queued[key] = at
            self._lock.notify()

    def get(self, timeout: float = 0.2) -> Optional[str]:
        with self._lock:
            deadline = time.time() + timeout
            while True:
                now = time.time()
                popped = None
                if self._heap and self._heap[0].at <= now:
                    popped = heapq.heappop(self._heap)
                    remaining = [it.at for it in self._heap if it.key == popped.key]
                    if remaining:
                        self._queued[popped.key] = min(remaining)
                    else:
                        self._queued.pop(popped.key, None)
                    if popped.key in self._processing:
                        # another worker holds this key: park it dirty
                        cur = self._dirty.get(popped.key)
                        if cur is None or popped.at < cur:
                            self._dirty[popped.key] = popped.at
                        continue
                    self._processing.add(popped.key)
                    return popped.key
                wait = min(
                    self._heap[0].at - now if self._heap else timeout,
                    deadline - now,
                )
                if wait <= 0:
                    return None
                self._lock.wait(wait)

    def done(self, key: str) -> None:
        """Worker finished ``key``: release it and re-queue any add that
        arrived while it was processing."""
        with self._lock:
            self._processing.discard(key)
            at = self._dirty.pop(key, None)
            if at is None:
                return
            earliest = self._queued.get(key)
            if earliest is not None and earliest <= at:
                return
            heapq.heappush(self._heap, _QueueItem(at, key))
            self._queued[key] = at
            self._lock.notify()

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)


@dataclass
class Result:
    requeue_after: Optional[float] = None


class ReconcileMetrics:
    """Per-controller reconcile metrics, Prometheus text exposition.

    The controller-runtime metrics surface [upstream: controller-runtime ->
    pkg/internal/controller/metrics: controller_runtime_reconcile_total,
    _errors_total, _time_seconds, workqueue depth], which the reference
    operators export on ``--metrics-bind-address`` (SURVEY.md §5 tracing).
    """

    #: reconcile-duration histogram upper bounds (seconds)
    BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0)

    def __init__(self, controller: str) -> None:
        self.controller = controller
        self._lock = threading.Lock()
        self.total = 0
        self.errors = 0
        self.duration_sum = 0.0
        self.bucket_counts = [0] * (len(self.BUCKETS) + 1)  # +inf tail

    def observe(self, seconds: float, error: bool) -> None:
        with self._lock:
            self.total += 1
            if error:
                self.errors += 1
            self.duration_sum += seconds
            for i, ub in enumerate(self.BUCKETS):
                if seconds <= ub:
                    self.bucket_counts[i] += 1
                    break
            else:
                self.bucket_counts[-1] += 1

    def prometheus(self, queue_depth: int) -> str:
        lab = f'controller="{self.controller}"'
        with self._lock:
            lines = [
                f"kft_reconcile_total{{{lab}}} {self.total}",
                f"kft_reconcile_errors_total{{{lab}}} {self.errors}",
                f"kft_reconcile_time_seconds_sum{{{lab}}} {self.duration_sum:.6f}",
                f"kft_reconcile_time_seconds_count{{{lab}}} {self.total}",
            ]
            cum = 0
            for ub, c in zip(self.BUCKETS, self.bucket_counts):
                cum += c
                lines.append(
                    f'kft_reconcile_time_seconds_bucket{{{lab},le="{ub}"}} {cum}')
            cum += self.bucket_counts[-1]
            lines.append(
                f'kft_reconcile_time_seconds_bucket{{{lab},le="+Inf"}} {cum}')
        lines.append(f"kft_workqueue_depth{{{lab}}} {queue_depth}")
        return "\n".join(lines) + "\n"


class Controller:
    """Base reconciler.  Subclasses set ``kind``, ``owned_kinds`` and
    implement ``reconcile(namespace, name) -> Optional[Result]``."""

    kind: str = ""
    owned_kinds: tuple[str, ...] = ()
    workers: int = 1

    def __init__(self, store: Store) -> None:
        self.store = store
        self.queue = WorkQueue()
        self.metrics = ReconcileMetrics(self.kind or type(self).__name__)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._watch = None
        self._backoff: dict[str, float] = {}

    # -- event -> key mapping --------------------------------------------------

    def owner_key_for(self, obj: TypedObject) -> Optional[str]:
        """Map an owned object's event to its controller's key via
        owner_references (the ``Owns()`` handler)."""
        for ref in obj.metadata.owner_references:
            if ref.kind == self.kind and ref.controller:
                return f"{obj.metadata.namespace}/{ref.name}"
        return None

    def observe(self, ev: WatchEvent) -> None:
        """Hook for expectation accounting; called for every owned event."""

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        kinds = (self.kind, *self.owned_kinds)
        self._watch = self.store.watch(kinds)
        self._prime()
        t = threading.Thread(target=self._watch_loop, name=f"{self.kind}-watch", daemon=True)
        t.start()
        self._threads.append(t)
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker_loop, name=f"{self.kind}-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._watch is not None:
            self.store.stop_watch(self._watch)
        for t in self._threads:
            t.join(timeout=5)

    def _prime(self) -> None:
        """Informer initial list: enqueue every existing object of our
        kind, AND the owner key of every existing owned object — after a
        control-plane restart an owned pod whose job is gone must still
        trigger a reconcile (orphan cleanup), and one whose job survived
        must be adopted, even though neither produces a watch event."""
        for obj in self.store.list(self.kind):
            self.queue.add(obj.key)
        for kind in self.owned_kinds:
            for obj in self.store.list(kind):
                key = self.owner_key_for(obj)
                if key:
                    self.queue.add(key)

    def _resync(self) -> None:
        """The watch overflowed (TOO_OLD): events were dropped and the
        ONLY correct recovery is a fresh watch + full relist — never
        resuming as if nothing was missed.  New watch FIRST, then list,
        so nothing lands in the gap between the two."""
        kinds = (self.kind, *self.owned_kinds)
        self._watch = self.store.watch(kinds)
        self._prime()

    def _watch_loop(self) -> None:
        assert self._watch is not None
        while not self._stop.is_set():
            try:
                ev = self._watch.q.get(timeout=0.2)
            except queue.Empty:
                continue
            if ev.type == TOO_OLD:
                log.warning("%s watch fell behind; relisting", self.kind)
                self._resync()
                continue
            assert ev.obj is not None
            if ev.obj.kind == self.kind:
                self.queue.add(ev.obj.key)
            else:
                self.observe(ev)
                key = self.owner_key_for(ev.obj)
                if key:
                    self.queue.add(key)

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            ns, name = key.split("/", 1)
            t0 = time.perf_counter()
            try:
                res = self.reconcile(ns, name)
            except Exception:  # noqa: BLE001
                self.metrics.observe(time.perf_counter() - t0, error=True)
                log.exception("reconcile %s %s failed", self.kind, key)
                back = min(self._backoff.get(key, 0.05) * 2, 5.0)
                self._backoff[key] = back
                self.queue.done(key)
                self.queue.add(key, delay=back)
                continue
            self.metrics.observe(time.perf_counter() - t0, error=False)
            self._backoff.pop(key, None)
            # release BEFORE the requeue so the requeue lands in the heap,
            # not the dirty set (watch events that arrived mid-reconcile
            # are flushed by done() as well)
            self.queue.done(key)
            if res and res.requeue_after is not None:
                self.queue.add(key, delay=res.requeue_after)

    # -- to implement ----------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        raise NotImplementedError

    # -- events (kubectl describe UX) -----------------------------------------

    def emit_event(
        self, obj: TypedObject, reason: str, message: str, type_: str = "Normal"
    ) -> None:
        from ..api.common import ObjectMeta

        name = f"{obj.metadata.name}-{reason.lower()}-{int(time.time() * 1000) % 1_000_000}"
        try:
            self.store.create(
                Event(
                    metadata=ObjectMeta(name=name, namespace=obj.metadata.namespace),
                    involved_kind=obj.kind,
                    involved_name=obj.metadata.name,
                    reason=reason,
                    message=message,
                    type=type_,
                )
            )
        except Exception:  # noqa: BLE001 — events are best-effort
            pass


def events_for(store: Store, kind: str, name: str) -> list[Event]:
    return sorted(
        (
            e
            for e in store.list(KIND_EVENT)
            if isinstance(e, Event) and e.involved_kind == kind and e.involved_name == name
        ),
        key=lambda e: e.timestamp,
    )
