"""Core (non-CRD) cluster objects: Node, Pod, Service, PodGroup, Event.

These are the Kubernetes primitives the reference's reconcilers emit
[upstream: kubeflow/training-operator -> pkg/controller.v1/common/{pod,service}.go;
volcano-sh/volcano -> PodGroup CRD].  The in-process cluster (SURVEY.md §4's
envtest analog) stores them in the same typed store as the CRDs; the gang
scheduler binds Pods to Nodes; the process runtime plays kubelet.
"""

from __future__ import annotations

import enum
import time
from typing import Optional

from pydantic import Field

from ..api.common import Container, TypedObject, _Model

KIND_POD = "Pod"
KIND_SERVICE = "Service"
KIND_PODGROUP = "PodGroup"
KIND_NODE = "Node"
KIND_EVENT = "Event"

#: Pod annotation naming its gang [reference analog: the
#: ``scheduling.k8s.io/group-name`` annotation Volcano keys on].
GROUP_NAME_ANNOTATION = "scheduling.kubeflow-tpu.dev/group-name"
#: Label keys the controllers stamp on pods for selector queries
#: [reference analog: training.kubeflow.org/job-name etc.].
LABEL_JOB_NAME = "job-name"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_REPLICA_INDEX = "replica-index"


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class PodSpec(_Model):
    container: Container = Field(default_factory=Container)
    node_name: Optional[str] = None  # set by the scheduler (binding)
    scheduler_name: str = "gang"  # "gang" | "default"
    restart_policy: str = "Never"


class PodStatus(_Model):
    phase: PodPhase = PodPhase.PENDING
    exit_code: Optional[int] = None
    message: str = ""
    start_time: Optional[float] = None
    finish_time: Optional[float] = None
    # Wall-clock when the in-pod runtime reported passing its first
    # collective barrier — source for the gang-startup metric.
    barrier_time: Optional[float] = None
    # Wall-clock of the pod's last self-reported activity heartbeat
    # (status-dir ``activity`` file) — the notebook culler's signal.
    last_activity: Optional[float] = None
    pid: Optional[int] = None


class Pod(TypedObject):
    kind: str = KIND_POD
    spec: PodSpec = Field(default_factory=PodSpec)
    status: PodStatus = Field(default_factory=PodStatus)

    @property
    def terminal(self) -> bool:
        return self.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)


class ServiceSpec(_Model):
    """Headless service: stable DNS for one pod [upstream:
    training-operator -> pkg/controller.v1/common/service.go]."""

    selector: dict[str, str] = Field(default_factory=dict)
    ports: list[int] = Field(default_factory=list)
    cluster_ip: Optional[str] = None  # None == headless


class Service(TypedObject):
    kind: str = KIND_SERVICE
    spec: ServiceSpec = Field(default_factory=ServiceSpec)


class PodGroupPhase(str, enum.Enum):
    PENDING = "Pending"
    INQUEUE = "Inqueue"
    RUNNING = "Running"  # admitted: all min_member pods bound
    UNSCHEDULABLE = "Unschedulable"


class PodGroupSpec(_Model):
    min_member: int = 1
    queue: str = "default"
    priority_class: Optional[str] = None
    # aggregate resources the gang needs (for all-or-nothing fit checks)
    min_resources: dict[str, float] = Field(default_factory=dict)


class PodGroupStatus(_Model):
    phase: PodGroupPhase = PodGroupPhase.PENDING
    admitted_time: Optional[float] = None
    message: str = ""


class PodGroup(TypedObject):
    kind: str = KIND_PODGROUP
    spec: PodGroupSpec = Field(default_factory=PodGroupSpec)
    status: PodGroupStatus = Field(default_factory=PodGroupStatus)


class NodeSpec(_Model):
    capacity: dict[str, float] = Field(default_factory=dict)  # cpu/memory_gb/tpu
    labels: dict[str, str] = Field(default_factory=dict)
    # TPU slice wiring: nodes in the same slice share ICI; different slices
    # talk over DCN.  Used by the mesh planner's axis-placement policy.
    slice_id: str = "slice-0"


class Node(TypedObject):
    kind: str = KIND_NODE
    spec: NodeSpec = Field(default_factory=NodeSpec)


class Event(TypedObject):
    kind: str = KIND_EVENT
    involved_kind: str = ""
    involved_name: str = ""
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    timestamp: float = Field(default_factory=time.time)


def pod_resources(pod: Pod) -> dict[str, float]:
    r = pod.spec.container.resources
    return {"cpu": r.cpu, "memory_gb": r.memory_gb, "tpu": float(r.tpu)}


# Make the cluster-substrate kinds YAML/REST-addressable (the api layer's
# KIND_REGISTRY must not import upward, so registration happens here).
from ..api.yaml_io import KIND_REGISTRY as _KIND_REGISTRY  # noqa: E402

_KIND_REGISTRY.update({
    KIND_POD: Pod,
    KIND_SERVICE: Service,
    KIND_PODGROUP: PodGroup,
    KIND_NODE: Node,
    KIND_EVENT: Event,
})
