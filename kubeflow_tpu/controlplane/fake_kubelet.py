"""FakeKubelet: drives bound pods through phases without real processes.

The envtest gap-filler (SURVEY.md §4): upstream controller tests create pods
that never run because envtest has no kubelet; gang-startup latency and
restart policies then go untested.  This kubelet simulator runs bound pods to
a scripted outcome (success, exit code, hang) so reconciler + scheduler
behavior — including failure/restart paths — is testable deterministically.
The *real* kubelet is ``kubeflow_tpu.runtime.launcher``, which runs actual
processes.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .objects import KIND_POD, Pod, PodPhase
from .store import NotFound, Store


@dataclass
class PodScript:
    """What happens to a pod once it starts."""

    run_seconds: float = 0.0
    exit_code: int = 0
    barrier_after: Optional[float] = 0.0  # None = never reaches the barrier
    hang: bool = False


DEFAULT_SCRIPT = PodScript()

ScriptFn = Callable[[Pod], PodScript]


class FakeKubelet:
    def __init__(self, store: Store, script: Optional[ScriptFn] = None, interval: float = 0.01):
        self.store = store
        self.script: ScriptFn = script or (lambda pod: DEFAULT_SCRIPT)
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._running: dict[str, tuple[float, PodScript]] = {}  # key -> (start, script)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="fake-kubelet", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:  # noqa: BLE001
                pass
            self._stop.wait(self.interval)

    def step(self) -> None:
        now = time.time()
        for pod in self.store.list(KIND_POD):
            assert isinstance(pod, Pod)
            key = f"{pod.metadata.namespace}/{pod.metadata.name}/{pod.metadata.uid}"
            if pod.status.phase == PodPhase.PENDING and pod.spec.node_name:
                script = self.script(pod)
                self._running[key] = (now, script)
                self._mutate(pod, lambda o: self._start(o, now, script))
            elif pod.status.phase == PodPhase.RUNNING and key in self._running:
                start, script = self._running[key]
                if script.hang:
                    continue
                if now - start >= script.run_seconds:
                    del self._running[key]
                    self._mutate(pod, lambda o: self._finish(o, script, now))

    @staticmethod
    def _start(pod: Pod, now: float, script: PodScript) -> None:
        pod.status.phase = PodPhase.RUNNING
        pod.status.start_time = now
        if script.barrier_after is not None and script.barrier_after <= 0:
            pod.status.barrier_time = now

    @staticmethod
    def _finish(pod: Pod, script: PodScript, now: float) -> None:
        if script.barrier_after is not None and pod.status.barrier_time is None:
            pod.status.barrier_time = (pod.status.start_time or now) + script.barrier_after
        pod.status.phase = PodPhase.SUCCEEDED if script.exit_code == 0 else PodPhase.FAILED
        pod.status.exit_code = script.exit_code
        pod.status.finish_time = now

    def _mutate(self, pod: Pod, fn) -> None:
        try:
            self.store.update_with_retry(
                KIND_POD, pod.metadata.name, pod.metadata.namespace, fn
            )
        except NotFound:
            self._running.pop(
                f"{pod.metadata.namespace}/{pod.metadata.name}/{pod.metadata.uid}", None
            )
