"""FakeKubelet: drives bound pods through phases without real processes.

The envtest gap-filler (SURVEY.md §4): upstream controller tests create pods
that never run because envtest has no kubelet; gang-startup latency and
restart policies then go untested.  This kubelet simulator runs bound pods to
a scripted outcome (success, exit code, hang) so reconciler + scheduler
behavior — including failure/restart paths — is testable deterministically.
The *real* kubelet is ``kubeflow_tpu.runtime.launcher``, which runs actual
processes.

Scripts come in two shapes:

- the classic single-phase :class:`PodScript` (run N seconds, then exit /
  hang), kept for every existing test;
- multi-phase scripts (``PodScript.phases``): an ordered list of
  :class:`ScriptPhase` steps the pod walks through while RUNNING — a
  barrier crossing, healthy activity, an activity stall — before the
  terminal outcome.  This is what the chaos layer
  (:mod:`kubeflow_tpu.chaos`) drives: a pod that runs fine, goes quiet,
  then dies is three phases, not a new kubelet.

Passing ``chaos=FaultPlan(...)`` additionally lets the plan stall this
kubelet's loop (detection-latency faults) and fire cluster-level faults
(node drains) from ``step()``.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .objects import KIND_POD, Pod, PodPhase
from .store import AlreadyExists, NotFound, Store

log = logging.getLogger("kubeflow_tpu.fake-kubelet")


@dataclass
class ScriptPhase:
    """One step of a multi-phase pod life (all while RUNNING)."""

    duration: float = 0.0
    #: the pod crosses its first collective barrier entering this phase
    barrier: bool = False
    #: whether the pod keeps reporting activity heartbeats in this phase
    #: (False models a wedged-but-alive process going quiet)
    activity: bool = True


@dataclass
class PodScript:
    """What happens to a pod once it starts."""

    run_seconds: float = 0.0
    exit_code: int = 0
    barrier_after: Optional[float] = 0.0  # None = never reaches the barrier
    hang: bool = False
    #: multi-phase mode: walk these steps, then apply exit_code/hang.
    #: ``run_seconds``/``barrier_after`` are ignored when phases are set
    #: (the phases carry the timing and the barrier crossing).
    phases: list[ScriptPhase] = field(default_factory=list)


DEFAULT_SCRIPT = PodScript()

ScriptFn = Callable[[Pod], PodScript]


@dataclass
class _Running:
    start: float
    script: PodScript
    phase: int = 0          # index into script.phases
    phase_start: float = 0.0


class FakeKubelet:
    def __init__(self, store: Store, script: Optional[ScriptFn] = None,
                 interval: float = 0.01, chaos=None):
        self.store = store
        if script is None and chaos is not None:
            script = chaos.script_fn()
        self.script: ScriptFn = script or (lambda pod: DEFAULT_SCRIPT)
        self.interval = interval
        self.chaos = chaos
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: serializes the tick against attach_store's swap+resync — a
        #: tick landing between the two would sweep crash-lost pods out
        #: of _last_seen before resync could re-report them
        self._tick_lock = threading.Lock()
        self._running: dict[str, _Running] = {}
        #: ns/name -> last pod object this kubelet reported (uid inside):
        #: the node's own view of its pods, which is what survives a
        #: control-plane crash and feeds the adoption relist (resync)
        self._last_seen: dict[str, Pod] = {}

    def start(self) -> None:
        if self.chaos is not None:
            self.chaos.activate()
        self._stop.clear()  # re-startable: the node outlives a control plane
        self._thread = threading.Thread(target=self._loop, name="fake-kubelet", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.chaos is not None and self.chaos.kubelet_stalled():
                    self._stop.wait(self.interval)
                    continue
                with self._tick_lock:
                    self.step()
            except Exception:  # noqa: BLE001 — the kubelet loop must survive
                log.debug("fake-kubelet step failed", exc_info=True)
            self._stop.wait(self.interval)

    # -- control-plane crash-restart (adoption) ---------------------------

    def attach_store(self, store: Store) -> None:
        """Point this kubelet at a RESTARTED control plane's store and
        re-report everything the node still knows (``resync``) — the
        kubelet relist that makes surviving pods adoptable.  Call this
        BEFORE the new cluster's controllers start, so their initial
        list already contains the survivors (informer-sync-before-
        reconcile); creates race-safely no-op on AlreadyExists either
        way.  Safe while the kubelet loop runs: the swap + resync are
        one atomic unit w.r.t. ticks."""
        with self._tick_lock:
            self.store = store
            self.resync()

    def resync(self) -> None:
        """Reconcile the store against this node's view:

        - a pod the node runs (or finished during the outage) that the
          recovered store LOST (its create/status records sat past the
          durability horizon) is re-created verbatim — same uid, labels,
          owner refs — so the controller adopts it by owner-ref match
          instead of double-creating the gang member;
        - a pod the store recovered with a STALE status (e.g. RUNNING
          though it finished while the control plane was down) gets the
          node's truth replayed onto it.  The kubelet is the sole status
          writer, so node truth always wins on matching uid."""
        for nkey, pod in list(self._last_seen.items()):
            ns, name = nkey.split("/", 1)
            cur = self.store.try_get(KIND_POD, name, ns)
            if cur is None:
                obj = copy.deepcopy(pod)
                obj.metadata.resource_version = 0
                try:
                    self.store.create(obj)
                except AlreadyExists:
                    pass  # raced a controller create of the same name
                except NotFound:
                    pass  # admission raced an owner lookup; next step heals
                continue
            assert isinstance(cur, Pod)
            if cur.metadata.uid != pod.metadata.uid:
                continue  # a newer incarnation owns the name now
            if cur.status == pod.status and cur.spec.node_name:
                continue

            def mut(o, p=pod):
                o.status = p.status.model_copy(deep=True)
                if not o.spec.node_name:  # lost binding: the node knows
                    o.spec.node_name = p.spec.node_name

            try:
                self.store.update_with_retry(KIND_POD, name, ns, mut)
            except NotFound:
                pass
        # the inverse direction: a recovered pod that claims to be
        # RUNNING but that this node does not know (its delete record
        # was lost, so the store resurrected it) has no process behind
        # it — report it failed so the controller re-forms the gang
        # instead of waiting forever on a ghost
        for pod in self.store.list(KIND_POD):
            assert isinstance(pod, Pod)
            nkey = f"{pod.metadata.namespace}/{pod.metadata.name}"
            if (pod.status.phase != PodPhase.RUNNING
                    or nkey in self._last_seen):
                continue

            def lost(o):
                o.status.phase = PodPhase.FAILED
                o.status.exit_code = 137
                o.status.message = "no process on node after restart"
                o.status.finish_time = time.time()

            try:
                self.store.update_with_retry(
                    KIND_POD, pod.metadata.name, pod.metadata.namespace, lost)
            except NotFound:
                pass

    def step(self) -> None:
        now = time.time()
        if self.chaos is not None:
            self.chaos.apply_cluster_faults(self.store, now)
        # ONE store snapshot per tick (list deep-copies under the store
        # lock): both the deletion sweep and the pod loop work off it
        pods = [p for p in self.store.list(KIND_POD) if isinstance(p, Pod)]
        present = {
            f"{p.metadata.namespace}/{p.metadata.name}/{p.metadata.uid}"
            for p in pods}
        for key in list(self._running):
            if key not in present:
                # the pod object was deleted while we watched: the
                # controller killed it — the local "process" dies too
                self._running.pop(key, None)
                nkey, _, uid = key.rpartition("/")
                seen = self._last_seen.get(nkey)
                if seen is not None and seen.metadata.uid == uid:
                    self._last_seen.pop(nkey, None)
        for pod in pods:
            key = f"{pod.metadata.namespace}/{pod.metadata.name}/{pod.metadata.uid}"
            if pod.status.phase == PodPhase.PENDING and pod.spec.node_name:
                script = self.script(pod)
                self._running[key] = _Running(now, script, phase_start=now)
                self._mutate(pod, lambda o: self._start(o, now, script))
            elif pod.status.phase == PodPhase.RUNNING and key in self._running:
                run = self._running[key]
                if run.script.phases:
                    self._step_phases(pod, run, now, key)
                    continue
                if run.script.hang:
                    continue
                if now - run.start >= run.script.run_seconds:
                    del self._running[key]
                    self._mutate(pod, lambda o: self._finish(o, run.script, now))

    def _step_phases(self, pod: Pod, run: _Running, now: float, key: str) -> None:
        """Advance a multi-phase script: cross due phase boundaries, stamp
        barrier/activity status, finish after the last phase."""
        while run.phase < len(run.script.phases):
            phase = run.script.phases[run.phase]
            if now - run.phase_start < phase.duration:
                # heartbeat at ~10 Hz, not every kubelet tick: each write
                # is a store update fanning out to every watch
                if phase.activity and (pod.status.last_activity is None
                                       or now - pod.status.last_activity >= 0.1):
                    self._mutate(pod, lambda o: setattr(
                        o.status, "last_activity", now))
                return
            run.phase += 1
            run.phase_start = now
            if run.phase < len(run.script.phases):
                nxt = run.script.phases[run.phase]
                if nxt.barrier:
                    self._mutate(pod, lambda o: setattr(
                        o.status, "barrier_time",
                        o.status.barrier_time or now))
        if run.script.hang:
            return
        del self._running[key]
        self._mutate(pod, lambda o: self._finish(o, run.script, now))

    @staticmethod
    def _start(pod: Pod, now: float, script: PodScript) -> None:
        pod.status.phase = PodPhase.RUNNING
        pod.status.start_time = now
        if script.phases:
            if script.phases[0].barrier:
                pod.status.barrier_time = now
            if script.phases[0].activity:
                pod.status.last_activity = now
        elif script.barrier_after is not None and script.barrier_after <= 0:
            pod.status.barrier_time = now

    @staticmethod
    def _finish(pod: Pod, script: PodScript, now: float) -> None:
        if (not script.phases and script.barrier_after is not None
                and pod.status.barrier_time is None):
            pod.status.barrier_time = (pod.status.start_time or now) + script.barrier_after
        pod.status.phase = PodPhase.SUCCEEDED if script.exit_code == 0 else PodPhase.FAILED
        pod.status.exit_code = script.exit_code
        pod.status.finish_time = now

    def _mutate(self, pod: Pod, fn) -> None:
        nkey = f"{pod.metadata.namespace}/{pod.metadata.name}"
        try:
            out = self.store.update_with_retry(
                KIND_POD, pod.metadata.name, pod.metadata.namespace, fn
            )
            assert isinstance(out, Pod)
            # the node's own record of this pod (store returns a copy):
            # what resync re-reports after a control-plane crash
            self._last_seen[nkey] = out
        except NotFound:
            self._running.pop(f"{nkey}/{pod.metadata.uid}", None)
            self._last_seen.pop(nkey, None)
