"""Expectations cache — suppress reconciles against a stale informer view.

The one subtle concurrency mechanism SURVEY.md §5 calls out as worth keeping
conceptually [upstream: kubeflow/training-operator ->
pkg/controller.v1/expectation/ (from k8s controller_utils.go)]: after a
controller issues N creates/deletes, it must not trust its cached listing
until the N watch events land, or it will double-create.  Our store is
strongly consistent, but reconcilers still interleave with the scheduler,
kubelet, and user writes across threads, so the same guard applies.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class _Exp:
    adds: int = 0
    dels: int = 0
    timestamp: float = field(default_factory=time.time)


#: Expectations older than this are considered expired (controller restart /
#: lost event safety valve), same 5-minute TTL as upstream.
EXPECTATION_TTL_SECONDS = 300.0


class Expectations:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_key: dict[str, _Exp] = {}

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            e = self._by_key.setdefault(key, _Exp())
            e.adds += n
            e.timestamp = time.time()

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            e = self._by_key.setdefault(key, _Exp())
            e.dels += n
            e.timestamp = time.time()

    def creation_observed(self, key: str) -> None:
        with self._lock:
            e = self._by_key.get(key)
            if e and e.adds > 0:
                e.adds -= 1

    def deletion_observed(self, key: str) -> None:
        with self._lock:
            e = self._by_key.get(key)
            if e and e.dels > 0:
                e.dels -= 1

    def satisfied(self, key: str) -> bool:
        with self._lock:
            e = self._by_key.get(key)
            if e is None:
                return True
            if e.adds <= 0 and e.dels <= 0:
                return True
            return (time.time() - e.timestamp) > EXPECTATION_TTL_SECONDS

    def forget(self, key: str) -> None:
        with self._lock:
            self._by_key.pop(key, None)
