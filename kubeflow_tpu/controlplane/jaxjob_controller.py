"""JaxJob reconciler: JaxJob -> PodGroup + Pods + Services -> status.

The training-operator core loop rebuilt TPU-first (SURVEY.md §3.1)
[upstream: kubeflow/training-operator -> pkg/controller.v1/common/job.go
ReconcileJobs, pkg/controller.v1/jax/ JAXJobReconciler]:

1. admission (defaulting+validation) happens at store-create via webhooks;
2. ensure a PodGroup with ``min_member`` (Volcano analog) so the gang
   scheduler admits all-or-nothing;
3. ensure one Pod + headless Service per replica index, with the
   ``jax.distributed.initialize`` triple injected as env — the TPU-native
   replacement for MASTER_ADDR/RANK/WORLD_SIZE and TF_CONFIG;
4. aggregate pod phases into ReplicaStatus + JobConditions; apply RunPolicy
   (backoff, deadlines, gang timeout, TTL, clean-pod policy) and per-replica
   RestartPolicy (ExitCode-aware retries);
5. record the gang-startup metric (create -> every process past its first
   barrier) on job status — a headline BASELINE metric.
"""

from __future__ import annotations

import json
import time
import zlib
from typing import Optional

from ..api.common import (
    JobCondition,
    JobConditionType,
    ObjectMeta,
    OwnerReference,
    ReplicaStatus,
    RestartPolicy,
    get_condition,
    has_condition,
    is_retryable_exit,
    replica_pod_name,
    replica_service_dns,
    set_condition,
)
from ..api.jaxjob import KIND_JAXJOB, WORKER, JaxJob
from ..api.common import CleanPodPolicy
from ..api.validation import default_jaxjob
from .controller import Controller, Result
from .expectations import Expectations
from .objects import (
    GROUP_NAME_ANNOTATION,
    KIND_POD,
    KIND_PODGROUP,
    KIND_SERVICE,
    LABEL_JOB_NAME,
    LABEL_REPLICA_INDEX,
    LABEL_REPLICA_TYPE,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    PodSpec,
    Service,
    ServiceSpec,
)
from .store import ADDED, AlreadyExists, DELETED, NotFound, Store, WatchEvent
from ..utils.net import allocate_port

#: Env var names — the runtime bootstrap contract
#: (kubeflow_tpu.runtime.bootstrap reads exactly these).
ENV_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"
ENV_JOB_NAME = "KFT_JOB_NAME"
ENV_JOB_NAMESPACE = "KFT_JOB_NAMESPACE"
ENV_REPLICA_TYPE = "KFT_REPLICA_TYPE"
ENV_REPLICA_INDEX = "KFT_REPLICA_INDEX"
ENV_MESH = "KFT_MESH"  # json dict axis -> size


class JaxJobController(Controller):
    kind = KIND_JAXJOB
    owned_kinds = (KIND_POD, KIND_SERVICE, KIND_PODGROUP)
    workers = 2

    def __init__(self, store: Store) -> None:
        super().__init__(store)
        # Intentionally NOT durable: in-flight create/delete intent died
        # with the old process, and a fresh ledger is all-satisfied, so
        # the first post-restart reconcile trusts the store listing.
        # That listing already contains the pods that outlived a crash
        # because kubelet resync runs BEFORE controllers start
        # (cluster.py crash-restart order) — the HasSynced-before-
        # reconcile half of the upstream expectations contract.
        self.expectations = Expectations()

    # -- expectation accounting (SatisfiedExpectations pattern) ---------------

    def observe(self, ev: WatchEvent) -> None:
        if ev.obj.kind != KIND_POD:
            return
        key = self.owner_key_for(ev.obj)
        if key is None:
            return
        if ev.type == ADDED:
            self.expectations.creation_observed(key)
        elif ev.type == DELETED:
            self.expectations.deletion_observed(key)

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        try:
            return self._reconcile(namespace, name)
        except NotFound:
            # the job (or an object mid-update) vanished under this pass —
            # a benign race with deletion, not a reconcile error: the
            # deletion's own watch event re-enqueues the key and the next
            # pass runs orphan cleanup (controller-runtime's IsNotFound
            # convention)
            return Result(requeue_after=0.02)

    def _reconcile(self, namespace: str, name: str) -> Optional[Result]:
        key = f"{namespace}/{name}"
        job = self.store.try_get(KIND_JAXJOB, name, namespace)
        if job is None:
            self._cleanup_orphans(namespace, name)
            self.expectations.forget(key)
            return None
        assert isinstance(job, JaxJob)

        if not self.expectations.satisfied(key):
            return Result(requeue_after=0.02)

        pods = [
            p
            for p in self.store.list(KIND_POD, namespace, labels={LABEL_JOB_NAME: name})
            if isinstance(p, Pod)
        ]

        # terminal jobs: only TTL cleanup remains
        terminal = has_condition(job.status.conditions, JobConditionType.SUCCEEDED) or (
            has_condition(job.status.conditions, JobConditionType.FAILED)
        )
        if terminal:
            return self._handle_ttl(job)

        if job.spec.run_policy.suspend:
            return self._handle_suspend(job, pods)

        resize_msg = self._resize_needed(job, pods)
        if resize_msg:
            return self._handle_resize(job, pods, resize_msg)

        self._ensure_condition(job, JobConditionType.CREATED, "JobCreated", "JaxJob accepted")

        pg = self._ensure_podgroup(job)
        if self._gang_timed_out(job, pg):
            self._fail(job, pods, "GangScheduleTimeout", "pod group unschedulable past timeout")
            return None

        job = self._resolve_coordinator_port(job)

        # restart pacing: while a gang restart's backoff window is open,
        # hold pod re-creation (a requeue alone would not — any owned-pod
        # event re-enqueues the key immediately)
        hold = self._restart_hold(job)
        if hold > 0:
            return Result(requeue_after=hold)
        self._ensure_pods_services(job, pods)

        # refresh pod view after creations for status aggregation
        pods = [
            p
            for p in self.store.list(KIND_POD, namespace, labels={LABEL_JOB_NAME: name})
            if isinstance(p, Pod)
        ]
        return self._update_status(job, pods)

    # -- ensure: PodGroup ------------------------------------------------------

    def _ensure_podgroup(self, job: JaxJob) -> Optional[PodGroup]:
        sp = job.spec.run_policy.scheduling_policy
        min_member = sp.min_available if sp and sp.min_available else job.spec.total_replicas
        pg = self.store.try_get(KIND_PODGROUP, job.metadata.name, job.metadata.namespace)
        if pg is None:
            pg = PodGroup(
                metadata=ObjectMeta(
                    name=job.metadata.name,
                    namespace=job.metadata.namespace,
                    owner_references=[self._owner_ref(job)],
                ),
                spec={
                    "min_member": min_member,
                    "queue": sp.queue if sp else "default",
                    "priority_class": sp.priority_class if sp else None,
                },
            )
            try:
                pg = self.store.create(pg)
                self.emit_event(job, "PodGroupCreated", f"gang minMember={min_member}")
            except AlreadyExists:
                pg = self.store.try_get(
                    KIND_PODGROUP, job.metadata.name, job.metadata.namespace
                )
        return pg  # type: ignore[return-value]

    def _gang_timed_out(self, job: JaxJob, pg: Optional[PodGroup]) -> bool:
        sp = job.spec.run_policy.scheduling_policy
        if not sp or sp.schedule_timeout_seconds is None or pg is None:
            return False
        if pg.status.phase == PodGroupPhase.RUNNING:
            return False
        created = pg.metadata.creation_timestamp or time.time()
        return (time.time() - created) > sp.schedule_timeout_seconds

    # -- coordinator port ------------------------------------------------------

    def _resolve_coordinator_port(self, job: JaxJob) -> JaxJob:
        """Allocate the rendezvous port at bind time, not submit time.

        spec.coordinator_port == 0 means "controller's choice": the port is
        picked here — in the one process that sees every gang on the host —
        and persisted to status so it survives gang restarts (r1 weak #6:
        SDK-side free_port() raced between pick and pod spawn, and parallel
        HPO trials could collide).
        """
        if job.spec.coordinator_port or job.status.coordinator_port:
            return job
        port = allocate_port()
        updated = self.store.update_with_retry(
            KIND_JAXJOB,
            job.metadata.name,
            job.metadata.namespace,
            lambda o: setattr(o.status, "coordinator_port", o.status.coordinator_port or port),
        )
        assert isinstance(updated, JaxJob)
        return updated

    def _job_port(self, job: JaxJob) -> int:
        return job.spec.coordinator_port or job.status.coordinator_port or 0

    # -- ensure: pods + headless services -------------------------------------

    def _ensure_pods_services(self, job: JaxJob, pods: list[Pod]) -> None:
        self._adopt_orphans(job, pods)
        existing = {
            (p.metadata.labels.get(LABEL_REPLICA_TYPE), int(p.metadata.labels.get(LABEL_REPLICA_INDEX, -1))): p
            for p in pods
        }
        to_create: list[Pod] = []
        for rtype, rspec in job.spec.replica_specs.items():
            for idx in range(rspec.replicas):
                if (rtype, idx) in existing:
                    continue
                to_create.append(self._build_pod(job, rtype, idx))
        if not to_create:
            return
        key = job.key
        self.expectations.expect_creations(key, len(to_create))
        created = 0
        for pod in to_create:
            try:
                self.store.create(pod)
                created += 1
            except AlreadyExists:
                self.expectations.creation_observed(key)
            self._ensure_service(job, pod)
        if created:
            self.emit_event(job, "PodsCreated", f"created {created} pods")

    def _adopt_orphans(self, job: JaxJob, pods: list[Pod]) -> None:
        """Pods matching this job's labels but missing its owner-ref are
        ADOPTED (owner-ref patched in) rather than shadowed by a
        recreate: after a control-plane crash a kubelet re-reports the
        pods that outlived it, and those must re-enter ownership — the
        ControllerRefManager adoption path [upstream: k8s
        controller_ref_manager.go], which is what keeps a restart from
        turning survivors into unadoptable orphans."""
        for p in pods:
            if any(r.kind == KIND_JAXJOB and r.name == job.metadata.name
                   and r.controller for r in p.metadata.owner_references):
                continue

            def mut(o, ref=self._owner_ref(job)):
                if not any(r.kind == ref.kind and r.name == ref.name
                           for r in o.metadata.owner_references):
                    o.metadata.owner_references.append(ref)

            try:
                self.store.update_with_retry(
                    KIND_POD, p.metadata.name, p.metadata.namespace, mut)
                self.emit_event(job, "PodAdopted",
                                f"adopted orphaned pod {p.metadata.name}")
            except NotFound:
                pass  # raced deletion: nothing to adopt

    def _build_pod(self, job: JaxJob, rtype: str, idx: int) -> Pod:
        rspec = job.spec.replica_specs[rtype]
        container = rspec.template.model_copy(deep=True)
        n_workers = job.spec.worker_count
        coord_dns = replica_service_dns(
            job.metadata.name, WORKER, 0, job.metadata.namespace
        )
        env = {
            ENV_JOB_NAME: job.metadata.name,
            ENV_JOB_NAMESPACE: job.metadata.namespace,
            ENV_REPLICA_TYPE: rtype,
            ENV_REPLICA_INDEX: str(idx),
            ENV_MESH: json.dumps(job.spec.mesh),
        }
        if rtype == WORKER:
            # only workers join the jax.distributed collective; auxiliary
            # roles (e.g. a dataset service) run outside it
            env[ENV_COORDINATOR_ADDRESS] = f"{coord_dns}:{self._job_port(job)}"
            env[ENV_NUM_PROCESSES] = str(n_workers)
            env[ENV_PROCESS_ID] = str(idx)
        container.env = {**env, **container.env}
        return Pod(
            metadata=ObjectMeta(
                name=replica_pod_name(job.metadata.name, rtype, idx),
                namespace=job.metadata.namespace,
                labels={
                    LABEL_JOB_NAME: job.metadata.name,
                    LABEL_REPLICA_TYPE: rtype,
                    LABEL_REPLICA_INDEX: str(idx),
                },
                annotations={GROUP_NAME_ANNOTATION: job.metadata.name},
                owner_references=[self._owner_ref(job)],
            ),
            spec=PodSpec(
                container=container,
                scheduler_name="gang",
                restart_policy=rspec.restart_policy.value,
            ),
        )

    def _ensure_service(self, job: JaxJob, pod: Pod) -> None:
        try:
            self.store.create(
                Service(
                    metadata=ObjectMeta(
                        name=pod.metadata.name,
                        namespace=pod.metadata.namespace,
                        owner_references=[self._owner_ref(job)],
                    ),
                    spec=ServiceSpec(
                        selector=dict(pod.metadata.labels),
                        ports=[self._job_port(job)],
                    ),
                )
            )
        except AlreadyExists:
            pass

    # -- status ----------------------------------------------------------------

    def _update_status(self, job: JaxJob, pods: list[Pod]) -> Optional[Result]:
        by_type: dict[str, ReplicaStatus] = {}
        failed_pods: list[Pod] = []
        barrier_times: list[float] = []
        workers_total = job.spec.worker_count
        for p in pods:
            rtype = p.metadata.labels.get(LABEL_REPLICA_TYPE, "")
            rs = by_type.setdefault(rtype, ReplicaStatus())
            if p.status.phase == PodPhase.SUCCEEDED:
                rs.succeeded += 1
            elif p.status.phase == PodPhase.FAILED:
                rs.failed += 1
                failed_pods.append(p)
            else:
                rs.active += 1
            if rtype == WORKER and p.status.barrier_time is not None:
                barrier_times.append(p.status.barrier_time)

        def mut(o):
            assert isinstance(o, JaxJob)
            o.status.replica_statuses = by_type
            if (
                o.status.gang_startup_seconds is None
                and len(barrier_times) == workers_total
                and workers_total > 0
            ):
                created = o.metadata.creation_timestamp or 0.0
                o.status.gang_startup_seconds = max(barrier_times) - created

        job = self._update_job(job, mut)

        worker_rs = by_type.get(WORKER, ReplicaStatus())
        any_running = any(
            p.status.phase == PodPhase.RUNNING for p in pods
        )
        if any_running and not has_condition(job.status.conditions, JobConditionType.RUNNING):
            job = self._set_cond(job, JobConditionType.RUNNING, "JobRunning", "workers running")
            job = self._update_job(job, lambda o: setattr(o.status, "start_time", o.status.start_time or time.time()))

        running_workers = sum(
            1 for p in pods
            if p.metadata.labels.get(LABEL_REPLICA_TYPE) == WORKER
            and p.status.phase == PodPhase.RUNNING
        )
        if not failed_pods and workers_total > 0 and running_workers == workers_total:
            job = self._observe_recovery(job)
            job = self._maybe_reset_restart_budget(job)

        # deadline
        rp = job.spec.run_policy
        if rp.active_deadline_seconds and job.status.start_time:
            if time.time() - job.status.start_time > rp.active_deadline_seconds:
                self._fail(job, pods, "DeadlineExceeded", "activeDeadlineSeconds exceeded")
                return None

        # success: every worker pod succeeded
        if workers_total > 0 and worker_rs.succeeded >= workers_total:
            job = self._set_cond(job, JobConditionType.SUCCEEDED, "JobSucceeded", "all workers succeeded")
            self._update_job(job, lambda o: setattr(o.status, "completion_time", time.time()))
            self.emit_event(job, "JobSucceeded", "all workers succeeded")
            self._clean_pods(job, pods)
            return self._handle_ttl(self.store.get(KIND_JAXJOB, job.metadata.name, job.metadata.namespace))  # type: ignore[arg-type]

        # failures: restart-policy + backoff decision
        if failed_pods:
            return self._handle_failures(job, pods, failed_pods)

        # keep polling while pods run (deadline / straggler watching)
        return Result(requeue_after=0.05) if any_running or worker_rs.active else None

    def _observe_recovery(self, job: JaxJob) -> JaxJob:
        """Every worker is Running again after a gang restart: close the
        Restarting condition and record restart->RUNNING latency (the
        recovery metric scripts/recovery_bench.py tracks the way
        gang_startup_bench.py tracks startup)."""
        cond = get_condition(job.status.conditions, JobConditionType.RESTARTING)
        if cond is None or not cond.status:
            return job
        # a resize also rides the Restarting condition but does not stamp
        # last_restart_time; its re-forming must not mint a bogus
        # recovery-latency sample off a stale failure timestamp
        recovery = (
            time.time() - job.status.last_restart_time
            if cond.reason == "PodsRestarting"
            and job.status.last_restart_time is not None else None
        )

        def mut(o):
            assert isinstance(o, JaxJob)
            o.status.conditions = set_condition(
                o.status.conditions,
                JobCondition(type=JobConditionType.RESTARTING, status=False,
                             reason="GangRecovered", message="gang re-formed"),
            )
            if recovery is not None:
                o.status.last_recovery_seconds = recovery

        job = self._update_job(job, mut)
        self.emit_event(
            job, "GangRecovered",
            json.dumps({"restart": job.status.restart_count,
                        "recovery_seconds":
                            round(recovery, 3) if recovery is not None else None}))
        return job

    def _maybe_reset_restart_budget(self, job: JaxJob) -> JaxJob:
        """Stable past the restart window -> restart_count goes back to 0,
        so backoff_limit bounds *flapping*, not lifetime bad luck."""
        rp = job.spec.run_policy
        if (rp.restart_window_seconds is None or not job.status.restart_count
                or has_condition(job.status.conditions, JobConditionType.RESTARTING)):
            return job
        anchor = job.status.last_restart_time or job.status.start_time
        if anchor is None:
            return job
        anchor += job.status.last_recovery_seconds or 0.0
        if time.time() - anchor <= rp.restart_window_seconds:
            return job
        job = self._update_job(
            job, lambda o: setattr(o.status, "restart_count", 0))
        self.emit_event(
            job, "RestartBudgetReset",
            f"stable for {rp.restart_window_seconds}s; restart budget restored")
        return job

    def _handle_failures(
        self, job: JaxJob, pods: list[Pod], failed_pods: list[Pod]
    ) -> Optional[Result]:
        retryable: list[Pod] = []
        for p in failed_pods:
            policy = RestartPolicy(p.spec.restart_policy)
            code = p.status.exit_code if p.status.exit_code is not None else 1
            if policy == RestartPolicy.ALWAYS or policy == RestartPolicy.ON_FAILURE:
                retryable.append(p)
            elif policy == RestartPolicy.EXIT_CODE and is_retryable_exit(code):
                retryable.append(p)
            else:
                self._fail(
                    job,
                    pods,
                    "PodFailed",
                    f"pod {p.metadata.name} exit={code} policy={policy.value}",
                )
                return None

        if job.status.restart_count + 1 > job.spec.run_policy.backoff_limit:
            self._fail(job, pods, "BackoffLimitExceeded", f"restarts={job.status.restart_count}")
            return None

        # gang restart: a failed member invalidates the collective; delete ALL
        # pods so the gang re-forms (jax.distributed cannot patch one rank).
        key = job.key
        live = [p for p in pods if self.store.try_get(KIND_POD, p.metadata.name, p.metadata.namespace)]
        self.expectations.expect_deletions(key, len(live))
        for p in live:
            if not self.store.try_delete(KIND_POD, p.metadata.name, p.metadata.namespace):
                self.expectations.deletion_observed(key)
        job = self._set_cond(job, JobConditionType.RESTARTING, "PodsRestarting", "gang restart after failure")

        def bump(o):
            o.status.restart_count += 1
            o.status.last_restart_time = time.time()
            if not o.spec.coordinator_port:
                # fresh coordinator port for the new incarnation: the old
                # coordinator process may hold the previous port through
                # its kill-grace window, and jax.distributed's bind/
                # connect retry backoff was the dominant term of
                # restart->resume (measured ~10.5s of 11s p50,
                # scripts/gang_startup_bench.py phase decomposition) —
                # the new gang's pods are rebuilt anyway, so they carry
                # the new port in their env
                o.status.coordinator_port = None

        job = self._update_job(job, bump)
        delay = self._restart_backoff(job)
        self.emit_event(
            job, "Restarting",
            json.dumps({"restart": job.status.restart_count,
                        "backoff_seconds": round(delay, 3)}),
            "Warning")
        return Result(requeue_after=delay)

    # -- restart pacing --------------------------------------------------------

    def _restart_backoff(self, job: JaxJob) -> float:
        """Delay before the gang's next incarnation: exponential in the
        restart count, capped, with deterministic +-50% jitter (stable
        across reconcile passes — a random draw here would make the hold
        gate flicker — but decorrelated across jobs, so N gangs felled by
        one node do not re-form in lockstep)."""
        rp = job.spec.run_policy
        n = max(job.status.restart_count - 1, 0)
        base = min(rp.restart_backoff_seconds * (2 ** n),
                   rp.restart_backoff_max_seconds)
        salt = f"{job.metadata.uid}:{job.status.restart_count}".encode()
        jitter = 0.5 + (zlib.crc32(salt) % 1000) / 1000.0
        return base * jitter

    def _restart_hold(self, job: JaxJob) -> float:
        """Seconds the backoff window still has open, 0 when clear."""
        if not has_condition(job.status.conditions, JobConditionType.RESTARTING):
            return 0.0
        if job.status.last_restart_time is None:
            return 0.0
        return max(
            0.0,
            job.status.last_restart_time + self._restart_backoff(job) - time.time(),
        )

    # -- terminal helpers ------------------------------------------------------

    def _fail(self, job: JaxJob, pods: list[Pod], reason: str, message: str) -> None:
        job = self._set_cond(job, JobConditionType.FAILED, reason, message)
        self._update_job(job, lambda o: setattr(o.status, "completion_time", time.time()))
        self.emit_event(job, reason, message, "Warning")
        self._clean_pods(job, pods)

    def _clean_pods(self, job: JaxJob, pods: list[Pod]) -> None:
        policy = job.spec.run_policy.clean_pod_policy
        if policy == CleanPodPolicy.NONE:
            return
        for p in pods:
            if policy == CleanPodPolicy.RUNNING and p.terminal:
                continue
            self.store.try_delete(KIND_POD, p.metadata.name, p.metadata.namespace)

    # -- elastic resize --------------------------------------------------------

    def _resize_needed(self, job: JaxJob, pods: list[Pod]) -> Optional[str]:
        """A live worker whose stamped world size (or index range) no longer
        matches the spec means the user changed ``replicas`` on a running
        job — the PyTorchJob ElasticPolicy capability, TPU-style: the
        collective cannot be patched one rank at a time, so the whole gang
        re-forms on the new world size and resumes from checkpoint
        (reshape-restore, SURVEY §2.5 elastic row)."""
        want = job.spec.worker_count
        for p in pods:
            if p.metadata.labels.get(LABEL_REPLICA_TYPE) != WORKER or p.terminal:
                continue
            stamped = p.spec.container.env.get(ENV_NUM_PROCESSES)
            if stamped is not None and int(stamped) != want:
                return f"world size {stamped} -> {want}"
            idx = int(p.metadata.labels.get(LABEL_REPLICA_INDEX, 0))
            if idx >= want:
                return f"worker index {idx} out of range for {want} replicas"
        return None

    def _handle_resize(
        self, job: JaxJob, pods: list[Pod], msg: str
    ) -> Optional[Result]:
        """suspend gang -> recompute stale defaults -> re-gang on the new
        size.  Deleted workers get SIGTERM and save-on-preemption; the new
        gang's ``restore_or_init`` reshape-restores onto the new mesh.
        Resizes do not consume the failure backoff budget."""

        def mut(o: JaxJob) -> None:
            # the new gang is all-or-nothing at its new size: a stamped
            # min_available from the old world size would under-admit
            # (scale-up) or over-demand (scale-down) the collective
            sp = o.spec.run_policy.scheduling_policy
            if sp is not None:
                sp.min_available = o.spec.total_replicas
            default_jaxjob(o)

        job = self._update_job(job, mut)
        # PodGroup is recreated next reconcile with the new min_member
        self.store.try_delete(KIND_PODGROUP, job.metadata.name, job.metadata.namespace)
        # per-replica Services for removed indices would otherwise leak
        # until job deletion; drop them all — the next reconcile recreates
        # one per surviving pod
        for svc in self.store.list(KIND_SERVICE, job.metadata.namespace):
            if any(
                r.kind == KIND_JAXJOB and r.name == job.metadata.name
                for r in svc.metadata.owner_references
            ):
                self.store.try_delete(
                    KIND_SERVICE, svc.metadata.name, job.metadata.namespace)
        key = job.key
        live = [
            p for p in pods
            if self.store.try_get(KIND_POD, p.metadata.name, p.metadata.namespace)
        ]
        self.expectations.expect_deletions(key, len(live))
        for p in live:
            if not self.store.try_delete(KIND_POD, p.metadata.name, p.metadata.namespace):
                self.expectations.deletion_observed(key)
        self._set_cond(
            job, JobConditionType.RESTARTING, "Resizing", f"elastic resize: {msg}")
        self.emit_event(job, "Resizing", msg)
        return Result(requeue_after=0.05)

    def _handle_suspend(self, job: JaxJob, pods: list[Pod]) -> Optional[Result]:
        for p in pods:
            self.store.try_delete(KIND_POD, p.metadata.name, p.metadata.namespace)
        self.store.try_delete(KIND_PODGROUP, job.metadata.name, job.metadata.namespace)
        self._set_cond(job, JobConditionType.SUSPENDED, "JobSuspended", "suspend=true")
        return None

    def _handle_ttl(self, job: JaxJob) -> Optional[Result]:
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is None:
            return None
        done = job.status.completion_time or time.time()
        remaining = done + ttl - time.time()
        if remaining > 0:
            return Result(requeue_after=remaining)
        self._cleanup_orphans(job.metadata.namespace, job.metadata.name)
        self.store.try_delete(KIND_JAXJOB, job.metadata.name, job.metadata.namespace)
        return None

    def _cleanup_orphans(self, namespace: str, name: str) -> None:
        for kind in (KIND_POD, KIND_SERVICE):
            for obj in self.store.list(kind, namespace, labels={LABEL_JOB_NAME: name}):
                self.store.try_delete(kind, obj.metadata.name, namespace)
        # services created per-pod carry the owner ref but not the job label
        for svc in self.store.list(KIND_SERVICE, namespace):
            if any(r.kind == KIND_JAXJOB and r.name == name for r in svc.metadata.owner_references):
                self.store.try_delete(KIND_SERVICE, svc.metadata.name, namespace)
        self.store.try_delete(KIND_PODGROUP, name, namespace)

    # -- small utils -----------------------------------------------------------

    def _owner_ref(self, job: JaxJob) -> OwnerReference:
        return OwnerReference(kind=KIND_JAXJOB, name=job.metadata.name, uid=job.metadata.uid)

    def _set_cond(self, job: JaxJob, ctype: JobConditionType, reason: str, msg: str) -> JaxJob:
        def mut(o):
            assert isinstance(o, JaxJob)
            o.status.conditions = set_condition(
                o.status.conditions, JobCondition(type=ctype, reason=reason, message=msg)
            )

        return self._update_job(job, mut)

    def _ensure_condition(self, job: JaxJob, ctype: JobConditionType, reason: str, msg: str) -> JaxJob:
        if has_condition(job.status.conditions, ctype):
            return job
        return self._set_cond(job, ctype, reason, msg)

    def _update_job(self, job: JaxJob, mut) -> JaxJob:
        out = self.store.update_with_retry(
            KIND_JAXJOB, job.metadata.name, job.metadata.namespace, mut
        )
        assert isinstance(out, JaxJob)
        return out
