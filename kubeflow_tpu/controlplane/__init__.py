"""In-process control plane: store, gang scheduler, reconcilers."""

from .cluster import Cluster
from .controller import Controller, Result, WorkQueue, events_for
from .expectations import Expectations
from .fake_kubelet import FakeKubelet, PodScript, ScriptPhase
from .jaxjob_controller import JaxJobController
from .objects import (
    GROUP_NAME_ANNOTATION,
    KIND_EVENT,
    KIND_NODE,
    KIND_POD,
    KIND_PODGROUP,
    KIND_SERVICE,
    LABEL_JOB_NAME,
    LABEL_REPLICA_INDEX,
    LABEL_REPLICA_TYPE,
    Event,
    Node,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    Service,
)
from .scheduler import GangScheduler
from .store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    ApiError,
    Conflict,
    NotFound,
    Rejected,
    Store,
    WatchEvent,
)

__all__ = [k for k in dir() if not k.startswith("_")]
