"""kubeflow_tpu — a TPU-native ML platform framework.

A from-scratch rebuild of the Kubeflow control-plane capabilities
(training-operator + KServe + Katib; reference: Garrybest/kubeflow, see
SURVEY.md) designed TPU-first: declarative gang-scheduled JaxJobs whose
rendezvous is ``jax.distributed.initialize`` over slice topology, a JAX/XLA
serving runtime, an HPO plane driving JaxJob trials, and — unlike the
reference, which ships no numerics — the in-container runtime itself:
named-axis meshes over ICI/DCN, pjit parallelism (DP/FSDP/TP/PP/SP/EP, ring
attention), Orbax checkpointing, and an observability/bench harness.
"""

__version__ = "0.1.0"
