"""Input pipeline: per-process sharded batches onto the global mesh.

The reference's data story is "each rank loads its own shard" (DDP samplers,
TF datasets) — the operator only sets rank envs (SURVEY.md §2.5 DP row).
The TPU-native equivalent: every host builds only its local slice of the
global batch and ``jax.make_array_from_process_local_data`` assembles the
global sharded array; XLA never sees host boundaries.

Synthetic streams keep tests/benches hermetic (zero-egress environment — the
reference's MNIST/C4 downloads are impossible here); real corpora plug in
through the same ``BatchSource`` protocol.
"""

from __future__ import annotations

from typing import Iterator, Protocol

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding


class BatchSource(Protocol):
    """A per-process source of host-local batch shards."""

    def local_batch(self, step: int) -> dict[str, np.ndarray]:
        ...


class SyntheticLm(BatchSource):
    """Deterministic fake LM tokens: a fixed-order Markov-ish stream derived
    from a hash of (step, process, position).  Deterministic across runs and
    independent of world size for a fixed global batch."""

    def __init__(
        self,
        global_batch: int,
        seq_len: int,
        vocab_size: int,
        *,
        process_index: int | None = None,
        process_count: int | None = None,
        seed: int = 0,
    ):
        self.global_batch = global_batch
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.proc = jax.process_index() if process_index is None else process_index
        self.nproc = jax.process_count() if process_count is None else process_count
        if global_batch % self.nproc:
            raise ValueError(
                f"global batch {global_batch} not divisible by {self.nproc} processes")
        self.local_bs = global_batch // self.nproc
        self.seed = seed

    def local_batch(self, step: int) -> dict[str, np.ndarray]:
        # rows [proc*local_bs, (proc+1)*local_bs) of the global batch
        row0 = self.proc * self.local_bs
        rows = np.arange(row0, row0 + self.local_bs, dtype=np.uint64)
        # splitmix64-style hash of (seed, step, row) -> per-row start/stride;
        # uint64 wraparound is the point, so silence overflow warnings
        with np.errstate(over="ignore"):
            x = (
                rows * np.uint64(0x9E3779B97F4A7C15)
                + np.uint64(step) * np.uint64(0x94D049BB133111EB)
                + np.uint64(self.seed)
            )
            x ^= x >> np.uint64(30)
            x *= np.uint64(0xBF58476D1CE4E5B9)
            x ^= x >> np.uint64(27)
        # each row is an arithmetic token sequence: learnable structure (the
        # stride is inferable from any two neighbors) with hash-random phase
        start = (x % np.uint64(self.vocab_size)).astype(np.int64)
        stride = ((x >> np.uint64(17)) % np.uint64(7) + np.uint64(1)).astype(np.int64)
        pos = np.arange(self.seq_len + 1, dtype=np.int64)
        tokens = (start[:, None] + stride[:, None] * pos[None, :]) % self.vocab_size
        return {"tokens": tokens.astype(np.int32)}


def device_batches(
    source: BatchSource, sharding: NamedSharding, steps: int, start_step: int = 0
) -> Iterator[dict[str, jax.Array]]:
    """Assemble host-local shards into global arrays on the mesh.

    ``start_step`` keys the source at the resumed position so a restore
    continues the data stream instead of replaying it from step 0.
    """
    for step in range(start_step, start_step + steps):
        local = source.local_batch(step)
        yield {
            k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in local.items()
        }
