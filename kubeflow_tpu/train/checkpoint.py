"""Checkpoint/resume: Orbax multi-host async save + topology-reshape restore.

The reference control plane has NO checkpointing of its own (SURVEY.md §5:
user-owned, framework checkpoints to PVC/GCS; MPIJob restart = rerun the
launcher).  Here it is first-class, because TPU elasticity IS
checkpoint-restart (a slice cannot grow in place): save-on-interval +
save-on-preemption, then restore onto a *different* mesh/world size by
re-sharding at load (the Tenplex pattern, PAPERS.md).

Orbax already does the hard parts (async device-to-host, per-host shard
writing, atomic commit via rename); this module pins the framework's
conventions: step-numbered directories, a single `state` item holding the
pytree, restore-with-shardings for reshape.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin policy layer over ``ocp.CheckpointManager``.

    save(step, state) is async (returns immediately; Orbax finishes the
    write in a background thread, multi-host-coordinated).  restore(state
    shardings) re-shards onto whatever mesh the caller is running now —
    the world size at save time is irrelevant, which is what makes
    checkpoint-restart elasticity work.
    """

    def __init__(
        self,
        directory: str,
        *,
        save_interval_steps: int = 100,
        max_to_keep: int = 3,
    ):
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=save_interval_steps,
            max_to_keep=max_to_keep,
            enable_async_checkpointing=True,
            create=True,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async save; returns True if a save was actually started."""
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        """Restore onto the shardings/structure of ``target``.

        ``target`` may be a pytree of real arrays or of
        ``jax.ShapeDtypeStruct`` with ``.sharding`` set — the reshape path:
        build the abstract state for the NEW mesh and restore into it.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(_as_abstract, target)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def _as_abstract(x: Any) -> Any:
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x
