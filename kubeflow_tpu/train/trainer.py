"""The in-container training runtime: sharded train step, metering, resume.

This is the layer upstream Kubeflow leaves to third-party frameworks
(SURVEY.md §1 closing paragraph) and the rebuild owns: given a mesh plan and
a model config, build the sharded state, run the jitted step loop, meter
tokens/sec/chip (the headline BASELINE metric), checkpoint/restore with
reshape.  Equivalent surface in the reference ecosystem: the training loops
inside TFJob/PyTorchJob user containers plus the SDK's packaged fine-tune
script [upstream: training-operator -> sdk/python/kubeflow/training, train()].

TPU-first: one ``jax.jit``-compiled step (donated state, sharded in/out) —
all collectives inserted by XLA from the sharding annotations; no gradient
bucketing/overlap machinery to hand-tune like NCCL DDP.
"""

from __future__ import annotations

import dataclasses
import signal
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ..models import llama as llamalib
from ..parallel import mesh as meshlib
from ..parallel import sharding as shardlib
from . import checkpoint as ckptlib
from . import data as datalib

#: bf16 peak matmul TFLOP/s per chip, for MFU reporting.
PEAK_TFLOPS = {"tpu v5 lite": 197.0, "tpu v5": 197.0, "cpu": 0.0}


def _sum_aux_losses(intermediates: dict) -> tuple[jax.Array, int]:
    """(sum, element count) of every ``moe_aux_loss`` sown in the tree —
    scan-stacked layers contribute one array of shape [num_layers], unrolled
    layers one scalar each; the mean over elements is the mean over layers."""
    from flax import traverse_util

    leaves = [
        leaf
        for path, val in traverse_util.flatten_dict(intermediates).items()
        if "moe_aux_loss" in path
        for leaf in jax.tree.leaves(val)
    ]
    total = sum(
        (jnp.sum(leaf.astype(jnp.float32)) for leaf in leaves),
        start=jnp.zeros((), jnp.float32))
    return total, sum(leaf.size for leaf in leaves)


@dataclasses.dataclass
class TrainConfig:
    model: llamalib.LlamaConfig = dataclasses.field(default_factory=llamalib.tiny)
    mesh_axes: dict[str, int] = dataclasses.field(default_factory=dict)
    num_slices: int = 1
    global_batch: int = 8
    seq_len: int = 128
    steps: int = 20
    learning_rate: float = 3e-4
    warmup_steps: int = 10
    weight_decay: float = 0.1
    grad_clip_norm: float = 1.0
    b1: float = 0.9
    b2: float = 0.95
    #: "adamw" (default) or "adafactor".  Adafactor's factored second
    #: moments + no first moment cut optimizer state from ~6 bytes/param
    #: to ~0 — the classic TPU big-model recipe (T5/PaLM) and what lets a
    #: >1B model train on a single 16 GiB v5e chip.
    optimizer: str = "adamw"
    #: dtype of AdamW's first moment (HBM-bandwidth lever; None = f32)
    mu_dtype: Optional[Any] = jnp.bfloat16
    #: weight on the MoE load-balancing auxiliary loss (Switch-style; only
    #: active when the model routes through MoeMlp).  0 disables collection.
    aux_loss_coef: float = 0.01
    #: gradient accumulation: split each global batch into this many
    #: microbatches, run them through a lax.scan, and average grads — the
    #: effective batch stays global_batch while per-step activation memory
    #: drops ~accum_steps-fold.  global_batch must be divisible by it.
    accum_steps: int = 1
    checkpoint_dir: Optional[str] = None
    save_interval_steps: int = 100
    #: pretrained snapshot dir (config.json + weights.msgpack — the
    #: models/llama.py save_pretrained layout): params initialize from it
    #: instead of randomly; optimizer state starts fresh.  THE fine-tune
    #: entry [upstream: training-operator sdk train() v1.9 LLM path,
    #: SURVEY.md §3.5] — hf:// URIs resolve through serving.storage first
    #: (train/llm.py KFT_INIT_FROM).  A newer checkpoint in
    #: checkpoint_dir still wins (resume > init).
    init_from: Optional[str] = None
    log_every: int = 10
    #: microbatch count for pipeline parallelism (mesh has a ``pipeline``
    #: axis > 1); default = pipeline degree.  Ignored otherwise.
    num_microbatches: Optional[int] = None
    #: pipeline schedule: "gpipe" (differentiable forward, autodiff
    #: backward — all M microbatch activations live through the step) or
    #: "1f1b" (fused value-and-grad, ~P in-flight microbatches — the
    #: perf-grade memory profile; see parallel/pipeline.py).
    pipeline_schedule: str = "gpipe"
    #: virtual stages per device under "1f1b" (Megatron interleaving):
    #: each device owns V non-contiguous model chunks, shortening the
    #: fill/drain bubble (wall ticks T = MV+P+PV-2 chunk-ticks = fewer
    #: stage-times as V grows).  The stacked layer axis is permuted to
    #: the interleaved layout inside the step (one weight reshard —
    #: cheap over ICI; charged for DCN in the projection model).
    pipeline_interleave: int = 1
    #: when set, capture a jax.profiler trace (XPlane, TensorBoard-loadable)
    #: of steps [profile_start, profile_stop) into this directory — the
    #: SURVEY §5 tracing-subsystem hook (reconcile metrics stay Prometheus-
    #: style on the control plane; device traces live here in the trainer).
    profile_dir: Optional[str] = None
    profile_start: int = 3
    profile_stop: int = 6


@dataclasses.dataclass
class StepMetrics:
    step: int
    loss: float
    grad_norm: float
    step_time_s: float
    tokens_per_sec: float
    tokens_per_sec_per_chip: float
    mfu: float


class Trainer:
    """Builds the sharded train state and runs compiled steps.

    All public methods must be called on every process of the job (SPMD) —
    the same contract as the reference's per-rank training scripts.
    """

    def __init__(self, cfg: TrainConfig, devices: Optional[list] = None):
        self.cfg = cfg
        devices = devices if devices is not None else jax.devices()
        axes = dict(cfg.mesh_axes) or {"data": len(devices)}
        self.mesh = meshlib.build_mesh(axes, devices=devices, num_slices=cfg.num_slices)
        self.model = llamalib.Llama(cfg.model)
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, cfg.warmup_steps,
            max(cfg.steps, cfg.warmup_steps + 1))
        if cfg.optimizer == "adamw":
            opt = optax.adamw(
                schedule,
                b1=cfg.b1, b2=cfg.b2, weight_decay=cfg.weight_decay,
                # bf16 first moment: halves mu's HBM read+write per step
                # (the optimizer update is pure bandwidth); nu stays f32 —
                # second moments span a wide dynamic range and bf16 there
                # measurably hurts convergence, bf16 mu does not (standard
                # large-scale practice)
                mu_dtype=cfg.mu_dtype,
            )
        elif cfg.optimizer == "adafactor":
            # no decoupled weight decay here: optax applies
            # weight_decay_rate per-step UNSCALED by the learning rate
            # (it chains add_decayed_weights after scale_by_learning_rate),
            # so AdamW's 0.1 convention would shrink params ~10%/step.
            # T5/PaLM-style Adafactor training runs without it.
            opt = optax.adafactor(schedule, min_dim_size_to_factor=128)
        else:
            raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip_norm), opt)
        if cfg.model.lora_rank > 0 and not cfg.init_from:
            # without a base snapshot the frozen base stays at RANDOM
            # init forever — the job would "succeed" producing adapters
            # that are garbage merged onto any real base
            raise ValueError(
                "lora_rank > 0 requires init_from: adapters train "
                "against a frozen base snapshot "
                "(TrainingClient.train(model=..., lora_rank=...))")
        if cfg.model.lora_rank > 0:
            # LoRA freezes the base: adapters get the real optimizer,
            # everything else set_to_zero (whose state is EMPTY — the
            # optimizer moments shrink to adapter size, which is the
            # memory economy adapters exist for).  SURVEY §3.5 peft path.
            from flax import traverse_util

            def labels(params):
                return traverse_util.unflatten_dict({
                    k: ("lora" if llamalib.is_lora_path(k) else "frozen")
                    for k in traverse_util.flatten_dict(params)})

            self.tx = optax.multi_transform(
                {"lora": self.tx, "frozen": optax.set_to_zero()}, labels)
        self.batch_sharding = meshlib.batch_sharding(self.mesh)
        self._step_fn = None
        self._abstract_state = None
        self.ckpt = (
            ckptlib.CheckpointManager(
                cfg.checkpoint_dir, save_interval_steps=cfg.save_interval_steps)
            if cfg.checkpoint_dir
            else None
        )
        #: LoRA + init_from: checkpoints persist ONLY {step, opt_state,
        #: adapters} — the base is reloadable from the snapshot, so a 7B
        #: fine-tune's checkpoint shrinks from 13 GiB of params to the
        #: MB-scale adapters (+ their moments).
        self._adapter_ckpt = (
            cfg.model.lora_rank > 0 and bool(cfg.init_from))
        #: final state after train() — the publish hook's source
        self.final_state: Optional[Any] = None

    # -- state ------------------------------------------------------------

    def _init_fn(self, rng: jax.Array) -> dict[str, Any]:
        # batch = global batch so batch-axis sharding inside the model (e.g.
        # ring attention's shard_map) sees divisible sizes during init
        dummy = jnp.ones((self.cfg.global_batch, self.cfg.seq_len), jnp.int32)
        variables = self.model.init(rng, dummy)
        params = variables["params"]
        # AdamW moments mirror the param shapes, so initializing from the
        # BOXED params propagates each param's logical sharding onto its
        # moments (FSDP shards them too).  Adafactor's factored state has
        # different ranks than the params — the copied 2-axis metadata
        # would be invalid on its rank-1 rows/cols, and the state is small
        # enough that replication (no metadata) is the right layout.
        opt_params = (
            params if self.cfg.optimizer == "adamw" else nn.meta.unbox(params))
        return {
            "step": jnp.zeros((), jnp.int32),
            "params": params,
            "opt_state": self.tx.init(opt_params),
        }

    def abstract_state(self) -> Any:
        """Unboxed ShapeDtypeStructs with shardings attached — the canonical
        description of the train state on THIS mesh (used by jit shardings,
        reshape-restore, and the dry-run compile check alike).  Cached: the
        eval_shape trace over a big scanned model is seconds of work."""
        if self._abstract_state is None:
            boxed = jax.eval_shape(self._init_fn, jax.random.PRNGKey(0))
            shardings = shardlib.param_shardings(boxed, self.mesh)
            self._abstract_state = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                nn.meta.unbox(boxed), shardings,
            )
        return self._abstract_state

    def init_state(self, seed: int = 0) -> Any:
        """Initialize sharded: weights are born on the mesh (no host round
        trip — a 7B state never materializes on one host).  With
        ``cfg.init_from``, params then load from the pretrained snapshot
        (optimizer state stays fresh — zeros/step-0, the standard
        fine-tune start)."""
        abstract = self.abstract_state()
        shardings = jax.tree.map(lambda a: a.sharding, abstract)
        if self.cfg.init_from:
            # snapshot weights replace random init entirely — running the
            # full jitted param init just to discard it would compile and
            # execute a 7B random initialization for nothing; only the
            # optimizer state (zeros) is built on-mesh here
            params = self._pretrained_params(abstract["params"])
            with shardlib.shard_context(self.mesh):
                rest = jax.jit(
                    lambda p: {"step": jnp.zeros((), jnp.int32),
                               "opt_state": self.tx.init(p)},
                    out_shardings={"step": shardings["step"],
                                   "opt_state": shardings["opt_state"]},
                )(params)
            return {"step": rest["step"], "params": params,
                    "opt_state": rest["opt_state"]}
        with shardlib.shard_context(self.mesh):
            state = jax.jit(
                self._init_fn, out_shardings=shardings
            )(jax.random.PRNGKey(seed))
        return nn.meta.unbox(state)

    def _fresh_adapters(self, lora_abstract: Any) -> Any:
        """Host-deterministic LoRA init (A ~ normal 0.02, B = 0) placed
        onto the mesh — every process computes the same values, so no
        cross-host RNG coordination is needed."""
        from flax import traverse_util

        rng = np.random.RandomState(0)
        out = {}
        for path, sds in sorted(
                traverse_util.flatten_dict(lora_abstract).items()):
            if path[-1] == "lora_a":
                host = rng.normal(0.0, 0.02, size=sds.shape).astype(
                    np.dtype(sds.dtype))
            else:
                host = np.zeros(sds.shape, np.dtype(sds.dtype))
            out[path] = jax.make_array_from_callback(
                sds.shape, sds.sharding, lambda idx, h=host: h[idx])
        return traverse_util.unflatten_dict(out)

    def _pretrained_params(
        self, abstract_params: Any, adapters: Optional[Any] = None
    ) -> Any:
        """Snapshot weights placed onto the mesh's param shardings.

        Loads host-side once per process and shards via
        ``make_array_from_callback`` (works identically single- and
        multi-host: each process materializes only its addressable
        shards).  The snapshot's architecture must match the training
        config — silent shape coercion would "fine-tune" a different
        model than the one named.

        With ``cfg.model.lora_rank > 0`` and a base (lora-free) snapshot,
        the base leaves load from the snapshot and the adapter leaves
        come from ``adapters`` (a checkpoint's) or fresh init."""
        snap_cfg, loaded = llamalib.load_pretrained(self.cfg.init_from)
        mcfg = self.cfg.model
        for f in ("vocab_size", "hidden_size", "intermediate_size",
                  "num_layers", "num_heads", "num_kv_heads", "head_dim",
                  "tie_embeddings", "moe_experts", "scan_layers"):
            if getattr(snap_cfg, f) != getattr(mcfg, f):
                raise ValueError(
                    f"init_from snapshot {self.cfg.init_from}: {f}="
                    f"{getattr(snap_cfg, f)} != model config "
                    f"{getattr(mcfg, f)}; the snapshot defines the "
                    "architecture — build TrainConfig.model from "
                    "load_pretrained_config")

        def put(sds, host):
            host = np.asarray(host)
            if host.shape != sds.shape:
                raise ValueError(
                    f"init_from: param shape {host.shape} != expected "
                    f"{sds.shape}")
            return jax.make_array_from_callback(
                sds.shape, sds.sharding,
                lambda idx: host[idx].astype(sds.dtype))

        from flax import traverse_util

        snap_has_lora = any(
            llamalib.is_lora_path(k)
            for k in traverse_util.flatten_dict(loaded))
        try:
            if self.cfg.model.lora_rank > 0 and not snap_has_lora:
                base_abs, lora_abs = llamalib.split_lora(abstract_params)
                base = jax.tree.map(put, base_abs, loaded)
                if adapters is None:
                    adapters = self._fresh_adapters(lora_abs)
                merged = dict(traverse_util.flatten_dict(base))
                merged.update(traverse_util.flatten_dict(adapters))
                return traverse_util.unflatten_dict(merged)
            return jax.tree.map(put, abstract_params, loaded)
        except ValueError as e:
            raise ValueError(
                f"init_from snapshot {self.cfg.init_from} does not match "
                f"the model's parameter tree: {e}") from None

    def _to_ckpt(self, state: Any) -> Any:
        """State as persisted: adapter-only under LoRA fine-tunes."""
        if not self._adapter_ckpt:
            return state
        _, adapters = llamalib.split_lora(state["params"])
        return {"step": state["step"], "opt_state": state["opt_state"],
                "adapters": adapters}

    def restore_or_init(self, seed: int = 0) -> Any:
        """Resume from the newest checkpoint if one exists — onto the
        CURRENT mesh, whatever topology wrote it (reshape-restore)."""
        if self.ckpt and self.ckpt.latest_step() is not None:
            abstract = self.abstract_state()
            if not self._adapter_ckpt:
                return self.ckpt.restore(abstract)
            _, lora_abs = llamalib.split_lora(abstract["params"])
            restored = self.ckpt.restore({
                "step": abstract["step"],
                "opt_state": abstract["opt_state"],
                "adapters": lora_abs,
            })
            params = self._pretrained_params(
                abstract["params"], adapters=restored["adapters"])
            return {"step": restored["step"], "params": params,
                    "opt_state": restored["opt_state"]}
        return self.init_state(seed)

    # -- step -------------------------------------------------------------

    def _loss_fn(self, params, tokens: jax.Array):
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        aux = None
        if self.mesh.shape.get("pipeline", 1) > 1:
            collect = (self.cfg.model.moe_experts > 0
                       and self.cfg.aux_loss_coef > 0)
            out = llamalib.pipelined_apply(
                self.cfg.model, params, inputs,
                mesh=self.mesh,
                num_microbatches=self.cfg.num_microbatches,
                with_aux=collect,
            )
            # MoE x PP: the balancing loss rides the schedule itself
            # (gpipe with_aux — masked per-tick sums, differentiable)
            logits, aux = out if collect else (out, None)
        elif self.cfg.model.moe_experts > 0 and self.cfg.aux_loss_coef > 0.0:
            # collect the sown Switch load-balancing loss — without this the
            # router has no balancing gradient and can collapse onto one
            # expert while the capacity factor silently drops the rest
            logits, mut = self.model.apply(
                {"params": params}, inputs, mutable=["intermediates"])
            total, count = _sum_aux_losses(mut["intermediates"])
            aux = total / jnp.maximum(count, 1)
        else:
            logits = self.model.apply({"params": params}, inputs)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets).mean()
        if aux is not None:
            loss = loss + self.cfg.aux_loss_coef * aux
        return loss

    def _grads_fn(self, params, tokens: jax.Array):
        """(loss, grads) for one global batch, microbatched when
        ``accum_steps > 1``.  Microbatches are strided slices of the batch
        dim (rows i, accum+i, ...) so each one stays evenly spread over the
        mesh's batch axes; grads accumulate in f32 regardless of param
        dtype and are averaged back to the param dtype at the end."""
        if (self.mesh.shape.get("pipeline", 1) > 1
                and self.cfg.pipeline_schedule == "1f1b"):
            # accum x 1F1B composes: each accum chunk runs the full 1F1B
            # round over its microbatches; grads average across chunks in
            # the same f32 scan as the non-pipelined path below
            grad_fn = self._pipeline_1f1b_grads
        else:
            grad_fn = jax.value_and_grad(self._loss_fn)
        accum = self.cfg.accum_steps
        if accum <= 1:
            return grad_fn(params, tokens)
        b = tokens.shape[0]
        if b % accum:
            raise ValueError(
                f"global_batch {b} not divisible by accum_steps {accum}")
        # each microbatch must still tile the mesh's batch shards exactly:
        # indivisible microbatches force XLA into its padded replicate-then-
        # repartition fallback, whose gather-gradient scatter is observed to
        # produce wrong embedding grads on the CPU SPMD backend — and it
        # would be a terrible layout on TPU anyway
        spec0 = self.batch_sharding.spec[0]
        axes = (spec0,) if isinstance(spec0, str) else (spec0 or ())
        n_shards = 1
        for a in axes:
            n_shards *= self.mesh.shape[a]
        if (b // accum) % n_shards:
            raise ValueError(
                f"microbatch {b // accum} (global_batch {b} / accum_steps "
                f"{accum}) not divisible by the mesh's {n_shards} batch shards")
        micro = tokens.reshape(b // accum, accum, -1).swapaxes(0, 1)
        micro = shardlib.constrain_microbatches(
            micro, self.mesh, self.batch_sharding)

        def body(carry, mb):
            acc_loss, acc = carry
            loss, grads = grad_fn(params, mb)
            acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), acc, grads)
            return (acc_loss + loss, acc), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (loss_sum, grad_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro)
        grads = jax.tree.map(
            lambda g, p: (g / accum).astype(p.dtype), grad_sum, params)
        return loss_sum / accum, grads

    def _pipeline_1f1b_grads(self, params, tokens: jax.Array):
        """(loss, grads) through the 1F1B pipeline executor: embedding runs
        data-parallel under ``jax.vjp``, the staged block stack goes through
        ``one_f_one_b`` (which owns its backward), and the head + loss live
        inside the schedule's last stage.  Numerically identical to the
        GPipe/single-mesh step (same blocks, same microbatch mean)."""
        from ..parallel import pipeline as pipelib

        mcfg = self.cfg.model
        if not mcfg.scan_layers:
            raise ValueError("pipeline schedules require scan_layers=True")
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        positions = jnp.arange(inputs.shape[-1])[None, :]
        embed = llamalib.Embedder(mcfg)
        x, embed_vjp = jax.vjp(
            lambda ep: embed.apply({"params": ep}, inputs), params["embedder"])

        collect = mcfg.moe_experts > 0 and self.cfg.aux_loss_coef > 0
        if collect:
            # MoE x 1F1B: the balancing loss + its gradient ride the
            # schedule's own fused backward (one_f_one_b with_aux)
            block_apply = llamalib.block_apply_with_aux(mcfg, positions)
            m = self.cfg.num_microbatches or self.mesh.shape["pipeline"]
            aux_weight = self.cfg.aux_loss_coef / (mcfg.num_layers * m)
        else:
            aux_weight = 0.0

            def block_apply(layer_params, h):
                return llamalib.Block(mcfg).apply(
                    {"params": layer_params}, h, positions)

        # tie_embeddings x 1F1B: the tied unembedding needs the embed
        # TABLE at the schedule's last stage.  Ride the existing head
        # machinery: bundle the table with the head params (replicated
        # over the pipeline axis like the head; its gradient comes back
        # psum'd through the same dhead path) and fold that gradient into
        # the embedder's below.
        if mcfg.tie_embeddings:
            head_bundle = {"head": params["head"],
                           "table": params["embedder"]["embedding"]}

            def loss_fn(hp, y, tgt):
                logits = llamalib.Head(mcfg).apply(
                    {"params": hp["head"]}, y, hp["table"])
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), tgt).mean()
        else:
            head_bundle = params["head"]

            def loss_fn(hp, y, tgt):
                logits = llamalib.Head(mcfg).apply({"params": hp}, y)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), tgt).mean()

        stacked = params["layers"]["block"]
        V = self.cfg.pipeline_interleave
        if V > 1:
            # interleaved layout: device d must hold model chunks
            # {d, P+d, ...}; permute the canonical layer axis to the
            # executor's device-contiguous order (and unpermute grads)
            perm = pipelib.interleave_permutation(
                mcfg.num_layers, self.mesh.shape["pipeline"], V)
            inv = jnp.asarray(np.argsort(perm))
            perm = jnp.asarray(perm)
            stacked = jax.tree.map(
                lambda a: jnp.take(a, perm, axis=0), stacked)
        loss, (dlayers, dhead, dx) = pipelib.one_f_one_b(
            block_apply, loss_fn, stacked, head_bundle,
            x, targets,
            mesh=self.mesh, num_microbatches=self.cfg.num_microbatches,
            remat=mcfg.remat, with_aux=collect, aux_weight=aux_weight,
            interleave=V)
        if V > 1:
            dlayers = jax.tree.map(
                lambda a: jnp.take(a, inv, axis=0), dlayers)
        (dembed,) = embed_vjp(dx)
        if mcfg.tie_embeddings:
            # the tied table earned gradient on BOTH paths: the embedding
            # lookup (embed_vjp) and the last-stage unembedding (dhead
            # bundle) — sum them, exactly as single-mesh autodiff would
            dembed = {**dembed, "embedding":
                      dembed["embedding"] + dhead["table"]}
            dhead = dhead["head"]
        return loss, {
            "embedder": dembed,
            "head": dhead,
            "layers": {"block": dlayers},
        }

    def _train_step(self, state, batch):
        loss, grads = self._grads_fn(state["params"], batch["tokens"])
        grad_norm = optax.global_norm(grads)
        updates, opt_state = self.tx.update(
            grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "step": state["step"] + 1, "params": params, "opt_state": opt_state}
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    def compiled_step(self) -> Callable:
        if self._step_fn is None:
            shardings = jax.tree.map(lambda a: a.sharding, self.abstract_state())
            self._step_fn = jax.jit(
                self._train_step,
                in_shardings=(shardings, {"tokens": self.batch_sharding}),
                out_shardings=(shardings, None),
                donate_argnums=(0,),
            )
        return self._step_fn

    # -- loop -------------------------------------------------------------

    def train(
        self,
        source: Optional[datalib.BatchSource] = None,
        on_metrics: Optional[Callable[[StepMetrics], None]] = None,
    ) -> StepMetrics:
        cfg = self.cfg
        source = source or datalib.SyntheticLm(
            cfg.global_batch, cfg.seq_len, cfg.model.vocab_size)
        # overlap restore/init with the step compile: the compile needs
        # only the ABSTRACT state, restore is IO + device_put — serial
        # they stack (recovery pays both, BASELINE restart metric), in
        # parallel the longer one hides the shorter (XLA compilation
        # releases the GIL)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=1) as ex:
            state_fut = ex.submit(self.restore_or_init)
            step_fn = self.compiled_step()
            state = state_fut.result()
        start_step = int(jax.device_get(state["step"]))
        n_chips = self.mesh.devices.size
        flops_tok = llamalib.flops_per_token(cfg.model, cfg.seq_len)
        peak = PEAK_TFLOPS.get(
            getattr(self.mesh.devices.flat[0], "device_kind", "cpu").lower(), 0.0)
        tokens_per_step = cfg.global_batch * cfg.seq_len

        batches = datalib.device_batches(
            source, self.batch_sharding, cfg.steps - start_step,
            start_step=start_step)
        # Save-on-preemption (SURVEY §5 failure detection; Tenplex-style
        # resume): SIGTERM — what the kubelet sends on pod deletion, gang
        # restart, or slice preemption — sets a flag; the loop checkpoints
        # and exits 143 (retryable) so the next incarnation resumes.
        self._preempted = False
        prev_handler = None
        handler_installed = False
        if self.ckpt and threading.current_thread() is threading.main_thread():
            def _on_sigterm(signum, frame):
                self._preempted = True
            prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            handler_installed = True

        # Steps are enqueued asynchronously and the host only blocks on
        # device results at log/profile boundaries: fetching the loss every
        # step serializes host round-trips into the device timeline (on a
        # remote-dispatch PJRT backend that is ~100ms/step) and hides none
        # of it.  Throughput is therefore metered per log window.
        try:
            metrics = self._run_loop(
                state, step_fn, batches, start_step,
                tokens_per_step, n_chips, flops_tok, peak, on_metrics)
        finally:
            if handler_installed:
                signal.signal(signal.SIGTERM, prev_handler)
        return metrics

    def _run_loop(self, state, step_fn, batches, start_step,
                  tokens_per_step, n_chips, flops_tok, peak, on_metrics):
        cfg = self.cfg
        metrics = None
        profiling = False
        window_t0 = time.perf_counter()
        window_steps = 0
        with shardlib.shard_context(self.mesh):
            for i, batch in enumerate(batches):
                step = start_step + i
                if cfg.profile_dir and step == cfg.profile_start:
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                state, out = step_fn(state, batch)  # async dispatch
                window_steps += 1
                if profiling and step + 1 >= cfg.profile_stop:
                    jax.device_get(out["loss"])  # drain before stopping
                    jax.profiler.stop_trace()
                    profiling = False
                sync = (step + 1) % cfg.log_every == 0 or step == cfg.steps - 1
                if sync:
                    loss = float(jax.device_get(out["loss"]))  # blocks
                    now = time.perf_counter()
                    dt = (now - window_t0) / window_steps
                    tps = tokens_per_step / dt
                    mfu = (
                        tps / n_chips * flops_tok / (peak * 1e12)
                        if peak else 0.0
                    )
                    metrics = StepMetrics(
                        step=step + 1,
                        loss=loss,
                        grad_norm=float(jax.device_get(out["grad_norm"])),
                        step_time_s=dt,
                        tokens_per_sec=tps,
                        tokens_per_sec_per_chip=tps / n_chips,
                        mfu=mfu,
                    )
                    window_t0 = now
                    window_steps = 0
                    if on_metrics:
                        on_metrics(metrics)
                if self.ckpt:
                    self.ckpt.save(step + 1, self._to_ckpt(state))
                if self._preempted and self.ckpt:
                    if step + 1 not in self.ckpt.all_steps():
                        self.ckpt.save(step + 1, self._to_ckpt(state),
                                       force=True)
                    self.ckpt.wait_until_finished()
                    raise SystemExit(143)
            if profiling:
                # loop ended inside the requested window (steps < stop, or
                # resume landed mid-window) — close the trace so the XPlane
                # is written and the global profiler session is released
                jax.profiler.stop_trace()
        if self.ckpt:
            # orbax force=True still refuses to overwrite an existing step,
            # so skip if the in-loop save already wrote the final step
            if cfg.steps not in self.ckpt.all_steps():
                self.ckpt.save(cfg.steps, self._to_ckpt(state), force=True)
            self.ckpt.wait_until_finished()
        self.final_state = state
        return metrics
