"""Real-corpus input pipeline over the native data-loader kernels.

The missing half of train/data.py's story: SyntheticLm keeps tests hermetic,
but a real pretrain reads a tokenized corpus.  This module provides it —
an mmap'd on-disk token corpus (documents + offsets), deterministic epoch
shuffling, GPT-style EOS-separated sequence packing, and per-process window
slicing into the same ``BatchSource`` protocol the trainer consumes.  The
hot loops run in C++ (kubeflow_tpu/native/dataloader.cpp, the reference's
PyTorch-DataLoader-worker analog) with exact-parity NumPy fallbacks, so
the corpus path works on any host and gets fast where g++ exists.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..native import load_library

# ---------------------------------------------------------------------------
# Kernels: native when available, NumPy parity fallback otherwise
# ---------------------------------------------------------------------------


def _splitmix64(state: np.uint64) -> tuple[np.uint64, np.uint64]:
    with np.errstate(over="ignore"):
        state = np.uint64(state + np.uint64(0x9E3779B97F4A7C15))
        z = state
        z = np.uint64((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9))
        z = np.uint64((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB))
        return state, np.uint64(z ^ (z >> np.uint64(31)))


def shuffle_indices(n: int, seed: int, *, force_fallback: bool = False) -> np.ndarray:
    """Deterministic Fisher-Yates permutation of [0, n) — identical output
    from the native and fallback paths (tested), so every host derives the
    same epoch order from the seed with no communication."""
    lib = None if force_fallback else load_library()
    out = np.empty(n, dtype=np.uint64)
    if lib is not None:
        lib.kft_shuffle_indices(
            n, np.uint64(seed),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return out
    out[:] = np.arange(n, dtype=np.uint64)
    state = np.uint64(seed)
    u64_max = np.uint64(0xFFFFFFFFFFFFFFFF)
    for i in range(n, 1, -1):
        bound = np.uint64(i)
        limit = np.uint64(u64_max - (u64_max % bound))
        while True:
            state, r = _splitmix64(state)
            if r < limit:
                break
        j = int(r % bound)
        out[i - 1], out[j] = out[j], out[i - 1]
    return out


def pack_sequences(
    tokens: np.ndarray,
    doc_offsets: np.ndarray,
    order: np.ndarray,
    eos: int,
    seq_len: int,
    row0: int,
    n_seqs: int,
    *,
    force_fallback: bool = False,
) -> tuple[np.ndarray, int]:
    """Rows [row0, row0+n_seqs) of the packed epoch stream.

    Returns (out[n_seqs, seq_len+1] int32, epoch_rows).  The stream is
    doc[order[0]] EOS doc[order[1]] EOS ..., EOS-padded at the tail.
    """
    tokens = np.ascontiguousarray(tokens, dtype=np.int32)
    doc_offsets = np.ascontiguousarray(doc_offsets, dtype=np.uint64)
    order = np.ascontiguousarray(order, dtype=np.uint64)
    row = seq_len + 1
    out = np.empty((n_seqs, row), dtype=np.int32)
    lib = None if force_fallback else load_library()
    if lib is not None:
        epoch_rows = lib.kft_pack_sequences(
            tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            doc_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(doc_offsets) - 1,
            order.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            np.int32(eos), seq_len, row0, n_seqs,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out, int(epoch_rows)
    # fallback: materialize the stream window naively
    lengths = (doc_offsets[1:] - doc_offsets[:-1]).astype(np.int64)
    stream_len = int((lengths[order.astype(np.int64)] + 1).sum())
    pieces = []
    for d in order:
        d = int(d)
        pieces.append(tokens[int(doc_offsets[d]): int(doc_offsets[d + 1])])
        pieces.append(np.array([eos], dtype=np.int32))
    stream = np.concatenate(pieces) if pieces else np.empty(0, np.int32)
    lo, hi = row0 * row, (row0 + n_seqs) * row
    window = stream[lo:hi]
    if len(window) < hi - lo:
        window = np.concatenate(
            [window, np.full((hi - lo) - len(window), eos, np.int32)])
    out[:] = window.reshape(n_seqs, row)
    return out, (stream_len + row - 1) // row


def gather_batch(
    data: np.ndarray, idx: np.ndarray, *, force_fallback: bool = False
) -> np.ndarray:
    """out[i] = data[idx[i]] for a 2D int32 array (batch assembly)."""
    data = np.ascontiguousarray(data, dtype=np.int32)
    idx = np.ascontiguousarray(idx, dtype=np.uint64)
    lib = None if force_fallback else load_library()
    if lib is None:
        return data[idx.astype(np.int64)]
    out = np.empty((len(idx), data.shape[1]), dtype=np.int32)
    lib.kft_gather_batch(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.shape[1],
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(idx),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


# ---------------------------------------------------------------------------
# On-disk token corpus
# ---------------------------------------------------------------------------

TOKENS_FILE = "tokens.npy"
OFFSETS_FILE = "offsets.npy"


class TokenCorpus:
    """A tokenized document corpus on disk, mmap'd for zero-copy reads.

    Layout: ``tokens.npy`` (int32, all documents concatenated) +
    ``offsets.npy`` (uint64, n_docs+1 prefix offsets) — the standard
    binary-corpus shape (Megatron/.bin+.idx, arrayrecord) minus the framing.
    """

    def __init__(self, tokens: np.ndarray, offsets: np.ndarray):
        self.tokens = tokens
        self.offsets = offsets

    @property
    def n_docs(self) -> int:
        return len(self.offsets) - 1

    @property
    def n_tokens(self) -> int:
        return int(self.offsets[-1])

    @classmethod
    def write(cls, path: str, docs: list[np.ndarray]) -> "TokenCorpus":
        os.makedirs(path, exist_ok=True)
        offsets = np.zeros(len(docs) + 1, dtype=np.uint64)
        for i, d in enumerate(docs):
            offsets[i + 1] = offsets[i] + len(d)
        tokens = (np.concatenate([np.asarray(d, np.int32) for d in docs])
                  if docs else np.empty(0, np.int32))
        np.save(os.path.join(path, TOKENS_FILE), tokens)
        np.save(os.path.join(path, OFFSETS_FILE), offsets)
        return cls.open(path)

    @classmethod
    def open(cls, path: str) -> "TokenCorpus":
        return cls(
            np.load(os.path.join(path, TOKENS_FILE), mmap_mode="r"),
            np.load(os.path.join(path, OFFSETS_FILE)),
        )


class PackedLmCorpus:
    """BatchSource over a TokenCorpus: shuffled, packed, process-sharded.

    Every process derives the same epoch permutation from (seed, epoch) and
    packs only its own rows of the epoch stream — disjoint global coverage
    with zero inter-host coordination, the same contract SyntheticLm keeps.
    ``local_batch(step)`` is resume-aware: any step index reproduces its
    batch exactly (checkpoint restore replays nothing).
    """

    def __init__(
        self,
        corpus: TokenCorpus,
        global_batch: int,
        seq_len: int,
        eos: int = 0,
        *,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        seed: int = 0,
    ):
        import jax

        self.corpus = corpus
        self.global_batch = global_batch
        self.seq_len = seq_len
        self.eos = eos
        self.proc = jax.process_index() if process_index is None else process_index
        self.nproc = jax.process_count() if process_count is None else process_count
        if global_batch % self.nproc:
            raise ValueError(
                f"global batch {global_batch} not divisible by {self.nproc}")
        self.local_bs = global_batch // self.nproc
        self.seed = seed
        row = seq_len + 1
        stream_len = corpus.n_tokens + corpus.n_docs  # + EOS separators
        epoch_rows = (stream_len + row - 1) // row
        #: full global batches per epoch (tail rows are dropped, like every
        #: fixed-shape LM pipeline; <1 batch of data is a config error)
        self.batches_per_epoch = epoch_rows // global_batch
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"corpus ({epoch_rows} rows) smaller than one global batch "
                f"({global_batch} rows of seq_len {seq_len})")
        self._epoch_cache: tuple[int, np.ndarray] = (-1, np.empty(0, np.uint64))

    def _order(self, epoch: int) -> np.ndarray:
        cached_epoch, cached = self._epoch_cache
        if cached_epoch != epoch:
            cached = shuffle_indices(self.corpus.n_docs, self.seed + epoch)
            self._epoch_cache = (epoch, cached)
        return cached

    def local_batch(self, step: int) -> dict[str, np.ndarray]:
        epoch, within = divmod(step, self.batches_per_epoch)
        row0 = within * self.global_batch + self.proc * self.local_bs
        out, _ = pack_sequences(
            self.corpus.tokens, self.corpus.offsets, self._order(epoch),
            self.eos, self.seq_len, row0, self.local_bs)
        return {"tokens": out}
