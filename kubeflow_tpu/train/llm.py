"""JaxJob entrypoint for LLM training: the packaged fine-tune/pretrain main.

The reference analog is the trainer container the SDK's ``train()`` injects
[upstream: training-operator -> sdk/python/kubeflow/training, train() v1.9
LLM path] — torch/peft behind a PyTorchJob.  Here: the Trainer over the
job's global mesh behind a JaxJob, config via env (the CRD-env contract the
controller injects, same channel the reference uses for MASTER_ADDR et al).

Env knobs (all optional):
  KFT_MODEL_PRESET  llama preset name (default "tiny")
  KFT_INIT_FROM     pretrained snapshot to fine-tune from: hf://org/name@rev
                    or file:///path (resolved through the storage
                    initializer).  The snapshot's config.json defines the
                    architecture; weights load before step 0; a newer
                    checkpoint in KFT_CKPT_DIR still wins (resume > init)
  KFT_STEPS, KFT_BATCH, KFT_SEQ_LEN, KFT_LR, KFT_CKPT_DIR, KFT_SAVE_EVERY
  KFT_CORPUS_DIR    tokenized TokenCorpus directory -> train on real data
                    through the native packing pipeline (train/native_data);
                    unset = hermetic SyntheticLm stream
  KFT_EOS_ID        EOS separator id for corpus packing (default 0)
  KFT_PBT_ROOT      population-based-training checkpoint root: this job
                    checkpoints under <root>/<job_name>, and when
                    KFT_RESUME_FROM names a sibling trial (the PBT
                    suggester's __parent assignment), its checkpoint is
                    forked before training — the exploit step
  KFT_RESUME_FROM   parent trial name to fork from ("" = fresh)
"""

from __future__ import annotations

import os

import jax

from ..models import llama as llamalib
from ..runtime import bootstrap
from . import trainer as trainlib


PBT_BASE_STEP_FILE = "pbt_base_step"


def _latest_step_on_disk(ckpt_dir: str) -> int:
    """Largest completed step directory (orbax layout: int-named subdirs);
    no CheckpointManager instantiation, so it is cheap and side-effect-free."""
    try:
        steps = [int(n) for n in os.listdir(ckpt_dir) if n.isdigit()]
    except OSError:
        return 0
    return max(steps, default=0)


def _pbt_checkpoint_dir(ctx: "bootstrap.PodContext") -> "str | None":
    """PBT checkpoint-fork contract: own dir under KFT_PBT_ROOT; exploit =
    copy the parent trial's checkpoints before first save/restore.  Only
    the coordinator forks; every rank then syncs before restoring.  The
    fork baseline step is recorded ONCE (``pbt_base_step``) so a gang
    restart mid-trial keeps the original training horizon instead of
    re-deriving it from the live checkpoint dir."""
    import shutil

    root = os.environ.get("KFT_PBT_ROOT")
    if not root:
        return None
    own = os.path.join(root, ctx.job_name)
    parent = os.environ.get("KFT_RESUME_FROM", "").strip()
    if ctx.is_coordinator and not os.path.isdir(own):
        if parent:
            parent_dir = os.path.join(root, parent)
            if not os.path.isdir(parent_dir):
                # a fork of nothing must fail, not silently train from
                # scratch while ranked against continued lineages
                raise RuntimeError(
                    f"PBT fork parent {parent!r} has no checkpoint dir "
                    f"under {root}; refusing to start from scratch")
            shutil.copytree(parent_dir, own)
        else:
            os.makedirs(own, exist_ok=True)
        # overwrite any marker copied from the parent: OUR baseline is the
        # parent's latest step, not the parent's own fork baseline
        with open(os.path.join(own, PBT_BASE_STEP_FILE), "w") as f:
            f.write(str(_latest_step_on_disk(own)))
    if ctx.num_processes > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"{ctx.job_name}-pbt-fork")
    return own


def _pbt_base_step(ckpt_dir: str) -> int:
    try:
        with open(os.path.join(ckpt_dir, PBT_BASE_STEP_FILE)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0


def config_from_env(ctx: "bootstrap.PodContext") -> trainlib.TrainConfig:
    e = os.environ
    init_from = e.get("KFT_INIT_FROM") or None
    if init_from:
        # the literal "stock Llama fine-tune" UX (SURVEY §3.5): resolve
        # hf://org/name@rev (or file://) through the storage initializer
        # and take the ARCHITECTURE from the snapshot — KFT_MODEL_PRESET
        # is ignored so the job can never fine-tune a mismatched shape
        from ..serving.storage import download

        init_from = download(init_from)
        model = llamalib.load_pretrained_config(init_from)
    else:
        preset = e.get("KFT_MODEL_PRESET", "tiny")
        model = llamalib.PRESETS[preset]()
    lora_rank = int(e.get("KFT_LORA_RANK", "0"))
    if lora_rank > 0:
        # LoRA fine-tune (SURVEY §3.5 peft path): adapters on the
        # snapshot's architecture; the trainer freezes the base
        import dataclasses as _dc

        model = _dc.replace(model, lora_rank=lora_rank)
    ckpt_dir = _pbt_checkpoint_dir(ctx) or e.get("KFT_CKPT_DIR") or None
    steps = int(e.get("KFT_STEPS", "10"))
    if e.get("KFT_PBT_ROOT") and ckpt_dir:
        # PBT semantics: KFT_STEPS means "this many MORE steps" past the
        # fork baseline recorded at fork time — stable across gang restarts
        steps += _pbt_base_step(ckpt_dir)
    return trainlib.TrainConfig(
        model=model,
        init_from=init_from,
        mesh_axes=dict(ctx.mesh_axes),
        global_batch=int(e.get("KFT_BATCH", "8")),
        seq_len=int(e.get("KFT_SEQ_LEN", "64")),
        steps=steps,
        learning_rate=float(e.get("KFT_LR", "3e-4")),
        warmup_steps=int(e.get("KFT_WARMUP", "5")),
        checkpoint_dir=ckpt_dir,
        save_interval_steps=int(e.get("KFT_SAVE_EVERY", "100")),
        log_every=int(e.get("KFT_LOG_EVERY", "5")),
    )


def source_from_env(cfg: trainlib.TrainConfig):
    """KFT_CORPUS_DIR -> PackedLmCorpus over the native loader; else None
    (the trainer defaults to the hermetic synthetic stream)."""
    corpus_dir = os.environ.get("KFT_CORPUS_DIR")
    if not corpus_dir:
        return None
    from .native_data import PackedLmCorpus, TokenCorpus

    corpus = TokenCorpus.open(corpus_dir)
    if corpus.n_tokens and int(corpus.tokens.max()) >= cfg.model.vocab_size:
        # fail fast: out-of-range ids would be silently clamped by the
        # embedding gather and the job would "succeed" on garbage
        raise ValueError(
            f"corpus {corpus_dir} has token id {int(corpus.tokens.max())} "
            f">= model vocab_size {cfg.model.vocab_size}; pick a larger "
            "KFT_MODEL_PRESET or retokenize")
    return PackedLmCorpus(
        corpus,
        cfg.global_batch,
        cfg.seq_len,
        eos=int(os.environ.get("KFT_EOS_ID", "0")),
    )


def train_main(ctx: "bootstrap.PodContext") -> None:
    """Runs on every worker; emits per-step metrics from the coordinator."""
    cfg = config_from_env(ctx)
    t = trainlib.Trainer(cfg)
    if ctx.is_coordinator and t.ckpt is not None:
        # observable resume marker: >0 after a gang restart picked up a
        # checkpoint (the fault-injection e2e asserts step continuity on it)
        bootstrap.emit_metric(ctx, "resume_step", t.ckpt.latest_step() or 0)

    def on_metrics(m: trainlib.StepMetrics) -> None:
        if ctx.is_coordinator:
            bootstrap.emit_metric(ctx, "loss", m.loss, step=m.step)
            bootstrap.emit_metric(
                ctx, "tokens_per_sec_per_chip", m.tokens_per_sec_per_chip,
                step=m.step)

    final = t.train(source=source_from_env(cfg), on_metrics=on_metrics)
    if ctx.is_coordinator and final is not None:
        bootstrap.emit_metric(ctx, "final_loss", final.loss)
        bootstrap.emit_metric(ctx, "mfu", final.mfu)
    publish_to = os.environ.get("KFT_PUBLISH_TO")
    if publish_to and t.final_state is not None:
        # publish the trained model as a serving snapshot: adapter-only
        # under LoRA (MB-scale, save_adapter), full save_pretrained
        # otherwise.  Every process gathers (the collective is global);
        # only the coordinator writes.
        from jax.experimental import multihost_utils

        params = t.final_state["params"]
        if cfg.model.lora_rank > 0:
            # only the MB-scale adapters publish — gathering the frozen
            # base would move GBs per host just to throw them away
            _, params = llamalib.split_lora(params)
        if ctx.num_processes > 1:
            params = jax.tree.map(
                lambda x: multihost_utils.process_allgather(x, tiled=True),
                params)
        else:
            params = jax.device_get(params)
        if ctx.is_coordinator:
            if cfg.model.lora_rank > 0:
                llamalib.save_adapter(publish_to, cfg.model, params)
            else:
                llamalib.save_pretrained(publish_to, cfg.model, params)
            bootstrap.emit_metric(ctx, "published", 1.0)
    # every process syncs before exit so Succeeded means "all ranks done"
    if ctx.num_processes > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"{ctx.job_name}-train-done")
