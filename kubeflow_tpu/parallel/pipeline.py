"""GPipe-style pipeline parallelism over the ``pipeline`` mesh axis.

The reference supports PP only as an orchestration contract (the operator
guarantees gang + env; Megatron/DeepSpeed do the scheduling inside user
containers) [SURVEY.md §2.5 PP row].  Here the schedule itself is
TPU-native: the scanned layer stack's leading dim is already sharded over
``pipeline`` (the ``("layers", "pipeline")`` logical rule), so each device
holds a contiguous stage of layers; this module adds the microbatch
schedule — a ``shard_map`` manual over *only* the pipeline axis, with
``lax.ppermute`` passing activations stage-to-stage, while every other
mesh axis (data/fsdp/model/seq) stays in GSPMD auto mode so ZeRO gathers
and TP collectives keep working inside each stage.

Why this shape: the pipeline axis is the DCN-tolerant one (mesh.py) — an
activation crosses a slice boundary once per microbatch per stage, which
amortizes over the whole stage's compute; the schedule is classic GPipe
(fill, steady state, drain: M + P - 1 ticks for M microbatches over P
stages).  Backward runs the reverse pipeline automatically: ``ppermute``
transposes to the opposite ring and ``lax.scan`` reverses.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import current_mesh

AXIS = "pipeline"


def pipeline_degree(mesh: Optional[Mesh]) -> int:
    if mesh is None or AXIS not in mesh.axis_names:
        return 1
    return mesh.shape[AXIS]


def gpipe(
    block_apply: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    num_microbatches: Optional[int] = None,
    remat: bool = True,
    with_aux: bool = False,
):
    """Run ``num_layers`` blocks over ``x`` as a P-stage microbatch pipeline.

    ``block_apply(layer_params, x) -> x`` applies ONE block given one
    layer's param subtree.  ``stacked_params`` is the scan-stacked tree
    (leading dim = num_layers, sharded over the ``pipeline`` mesh axis so
    each device already holds its stage's layers — no weight movement).
    ``x``: [batch, ...] activations, batch divisible by the microbatch
    count (default: the pipeline degree).

    With ``with_aux=True``, ``block_apply(lp, x) -> (x, aux_scalar)`` and
    the call returns ``(out, aux_sum)`` where ``aux_sum`` is the sum of
    every block's aux over all layers and microbatches — garbage
    fill/drain ticks are masked out, and the sum is differentiable, so a
    MoE load-balancing loss collected this way trains exactly like the
    single-mesh path (SURVEY §2.5 EP x PP composition).

    Falls back to a plain sequential scan when no pipeline axis is active,
    so callers can use it unconditionally.
    """
    mesh = mesh or current_mesh()
    p_size = pipeline_degree(mesh)

    if not with_aux:
        plain = block_apply
        block_apply = lambda lp, h: (plain(lp, h), jnp.zeros((), jnp.float32))  # noqa: E731

    one = jax.checkpoint(block_apply) if remat else block_apply

    def apply_stage(layers, h):
        def body(carry, lp):
            h, aux = carry
            h, a = one(lp, h)
            return (h, aux + a.astype(jnp.float32)), None
        (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)), layers)
        return h, aux

    if p_size == 1:
        out, aux = apply_stage(stacked_params, x)
        return (out, aux) if with_aux else out

    m = num_microbatches or p_size
    batch = x.shape[0]
    if batch % m:
        raise ValueError(
            f"batch {batch} not divisible by {m} microbatches")
    x_mb = x.reshape(m, batch // m, *x.shape[1:])

    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % p_size:
        raise ValueError(
            f"{num_layers} layers not divisible by {p_size} pipeline stages")

    layer_specs = jax.tree.map(lambda _: P(AXIS), stacked_params)
    perm = [(i, i + 1) for i in range(p_size - 1)]

    def body(local_layers, x_mb):
        stage = lax.axis_index(AXIS)
        state = jnp.zeros_like(x_mb[0])
        out_buf = jnp.zeros_like(x_mb)

        def tick(carry, t):
            state, out_buf, aux_acc = carry
            # stage 0 ingests microbatch t during the fill/steady phase
            inp = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            cur = jnp.where(stage == 0, inp, state)
            y, aux = apply_stage(local_layers, cur)
            # a stage's tick t processes microbatch t - stage; outside
            # [0, m) it is fill/drain garbage whose aux must not count
            real = jnp.logical_and(t - stage >= 0, t - stage < m)
            aux_acc = aux_acc + jnp.where(real, aux, 0.0)
            # last stage emits microbatch t-(P-1) once the fill completes
            widx = t - (p_size - 1)
            upd = lax.dynamic_update_index_in_dim(
                out_buf, y, jnp.clip(widx, 0, m - 1), 0)
            emit = jnp.logical_and(widx >= 0, stage == p_size - 1)
            out_buf = jnp.where(emit, upd, out_buf)
            nxt = lax.ppermute(y, AXIS, perm)
            return (nxt, out_buf, aux_acc), None

        (_, out_buf, aux_acc), _ = lax.scan(
            tick, (state, out_buf, jnp.zeros((), jnp.float32)),
            jnp.arange(m + p_size - 1))
        # broadcast the finished buffer from the last stage to every rank
        # (the head/loss run data-parallel on all devices afterwards);
        # aux sums over stages (each stage owns its layers' aux)
        out_buf = lax.psum(
            jnp.where(stage == p_size - 1, out_buf, jnp.zeros_like(out_buf)),
            AXIS,
        )
        return out_buf, lax.psum(aux_acc, AXIS)

    out, aux = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, P()),
        out_specs=(P(), P()),
        axis_names={AXIS},
        check_vma=False,
    )(stacked_params, x_mb)
    out = out.reshape(batch, *x.shape[1:])
    return (out, aux) if with_aux else out


def interleave_permutation(num_layers: int, p: int, v: int) -> np.ndarray:
    """Layer-axis permutation for the interleaved executor.

    ``perm[new] = canonical`` such that taking the canonical stacked
    layers at ``perm`` yields device-contiguous storage: device d's slice
    holds model chunks {d, P+d, ..., (V-1)P+d} in local order.  The
    inverse (for gradients) is ``np.argsort(perm)``.  On TPU this is one
    weight reshard per step (cheap over ICI; over DCN it is charged in
    the projection model — BASELINE.md).
    """
    if num_layers <= 0 or num_layers % (p * v):
        raise ValueError(
            f"{num_layers} layers not divisible by {p} stages x {v} chunks")
    cl = num_layers // (p * v)
    order = []
    for d in range(p):
        for lv in range(v):
            c = lv * p + d
            order.extend(range(c * cl, (c + 1) * cl))
    return np.asarray(order, np.int32)


# -- 1F1B (perf-grade schedule) ---------------------------------------------


@dataclasses.dataclass(frozen=True)
class Schedule1F1B:
    """Static 1F1B tick tables for ``p`` devices x ``m`` microbatches x
    ``v`` virtual stages per device (Megatron interleaving; v=1 is the
    classic non-interleaved schedule).

    The model's ``p*v`` chunks are assigned round-robin: chunk ``c`` runs
    on device ``c % p`` as its local chunk ``c // p`` — consecutive chunks
    sit on consecutive devices, so activations ride the same +1 ppermute
    ring (with a wraparound edge for the chunk-(kP-1) -> chunk-(kP)
    transition).  Each tick is one chunk-fwd slot + one chunk-bwd slot
    per device; tables give, per [tick, device]:

    - ``fwd``/``fwd_lv``: microbatch + local chunk of the fwd slot (-1 idle)
    - ``fwd_slot``: act-stash slot holding the input (-1 = read x_mb,
      i.e. model chunk 0)
    - ``fwd_seed_slot``: grad-stash slot to seed with the loss cotangent
      (>=0 only when the slot forwards the LAST model chunk)
    - ``bwd``/``bwd_lv``/``bwd_slot``/``bwd_gslot``: same for the bwd slot
    - ``ra_slot``/``rg_slot``: stash slot the activation/cotangent
      arriving over the ring this tick is written to (-1 = ignore)

    ``act_slots``/``grad_slots`` are exact stash high-waters from the
    simulation — the schedule's memory bound, reported (not assumed).
    """

    p: int
    m: int
    v: int
    fwd: np.ndarray           # [T, P] microbatch (-1 idle)
    fwd_lv: np.ndarray        # [T, P] local chunk index
    fwd_slot: np.ndarray      # [T, P] act slot (-1 = x_mb)
    fwd_seed_slot: np.ndarray  # [T, P] grad slot to seed (-1 = not last)
    bwd: np.ndarray
    bwd_lv: np.ndarray
    bwd_slot: np.ndarray
    bwd_gslot: np.ndarray
    ra_slot: np.ndarray
    rg_slot: np.ndarray
    act_slots: int
    grad_slots: int

    @property
    def ticks(self) -> int:
        return self.fwd.shape[0]

    @property
    def useful_fraction(self) -> float:
        """Filled fwd+bwd slots over total slots (1 - bubble fraction).
        Slot units are CHUNK work items: at v>1 a device fills m*v of
        each direction, so fractions compare across v."""
        filled = int((self.fwd >= 0).sum() + (self.bwd >= 0).sum())
        return filled / (2 * self.ticks * self.p)


class _SlotPool:
    """Exact slot allocator: reuse the lowest free slot, track high-water."""

    def __init__(self):
        self.free: list[int] = []
        self.next = 0
        self.high = 0

    def alloc(self) -> int:
        if self.free:
            return self.free.pop()
        s = self.next
        self.next += 1
        self.high = max(self.high, self.next)
        return s

    def release(self, s: int) -> None:
        self.free.append(s)


def schedule_1f1b(p: int, m: int, v: int = 1,
                  cap: Optional[int] = None) -> Schedule1F1B:
    """Simulate the (interleaved) 1F1B schedule event-by-event and emit
    static tick tables plus exact stash-slot assignments.

    Rules (Megatron-style, adapted to a lockstep SPMD program with a
    1-tick ppermute latency):

    - fwd work follows the Megatron interleaved order — rounds of P
      microbatches per chunk, lower chunks first within a round — as
      activations arrive, throttled so a device holds at most ``cap``
      forwarded-not-yet-backwarded chunk inputs (the memory throttle;
      default p+2 at v=1, p+2+(v-1) interleaved);
    - bwd work follows the mirrored order (higher chunks first within a
      round) as cotangents arrive; the device owning the LAST model chunk
      seeds that cotangent from the loss at forward time, so it can run
      fwd and bwd of the same microbatch in one tick;
    - within a tick the fwd slot runs before the bwd slot, and a
      bwd-completing-this-tick frees its in-flight slot for the fwd
      admission check.
    """
    if p < 1 or m < 1 or v < 1:
        raise ValueError("need p >= 1, m >= 1, v >= 1")
    C = p * v
    # default throttle = the warmup depth the latency-optimal schedule
    # needs (p*v + 2; p + 2 at v=1, the classic 1F1B bound).  The
    # simulator achieves the model's exact lower bound T = mv + p + pv - 2
    # at this cap (see PERF.md "interleaved 1F1B" for the bound's proof).
    cap = cap if cap is not None else min(p * v + 2, m * v)

    next_f = [0] * C
    next_b = [0] * C
    recv_act = [set() for _ in range(C)]   # mb whose input arrived
    recv_grad = [set() for _ in range(C)]  # mb whose cotangent arrived
    fwd_done = [set() for _ in range(C)]
    act_slot_of: dict[tuple[int, int], int] = {}
    grad_slot_of: dict[tuple[int, int], int] = {}
    act_pool = [_SlotPool() for _ in range(p)]
    grad_pool = [_SlotPool() for _ in range(p)]

    def fkey(c: int, mb: int) -> tuple:
        # Megatron interleaved fwd order: rounds of p microbatches per
        # chunk, chunk-major within the round
        return (mb // p, c // p, mb % p, c)

    def bkey(c: int, mb: int) -> tuple:
        # mirrored for bwd: higher chunks drain first within a round
        return (mb // p, (v - 1) - c // p, mb % p, c)

    rows: dict[str, list] = {k: [] for k in (
        "fwd", "fwd_lv", "fwd_slot", "fwd_seed_slot",
        "bwd", "bwd_lv", "bwd_slot", "bwd_gslot", "ra", "rg")}
    # deliveries computed at tick t land in the tables at t+1
    pending_ra = [-1] * p
    pending_rg = [-1] * p

    t = 0
    while any(nb < m for nb in next_b):
        frow = [-1] * p
        flv = [-1] * p
        fslot = [-1] * p
        fseed = [-1] * p
        brow = [-1] * p
        blv = [-1] * p
        bslot = [-1] * p
        bgslot = [-1] * p
        rows["ra"].append(list(pending_ra))
        rows["rg"].append(list(pending_rg))
        pending_ra = [-1] * p
        pending_rg = [-1] * p

        fwd_chosen: list[Optional[tuple[int, int]]] = [None] * p
        bwd_chosen: list[Optional[tuple[int, int]]] = [None] * p
        for d in range(p):
            chunks = [c for c in range(d, C, p)]
            # tentative bwd readiness (ignoring this tick's own fwd seed)
            ready0 = [
                (c, next_b[c]) for c in chunks
                if next_b[c] < m and (
                    (c < C - 1 and next_b[c] in recv_grad[c])
                    or (c == C - 1 and next_b[c] in fwd_done[c]))
            ]
            in_flight = sum(next_f[c] - next_b[c] for c in chunks)
            fcands = [
                (c, next_f[c]) for c in chunks
                if next_f[c] < m and next_f[c] < next_b[c] + m  # sanity
                and (c == 0 or next_f[c] in recv_act[c])
            ]
            if fcands and in_flight - (1 if ready0 else 0) < cap:
                c, mb = min(fcands, key=lambda cm: fkey(*cm))
                fwd_chosen[d] = (c, mb)
            # bwd: include a same-tick seed from this tick's fwd
            bcands = list(ready0)
            fc = fwd_chosen[d]
            if (fc is not None and fc[0] == C - 1
                    and next_b[C - 1] == fc[1]
                    and all((cc, mm) != fc for cc, mm in bcands)):
                bcands.append(fc)
            if bcands:
                bwd_chosen[d] = min(bcands, key=lambda cm: bkey(*cm))

        for d in range(p):
            fc = fwd_chosen[d]
            if fc is not None:
                c, mb = fc
                frow[d], flv[d] = mb, c // p
                fslot[d] = act_slot_of.get((c, mb), -1) if c > 0 else -1
                next_f[c] += 1
                fwd_done[c].add(mb)
                if c == C - 1:
                    s = grad_pool[d].alloc()
                    grad_slot_of[(c, mb)] = s
                    fseed[d] = s
                    recv_grad[c].add(mb)
            bc = bwd_chosen[d]
            if bc is not None:
                c, mb = bc
                brow[d], blv[d] = mb, c // p
                bslot[d] = act_slot_of.get((c, mb), -1) if c > 0 else -1
                bgslot[d] = grad_slot_of[(c, mb)]
                next_b[c] += 1
                # frees happen at end of tick (slot read during the tick)

        # deliveries (land next tick) + slot frees
        for d in range(p):
            fc = fwd_chosen[d]
            if fc is not None:
                c, mb = fc
                if c + 1 < C:
                    d2 = (c + 1) % p
                    s = act_pool[d2].alloc()
                    act_slot_of[(c + 1, mb)] = s
                    pending_ra[d2] = s
                    recv_act[c + 1].add(mb)
            bc = bwd_chosen[d]
            if bc is not None:
                c, mb = bc
                if c - 1 >= 0:
                    d2 = (c - 1) % p
                    s = grad_pool[d2].alloc()
                    grad_slot_of[(c - 1, mb)] = s
                    pending_rg[d2] = s
                    recv_grad[c - 1].add(mb)
                # free the consumed stash entries
                if c > 0:
                    act_pool[d].release(act_slot_of.pop((c, mb)))
                grad_pool[d].release(grad_slot_of.pop((c, mb)))

        for key, row in (("fwd", frow), ("fwd_lv", flv), ("fwd_slot", fslot),
                         ("fwd_seed_slot", fseed), ("bwd", brow),
                         ("bwd_lv", blv), ("bwd_slot", bslot),
                         ("bwd_gslot", bgslot)):
            rows[key].append(row)
        t += 1
        if t > 4 * (m * v + p) + 16 * v:
            raise RuntimeError(
                f"1F1B schedule deadlocked at p={p} m={m} v={v} cap={cap}")

    arr = {k: np.array(rows[k], np.int32) for k in rows}
    return Schedule1F1B(
        p=p, m=m, v=v,
        fwd=arr["fwd"], fwd_lv=arr["fwd_lv"], fwd_slot=arr["fwd_slot"],
        fwd_seed_slot=arr["fwd_seed_slot"],
        bwd=arr["bwd"], bwd_lv=arr["bwd_lv"], bwd_slot=arr["bwd_slot"],
        bwd_gslot=arr["bwd_gslot"],
        ra_slot=arr["ra"], rg_slot=arr["rg"],
        act_slots=max(1, max(pl.high for pl in act_pool)),
        grad_slots=max(1, max(pl.high for pl in grad_pool)),
    )


def one_f_one_b(
    block_apply: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[..., jax.Array],
    stacked_params: Any,
    head_params: Any,
    x: jax.Array,
    loss_args: Any,
    *,
    mesh: Optional[Mesh] = None,
    num_microbatches: Optional[int] = None,
    remat: bool = True,
    with_aux: bool = False,
    aux_weight: float = 0.0,
    interleave: int = 1,
):
    """Loss **and grads** of a staged block stack under the 1F1B schedule.

    ``loss = mean_mb loss_fn(head_params, blocks(x_mb), loss_args_mb)``;
    returns ``(loss, (d_stacked_params, d_head_params, d_x))``.

    Why a fused value-and-grad instead of a differentiable forward (what
    ``gpipe`` is): 1F1B's defining property is that microbatch i's
    *backward* runs while microbatch i+k's *forward* is still in flight,
    bounding in-flight activations at ~P per stage instead of M.  Under
    ``jax.grad`` the whole forward completes before any backward starts
    (GPipe), so the schedule must own its backward: each backward tick
    re-runs the stage forward from the stashed input (full within-stage
    remat) through ``jax.vjp`` and sends the input-cotangent upstream
    over the reverse ``ppermute`` ring.

    ``loss_fn(head_params, y_mb, args_mb) -> scalar`` runs at the last
    stage (masked elsewhere — SPMD lockstep executes it everywhere, so
    keep the head small relative to a stage; at T/M > 1 ticks per useful
    microbatch the head overhead multiplies).  ``loss_args`` is a pytree
    whose leaves lead with the batch dim (e.g. targets), microbatched
    like ``x``.

    ``interleave=V`` runs the Megatron interleaved schedule: each device
    owns V non-contiguous model chunks (chunk c on device c % P), cutting
    the fill/drain bubble from P-1 stage-times to P-1 CHUNK-times —
    useful fraction MV/(MV+2(P-1)) vs M/(M+2(P-1)).  NOTE the layer
    assignment: the executor interprets each device's contiguous
    ``stacked_params`` slice as its V chunks in local order, i.e. device
    d's layers serve model chunks {d, P+d, ..., (V-1)P+d}.  Callers that
    need canonical model order (the trainer) must permute the stacked
    layer axis accordingly before the call and unpermute the gradients
    after (``interleave_permutation``).

    ``with_aux=True``: ``block_apply(lp, h) -> (h, aux_scalar)`` and the
    total loss gains ``aux_weight * sum(aux over layers, microbatches)``;
    the aux gradient rides the schedule's own backward VJPs.
    """
    mesh = mesh or current_mesh()
    p_size = pipeline_degree(mesh)

    if not with_aux:
        plain = block_apply
        block_apply = lambda lp, h: (plain(lp, h), jnp.zeros((), jnp.float32))  # noqa: E731

    one = jax.checkpoint(block_apply) if remat else block_apply

    def apply_stage(layers, h):
        def body(carry, lp):
            h, aux = carry
            h, a = one(lp, h)
            return (h, aux + a.astype(jnp.float32)), None
        (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)), layers)
        return h, aux

    if p_size == 1:
        def seq_loss(sp, hp, xx):
            y, aux = apply_stage(sp, xx)
            return loss_fn(hp, y, loss_args) + aux_weight * aux
        loss, grads = jax.value_and_grad(seq_loss, argnums=(0, 1, 2))(
            stacked_params, head_params, x)
        return loss, grads

    m = num_microbatches or p_size
    batch = x.shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by {m} microbatches")
    x_mb = x.reshape(m, batch // m, *x.shape[1:])
    args_mb = jax.tree.map(
        lambda a: a.reshape(m, batch // m, *a.shape[1:]), loss_args)

    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % (p_size * interleave):
        raise ValueError(
            f"{num_layers} layers not divisible by {p_size} stages x "
            f"{interleave} virtual chunks")

    sched = schedule_1f1b(p_size, m, v=interleave)
    C, Cg = sched.act_slots, sched.grad_slots
    cl = num_layers // (p_size * interleave)  # layers per chunk
    tbls = tuple(jnp.asarray(a) for a in (
        sched.fwd, sched.fwd_lv, sched.fwd_slot, sched.fwd_seed_slot,
        sched.bwd, sched.bwd_lv, sched.bwd_slot, sched.bwd_gslot,
        sched.ra_slot, sched.rg_slot))

    layer_specs = jax.tree.map(lambda _: P(AXIS), stacked_params)
    # interleaved: full +1 / -1 rings — the wraparound edges carry the
    # chunk-(kP-1) -> chunk-(kP) handoff.  Non-interleaved: OPEN chains;
    # a wrap edge would still be executed every tick (recv slots are
    # traced, so XLA cannot elide it) and at P=2-over-DCN that useless
    # transfer would double the pipeline's DCN bill.
    if interleave > 1:
        perm_fwd = [(i, (i + 1) % p_size) for i in range(p_size)]
        perm_bwd = [((i + 1) % p_size, i) for i in range(p_size)]
    else:
        perm_fwd = [(i, i + 1) for i in range(p_size - 1)]
        perm_bwd = [(i + 1, i) for i in range(p_size - 1)]

    def body(local_layers, head_p, x_mb, args_mb):
        stage = lax.axis_index(AXIS)
        mb_shape = x_mb.shape[1:]

        def chunk_apply(layers_full, h, lv):
            """One model CHUNK (cl layers at local offset lv) — the unit
            the interleaved schedule executes; v=1 makes it the stage."""
            layers_c = jax.tree.map(
                lambda a: lax.dynamic_slice_in_dim(a, lv * cl, cl, axis=0),
                layers_full)
            return apply_stage(layers_c, h)

        acts_buf = jnp.zeros((C, *mb_shape), x_mb.dtype)
        grads_buf = jnp.zeros((Cg, *mb_shape), x_mb.dtype)
        y_prev = jnp.zeros(mb_shape, x_mb.dtype)
        dh_prev = jnp.zeros(mb_shape, x_mb.dtype)
        loss_acc = jnp.zeros((), jnp.float32)
        aux_acc = jnp.zeros((), jnp.float32)
        dlayers_acc = jax.tree.map(
            lambda l: jnp.zeros(l.shape, l.dtype), local_layers)
        dhead_acc = jax.tree.map(
            lambda h: jnp.zeros(h.shape, h.dtype), head_p)
        dx_buf = jnp.zeros_like(x_mb)

        loss_vag = jax.value_and_grad(loss_fn, argnums=(1, 0))

        def tick(carry, rows):
            (acts_buf, grads_buf, y_prev, dh_prev,
             loss_acc, aux_acc, dlayers_acc, dhead_acc, dx_buf) = carry
            (f, f_lv, f_slot, f_seed, b, b_lv, b_slot, b_gslot,
             ra, rg) = (jnp.take(r, stage) for r in rows)

            # 1. receive activation sent over the ring last tick
            in_act = lax.ppermute(y_prev, AXIS, perm_fwd)
            sra = jnp.maximum(ra, 0)
            acts_buf = acts_buf.at[sra].set(
                jnp.where(ra >= 0, in_act, acts_buf[sra]))
            # 2. receive cotangent sent over the reverse ring last tick
            in_grad = lax.ppermute(dh_prev, AXIS, perm_bwd)
            srg = jnp.maximum(rg, 0)
            grads_buf = grads_buf.at[srg].set(
                jnp.where(rg >= 0, in_grad, grads_buf[srg]))

            # 3. forward slot (masked garbage when f == -1);
            #    f_slot == -1 means "input is x_mb" (model chunk 0)
            fidx = jnp.clip(jnp.maximum(f, 0), 0, m - 1)
            h_in_f = jnp.where(
                f_slot < 0, x_mb[fidx], acts_buf[jnp.maximum(f_slot, 0)])
            y, aux_f = chunk_apply(local_layers, h_in_f, jnp.maximum(f_lv, 0))
            # aux counts only real forward slots (f == -1 is bubble junk)
            aux_acc = aux_acc + jnp.where(f >= 0, aux_f, 0.0)
            # the LAST model chunk seeds its own cotangent from the loss
            a_f = jax.tree.map(lambda a: a[fidx], args_mb)
            loss_f, (dy_f, dhead_f) = loss_vag(head_p, y, a_f)
            seed = f_seed >= 0
            sfs = jnp.maximum(f_seed, 0)
            grads_buf = grads_buf.at[sfs].set(
                jnp.where(seed, (dy_f / m).astype(grads_buf.dtype),
                          grads_buf[sfs]))
            loss_acc = loss_acc + jnp.where(seed, loss_f / m, 0.0)
            dhead_acc = jax.tree.map(
                lambda a, g: a + jnp.where(seed, g / m, 0.0).astype(a.dtype),
                dhead_acc, dhead_f)

            # 4. backward slot: re-run the chunk fwd from the stashed input
            bidx = jnp.clip(jnp.maximum(b, 0), 0, m - 1)
            h_in_b = jnp.where(
                b_slot < 0, x_mb[bidx], acts_buf[jnp.maximum(b_slot, 0)])
            dy_b = grads_buf[jnp.maximum(b_gslot, 0)]
            blv = jnp.maximum(b_lv, 0)
            _, chunk_vjp = jax.vjp(
                lambda L, h: chunk_apply(L, h, blv), local_layers, h_in_b)
            b_ok = b >= 0
            # cotangents: (d loss/d y, d loss/d aux) — the aux term's
            # gradient rides the same within-chunk VJP
            aux_ct = jnp.where(b_ok, jnp.float32(aux_weight), 0.0)
            dlayers_b, dh_b = chunk_vjp((dy_b, aux_ct))
            dlayers_acc = jax.tree.map(
                lambda a, g: a + jnp.where(b_ok, g, 0.0).astype(a.dtype),
                dlayers_acc, dlayers_b)
            # model chunk 0's input-cotangent is d loss / d x_mb[mb]
            wx = jnp.logical_and(b_ok, b_slot < 0)
            dx_buf = dx_buf.at[bidx].set(
                jnp.where(wx, dh_b.astype(dx_buf.dtype), dx_buf[bidx]))

            # 5. what this tick sends (consumed next tick per the tables)
            return (acts_buf, grads_buf, y, dh_b,
                    loss_acc, aux_acc, dlayers_acc, dhead_acc, dx_buf), None

        carry = (acts_buf, grads_buf, y_prev, dh_prev,
                 loss_acc, aux_acc, dlayers_acc, dhead_acc, dx_buf)
        carry, _ = lax.scan(tick, carry, tbls)
        (_, _, _, _, loss_acc, aux_acc, dlayers_acc, dhead_acc,
         dx_buf) = carry

        # accumulators are nonzero only on their owning device (loss/head:
        # wherever the last chunk seeded; dx: the chunk-0 device); psum
        # broadcasts them to every rank.  Aux sums over ALL devices.
        loss = lax.psum(loss_acc, AXIS)
        loss = loss + aux_weight * lax.psum(aux_acc, AXIS)
        dhead = jax.tree.map(lambda g: lax.psum(g, AXIS), dhead_acc)
        dx = lax.psum(dx_buf, AXIS)
        return loss, dlayers_acc, dhead, dx

    head_specs = jax.tree.map(lambda _: P(), head_params)
    loss, dlayers, dhead, dx = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, head_specs, P(), P()),
        out_specs=(P(), layer_specs, head_specs, P()),
        axis_names={AXIS},
        check_vma=False,
    )(stacked_params, head_params, x_mb, args_mb)
    return loss, (dlayers, dhead, dx.reshape(batch, *x.shape[1:]))
