"""GPipe-style pipeline parallelism over the ``pipeline`` mesh axis.

The reference supports PP only as an orchestration contract (the operator
guarantees gang + env; Megatron/DeepSpeed do the scheduling inside user
containers) [SURVEY.md §2.5 PP row].  Here the schedule itself is
TPU-native: the scanned layer stack's leading dim is already sharded over
``pipeline`` (the ``("layers", "pipeline")`` logical rule), so each device
holds a contiguous stage of layers; this module adds the microbatch
schedule — a ``shard_map`` manual over *only* the pipeline axis, with
``lax.ppermute`` passing activations stage-to-stage, while every other
mesh axis (data/fsdp/model/seq) stays in GSPMD auto mode so ZeRO gathers
and TP collectives keep working inside each stage.

Why this shape: the pipeline axis is the DCN-tolerant one (mesh.py) — an
activation crosses a slice boundary once per microbatch per stage, which
amortizes over the whole stage's compute; the schedule is classic GPipe
(fill, steady state, drain: M + P - 1 ticks for M microbatches over P
stages).  Backward runs the reverse pipeline automatically: ``ppermute``
transposes to the opposite ring and ``lax.scan`` reverses.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import current_mesh

AXIS = "pipeline"


def pipeline_degree(mesh: Optional[Mesh]) -> int:
    if mesh is None or AXIS not in mesh.axis_names:
        return 1
    return mesh.shape[AXIS]


def gpipe(
    block_apply: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    num_microbatches: Optional[int] = None,
    remat: bool = True,
) -> jax.Array:
    """Run ``num_layers`` blocks over ``x`` as a P-stage microbatch pipeline.

    ``block_apply(layer_params, x) -> x`` applies ONE block given one
    layer's param subtree.  ``stacked_params`` is the scan-stacked tree
    (leading dim = num_layers, sharded over the ``pipeline`` mesh axis so
    each device already holds its stage's layers — no weight movement).
    ``x``: [batch, ...] activations, batch divisible by the microbatch
    count (default: the pipeline degree).

    Falls back to a plain sequential scan when no pipeline axis is active,
    so callers can use it unconditionally.
    """
    mesh = mesh or current_mesh()
    p_size = pipeline_degree(mesh)

    one = jax.checkpoint(block_apply) if remat else block_apply

    def apply_stage(layers, h):
        def body(h, lp):
            return one(lp, h), None
        h, _ = lax.scan(body, h, layers)
        return h

    if p_size == 1:
        return apply_stage(stacked_params, x)

    m = num_microbatches or p_size
    batch = x.shape[0]
    if batch % m:
        raise ValueError(
            f"batch {batch} not divisible by {m} microbatches")
    x_mb = x.reshape(m, batch // m, *x.shape[1:])

    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % p_size:
        raise ValueError(
            f"{num_layers} layers not divisible by {p_size} pipeline stages")

    layer_specs = jax.tree.map(lambda _: P(AXIS), stacked_params)
    perm = [(i, i + 1) for i in range(p_size - 1)]

    def body(local_layers, x_mb):
        stage = lax.axis_index(AXIS)
        state = jnp.zeros_like(x_mb[0])
        out_buf = jnp.zeros_like(x_mb)

        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t during the fill/steady phase
            inp = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            cur = jnp.where(stage == 0, inp, state)
            y = apply_stage(local_layers, cur)
            # last stage emits microbatch t-(P-1) once the fill completes
            widx = t - (p_size - 1)
            upd = lax.dynamic_update_index_in_dim(
                out_buf, y, jnp.clip(widx, 0, m - 1), 0)
            emit = jnp.logical_and(widx >= 0, stage == p_size - 1)
            out_buf = jnp.where(emit, upd, out_buf)
            nxt = lax.ppermute(y, AXIS, perm)
            return (nxt, out_buf), None

        (_, out_buf), _ = lax.scan(
            tick, (state, out_buf), jnp.arange(m + p_size - 1))
        # broadcast the finished buffer from the last stage to every rank
        # (the head/loss run data-parallel on all devices afterwards)
        out_buf = lax.psum(
            jnp.where(stage == p_size - 1, out_buf, jnp.zeros_like(out_buf)),
            AXIS,
        )
        return out_buf

    out = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, P()),
        out_specs=P(),
        axis_names={AXIS},
        check_vma=False,
    )(stacked_params, x_mb)
    return out.reshape(batch, *x.shape[1:])


# -- 1F1B (perf-grade schedule) ---------------------------------------------


@dataclasses.dataclass(frozen=True)
class Schedule1F1B:
    """Static 1F1B tick tables for ``p`` stages x ``m`` microbatches.

    Each tick is one fwd slot + one bwd slot per stage (the steady-state
    1F1B pattern).  ``fwd[t, s]`` / ``bwd[t, s]`` give the microbatch index
    each stage processes at tick ``t`` (-1 = idle slot); ``recv_act`` /
    ``recv_grad`` give the microbatch whose activation/cotangent arrives
    over the ppermute ring that tick.  ``act_slots`` / ``grad_slots`` are
    the stash capacities the schedule provably needs — the 1F1B memory
    bound (≈ P in-flight microbatches per stage, vs GPipe's M).
    """

    p: int
    m: int
    fwd: np.ndarray        # [T, P] int32
    bwd: np.ndarray        # [T, P] int32
    recv_act: np.ndarray   # [T, P] int32
    recv_grad: np.ndarray  # [T, P] int32
    act_slots: int
    grad_slots: int

    @property
    def ticks(self) -> int:
        return self.fwd.shape[0]

    @property
    def useful_fraction(self) -> float:
        """Filled fwd+bwd slots over total slots (1 - bubble fraction)."""
        filled = int((self.fwd >= 0).sum() + (self.bwd >= 0).sum())
        return filled / (2 * self.ticks * self.p)


def schedule_1f1b(p: int, m: int) -> Schedule1F1B:
    """Simulate the 1F1B schedule event-by-event and emit static tables.

    Rules (classic non-interleaved 1F1B, Megatron-style, adapted to a
    lockstep SPMD program with a 1-tick ppermute latency):

    - a stage forwards microbatches in order as their activations arrive,
      but holds at most ``P - s + 2`` in flight (the 1F1B throttle — this
      is what bounds activation memory; the +2 absorbs the two-tick
      send/receive round trip, reaching the zero-latency schedule length
      T = M + 2(P-1) at a stash cost of ~2 extra microbatches);
    - a stage backwards microbatches in order as cotangents arrive; the
      last stage seeds its own cotangent from the loss at forward time,
      so it can run fwd(m) and bwd(m) in the same tick;
    - within a tick, the fwd slot runs before the bwd slot, and a
      bwd-completing-this-tick frees its in-flight slot for the fwd
      admission check.
    """
    if p < 1 or m < 1:
        raise ValueError("need p >= 1 and m >= 1")
    cap = [min(p - s + 2, m) for s in range(p)]
    next_f, next_b = [0] * p, [0] * p
    recv_act = [set() for _ in range(p)]
    recv_grad = [set() for _ in range(p)]
    fwd_tick = [[-1] * m for _ in range(p)]
    bwd_tick = [[-1] * m for _ in range(p)]
    frows, brows = [], []
    t = 0
    while any(nb < m for nb in next_b):
        frow, brow = [-1] * p, [-1] * p
        for s in range(p):
            f, b = next_f[s], next_b[s]
            # tentative bwd readiness (ignoring this tick's own fwd)
            ready0 = b < m and (
                (s < p - 1 and b in recv_grad[s])
                or (s == p - 1 and fwd_tick[s][b] >= 0)
            )
            in_flight = f - b
            if (
                f < m
                and (s == 0 or f in recv_act[s])
                and in_flight - (1 if ready0 else 0) < cap[s]
            ):
                frow[s] = f
            ready = b < m and (
                (s < p - 1 and b in recv_grad[s])
                or (s == p - 1 and (fwd_tick[s][b] >= 0 or frow[s] == b))
            )
            if ready:
                brow[s] = b
        for s in range(p):
            if frow[s] >= 0:
                fwd_tick[s][frow[s]] = t
                next_f[s] += 1
            if brow[s] >= 0:
                bwd_tick[s][brow[s]] = t
                next_b[s] += 1
        # deliveries land next tick (decisions above read pre-tick state)
        for s in range(p):
            if frow[s] >= 0 and s + 1 < p:
                recv_act[s + 1].add(frow[s])
            if brow[s] >= 0 and s - 1 >= 0:
                recv_grad[s - 1].add(brow[s])
        frows.append(frow)
        brows.append(brow)
        t += 1
        if t > 4 * (m + p) + 16:
            raise RuntimeError(f"1F1B schedule deadlocked at p={p} m={m}")

    T = len(frows)
    fwd = np.array(frows, np.int32)
    bwd = np.array(brows, np.int32)
    ra = np.full((T, p), -1, np.int32)
    rg = np.full((T, p), -1, np.int32)
    for tt in range(1, T):
        for s in range(p):
            if s > 0:
                ra[tt, s] = fwd[tt - 1, s - 1]
            if s < p - 1:
                rg[tt, s] = bwd[tt - 1, s + 1]

    def max_overlap(intervals: list[tuple[int, int]]) -> int:
        best = 0
        for i, (lo, _) in enumerate(intervals):
            live = sum(1 for lo2, hi2 in intervals if lo2 <= lo <= hi2)
            best = max(best, live)
        return best

    act_slots = 1
    grad_slots = 1
    for s in range(p):
        if s > 0:
            ivs = [(fwd_tick[s - 1][mb] + 1, bwd_tick[s][mb]) for mb in range(m)]
            act_slots = max(act_slots, max_overlap(ivs))
        if s < p - 1:
            ivs = [(bwd_tick[s + 1][mb] + 1, bwd_tick[s][mb]) for mb in range(m)]
        else:
            ivs = [(fwd_tick[s][mb], bwd_tick[s][mb]) for mb in range(m)]
        grad_slots = max(grad_slots, max_overlap(ivs))
    return Schedule1F1B(
        p=p, m=m, fwd=fwd, bwd=bwd, recv_act=ra, recv_grad=rg,
        act_slots=act_slots, grad_slots=grad_slots,
    )


def one_f_one_b(
    block_apply: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[..., jax.Array],
    stacked_params: Any,
    head_params: Any,
    x: jax.Array,
    loss_args: Any,
    *,
    mesh: Optional[Mesh] = None,
    num_microbatches: Optional[int] = None,
    remat: bool = True,
):
    """Loss **and grads** of a staged block stack under the 1F1B schedule.

    ``loss = mean_mb loss_fn(head_params, blocks(x_mb), loss_args_mb)``;
    returns ``(loss, (d_stacked_params, d_head_params, d_x))``.

    Why a fused value-and-grad instead of a differentiable forward (what
    ``gpipe`` is): 1F1B's defining property is that microbatch i's
    *backward* runs while microbatch i+k's *forward* is still in flight,
    bounding in-flight activations at ~P per stage instead of M.  Under
    ``jax.grad`` the whole forward completes before any backward starts
    (GPipe), so the schedule must own its backward: each backward tick
    re-runs the stage forward from the stashed input (full within-stage
    remat) through ``jax.vjp`` and sends the input-cotangent upstream
    over the reverse ``ppermute`` ring.

    ``loss_fn(head_params, y_mb, args_mb) -> scalar`` runs at the last
    stage (masked elsewhere — SPMD lockstep executes it everywhere, so
    keep the head small relative to a stage; at T/M > 1 ticks per useful
    microbatch the head overhead multiplies).  ``loss_args`` is a pytree
    whose leaves lead with the batch dim (e.g. targets), microbatched
    like ``x``.
    """
    mesh = mesh or current_mesh()
    p_size = pipeline_degree(mesh)

    one = jax.checkpoint(block_apply) if remat else block_apply

    def apply_stage(layers, h):
        def body(h, lp):
            return one(lp, h), None
        h, _ = lax.scan(body, h, layers)
        return h

    if p_size == 1:
        def seq_loss(sp, hp, xx):
            return loss_fn(hp, apply_stage(sp, xx), loss_args)
        loss, grads = jax.value_and_grad(seq_loss, argnums=(0, 1, 2))(
            stacked_params, head_params, x)
        return loss, grads

    m = num_microbatches or p_size
    batch = x.shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by {m} microbatches")
    x_mb = x.reshape(m, batch // m, *x.shape[1:])
    args_mb = jax.tree.map(
        lambda a: a.reshape(m, batch // m, *a.shape[1:]), loss_args)

    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % p_size:
        raise ValueError(
            f"{num_layers} layers not divisible by {p_size} pipeline stages")

    sched = schedule_1f1b(p_size, m)
    C, Cg = sched.act_slots, sched.grad_slots
    fwd_tbl = jnp.asarray(sched.fwd)
    bwd_tbl = jnp.asarray(sched.bwd)
    ra_tbl = jnp.asarray(sched.recv_act)
    rg_tbl = jnp.asarray(sched.recv_grad)

    layer_specs = jax.tree.map(lambda _: P(AXIS), stacked_params)
    perm_fwd = [(i, i + 1) for i in range(p_size - 1)]
    perm_bwd = [(i + 1, i) for i in range(p_size - 1)]

    def body(local_layers, head_p, x_mb, args_mb):
        stage = lax.axis_index(AXIS)
        is_last = stage == p_size - 1
        mb_shape = x_mb.shape[1:]

        acts_buf = jnp.zeros((C, *mb_shape), x_mb.dtype)
        grads_buf = jnp.zeros((Cg, *mb_shape), x_mb.dtype)
        y_prev = jnp.zeros(mb_shape, x_mb.dtype)
        dh_prev = jnp.zeros(mb_shape, x_mb.dtype)
        loss_acc = jnp.zeros((), jnp.float32)
        dlayers_acc = jax.tree.map(
            lambda l: jnp.zeros(l.shape, l.dtype), local_layers)
        dhead_acc = jax.tree.map(
            lambda h: jnp.zeros(h.shape, h.dtype), head_p)
        dx_buf = jnp.zeros_like(x_mb)

        loss_vag = jax.value_and_grad(loss_fn, argnums=(1, 0))

        def tick(carry, rows):
            (acts_buf, grads_buf, y_prev, dh_prev,
             loss_acc, dlayers_acc, dhead_acc, dx_buf) = carry
            f_row, b_row, ra_row, rg_row = rows
            f = jnp.take(f_row, stage)
            b = jnp.take(b_row, stage)
            ra = jnp.take(ra_row, stage)
            rg = jnp.take(rg_row, stage)

            # 1. receive activation sent by upstream last tick
            in_act = lax.ppermute(y_prev, AXIS, perm_fwd)
            slot_ra = jnp.maximum(ra, 0) % C
            acts_buf = acts_buf.at[slot_ra].set(
                jnp.where(ra >= 0, in_act, acts_buf[slot_ra]))
            # 2. receive cotangent sent by downstream last tick
            in_grad = lax.ppermute(dh_prev, AXIS, perm_bwd)
            slot_rg = jnp.maximum(rg, 0) % Cg
            grads_buf = grads_buf.at[slot_rg].set(
                jnp.where(rg >= 0, in_grad, grads_buf[slot_rg]))

            # 3. forward slot (masked garbage when f == -1)
            fidx = jnp.maximum(f, 0)
            h_in_f = jnp.where(
                stage == 0, x_mb[jnp.clip(fidx, 0, m - 1)], acts_buf[fidx % C])
            y = apply_stage(local_layers, h_in_f)
            # last stage seeds its own cotangent from the loss
            a_f = jax.tree.map(lambda a: a[jnp.clip(fidx, 0, m - 1)], args_mb)
            loss_f, (dy_f, dhead_f) = loss_vag(head_p, y, a_f)
            seed = jnp.logical_and(is_last, f >= 0)
            slot_f = fidx % Cg
            grads_buf = grads_buf.at[slot_f].set(
                jnp.where(seed, (dy_f / m).astype(grads_buf.dtype),
                          grads_buf[slot_f]))
            loss_acc = loss_acc + jnp.where(seed, loss_f / m, 0.0)
            dhead_acc = jax.tree.map(
                lambda a, g: a + jnp.where(seed, g / m, 0.0).astype(a.dtype),
                dhead_acc, dhead_f)

            # 4. backward slot: re-run the stage fwd from the stashed input
            bidx = jnp.maximum(b, 0)
            h_in_b = jnp.where(
                stage == 0, x_mb[jnp.clip(bidx, 0, m - 1)], acts_buf[bidx % C])
            dy_b = grads_buf[bidx % Cg]
            _, stage_vjp = jax.vjp(apply_stage, local_layers, h_in_b)
            dlayers_b, dh_b = stage_vjp(dy_b)
            b_ok = b >= 0
            dlayers_acc = jax.tree.map(
                lambda a, g: a + jnp.where(b_ok, g, 0.0).astype(a.dtype),
                dlayers_acc, dlayers_b)
            bslot = jnp.clip(bidx, 0, m - 1)
            wx = jnp.logical_and(b_ok, stage == 0)
            dx_buf = dx_buf.at[bslot].set(
                jnp.where(wx, dh_b.astype(dx_buf.dtype), dx_buf[bslot]))

            # 5. what this tick sends (consumed next tick per the tables)
            return (acts_buf, grads_buf, y, dh_b,
                    loss_acc, dlayers_acc, dhead_acc, dx_buf), None

        carry = (acts_buf, grads_buf, y_prev, dh_prev,
                 loss_acc, dlayers_acc, dhead_acc, dx_buf)
        carry, _ = lax.scan(tick, carry, (fwd_tbl, bwd_tbl, ra_tbl, rg_tbl))
        (_, _, _, _, loss_acc, dlayers_acc, dhead_acc, dx_buf) = carry

        # only the owning stage's accumulators are real; psum-mask them to
        # every rank (loss/head: last stage; dx: first stage)
        loss = lax.psum(jnp.where(is_last, loss_acc, 0.0), AXIS)
        dhead = jax.tree.map(
            lambda g: lax.psum(jnp.where(is_last, g, 0.0), AXIS), dhead_acc)
        dx = lax.psum(
            jnp.where(stage == 0, dx_buf, jnp.zeros_like(dx_buf)), AXIS)
        return loss, dlayers_acc, dhead, dx

    head_specs = jax.tree.map(lambda _: P(), head_params)
    loss, dlayers, dhead, dx = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, head_specs, P(), P()),
        out_specs=(P(), layer_specs, head_specs, P()),
        axis_names={AXIS},
        check_vma=False,
    )(stacked_params, head_params, x_mb, args_mb)
    return loss, (dlayers, dhead, dx.reshape(batch, *x.shape[1:]))
