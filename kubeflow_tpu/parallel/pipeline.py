"""GPipe-style pipeline parallelism over the ``pipeline`` mesh axis.

The reference supports PP only as an orchestration contract (the operator
guarantees gang + env; Megatron/DeepSpeed do the scheduling inside user
containers) [SURVEY.md §2.5 PP row].  Here the schedule itself is
TPU-native: the scanned layer stack's leading dim is already sharded over
``pipeline`` (the ``("layers", "pipeline")`` logical rule), so each device
holds a contiguous stage of layers; this module adds the microbatch
schedule — a ``shard_map`` manual over *only* the pipeline axis, with
``lax.ppermute`` passing activations stage-to-stage, while every other
mesh axis (data/fsdp/model/seq) stays in GSPMD auto mode so ZeRO gathers
and TP collectives keep working inside each stage.

Why this shape: the pipeline axis is the DCN-tolerant one (mesh.py) — an
activation crosses a slice boundary once per microbatch per stage, which
amortizes over the whole stage's compute; the schedule is classic GPipe
(fill, steady state, drain: M + P - 1 ticks for M microbatches over P
stages).  Backward runs the reverse pipeline automatically: ``ppermute``
transposes to the opposite ring and ``lax.scan`` reverses.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import current_mesh

AXIS = "pipeline"


def pipeline_degree(mesh: Optional[Mesh]) -> int:
    if mesh is None or AXIS not in mesh.axis_names:
        return 1
    return mesh.shape[AXIS]


def gpipe(
    block_apply: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    num_microbatches: Optional[int] = None,
    remat: bool = True,
) -> jax.Array:
    """Run ``num_layers`` blocks over ``x`` as a P-stage microbatch pipeline.

    ``block_apply(layer_params, x) -> x`` applies ONE block given one
    layer's param subtree.  ``stacked_params`` is the scan-stacked tree
    (leading dim = num_layers, sharded over the ``pipeline`` mesh axis so
    each device already holds its stage's layers — no weight movement).
    ``x``: [batch, ...] activations, batch divisible by the microbatch
    count (default: the pipeline degree).

    Falls back to a plain sequential scan when no pipeline axis is active,
    so callers can use it unconditionally.
    """
    mesh = mesh or current_mesh()
    p_size = pipeline_degree(mesh)

    one = jax.checkpoint(block_apply) if remat else block_apply

    def apply_stage(layers, h):
        def body(h, lp):
            return one(lp, h), None
        h, _ = lax.scan(body, h, layers)
        return h

    if p_size == 1:
        return apply_stage(stacked_params, x)

    m = num_microbatches or p_size
    batch = x.shape[0]
    if batch % m:
        raise ValueError(
            f"batch {batch} not divisible by {m} microbatches")
    x_mb = x.reshape(m, batch // m, *x.shape[1:])

    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % p_size:
        raise ValueError(
            f"{num_layers} layers not divisible by {p_size} pipeline stages")

    layer_specs = jax.tree.map(lambda _: P(AXIS), stacked_params)
    perm = [(i, i + 1) for i in range(p_size - 1)]

    def body(local_layers, x_mb):
        stage = lax.axis_index(AXIS)
        state = jnp.zeros_like(x_mb[0])
        out_buf = jnp.zeros_like(x_mb)

        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t during the fill/steady phase
            inp = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            cur = jnp.where(stage == 0, inp, state)
            y = apply_stage(local_layers, cur)
            # last stage emits microbatch t-(P-1) once the fill completes
            widx = t - (p_size - 1)
            upd = lax.dynamic_update_index_in_dim(
                out_buf, y, jnp.clip(widx, 0, m - 1), 0)
            emit = jnp.logical_and(widx >= 0, stage == p_size - 1)
            out_buf = jnp.where(emit, upd, out_buf)
            nxt = lax.ppermute(y, AXIS, perm)
            return (nxt, out_buf), None

        (_, out_buf), _ = lax.scan(
            tick, (state, out_buf), jnp.arange(m + p_size - 1))
        # broadcast the finished buffer from the last stage to every rank
        # (the head/loss run data-parallel on all devices afterwards)
        out_buf = lax.psum(
            jnp.where(stage == p_size - 1, out_buf, jnp.zeros_like(out_buf)),
            AXIS,
        )
        return out_buf

    out = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, P()),
        out_specs=P(),
        axis_names={AXIS},
        check_vma=False,
    )(stacked_params, x_mb)
    return out.reshape(batch, *x.shape[1:])
