"""Mesh construction and parallelism strategies."""

from .mesh import (
    AXIS_ORDER,
    BATCH_AXES,
    MeshPlan,
    MeshPlanError,
    batch_sharding,
    build_mesh,
    local_batch_size,
    plan_mesh,
    replicated,
)

__all__ = [k for k in dir() if not k.startswith("_")]
