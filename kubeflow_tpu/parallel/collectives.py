"""Collective wrappers with CPU-testable fallbacks.

``jax.lax.ragged_all_to_all`` (the dropless-MoE transport, SURVEY §2.5 EP
row) lowers to an HLO the TPU runtime implements but XLA:CPU does not
(``ragged-all-to-all is not supported by XLA:CPU ThunkEmitter``).  The
test/dryrun contract of this repo is that every multi-chip code path runs
on the virtual CPU mesh (SURVEY §4c), so this module provides a wrapper
with the primitive's exact documented semantics:

- on TPU: the native primitive (which has jvp + transpose rules, so it
  trains);
- on CPU: an emulation built from ``lax.all_to_all`` over max-padded
  chunks plus masked scatters — mathematically identical, differentiable,
  and O(D x operand) instead of O(sum sizes), which is irrelevant at test
  shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def ragged_all_to_all(
    operand: jax.Array,
    output: jax.Array,
    input_offsets: jax.Array,
    send_sizes: jax.Array,
    output_offsets: jax.Array,
    recv_sizes: jax.Array,
    *,
    axis_name: str,
) -> jax.Array:
    """``lax.ragged_all_to_all`` semantics on every backend.

    Per the primitive's contract: device ``i`` sends, for each destination
    ``d``, ``operand[input_offsets[d] : +send_sizes[d]]``, which lands on
    ``d`` at ``output_offsets[d]`` (the *receiver-side* offset); rows of
    ``output`` not written by any received chunk keep their values.
    """
    if jax.default_backend() != "cpu":
        return lax.ragged_all_to_all(
            operand, output, input_offsets, send_sizes, output_offsets,
            recv_sizes, axis_name=axis_name)
    return _emulated_ragged_all_to_all(
        operand, output, input_offsets, send_sizes, output_offsets,
        recv_sizes, axis_name=axis_name)


def _emulated_ragged_all_to_all(
    operand, output, input_offsets, send_sizes, output_offsets, recv_sizes,
    *, axis_name,
):
    d = lax.psum(1, axis_name)
    pad = operand.shape[0]

    # chunk for destination i, max-padded: roll the chunk start to row 0
    # (send_sizes[i] rows are real, the rest ride along and are masked off
    # at the receiver)
    def chunk(i):
        return jnp.roll(operand, -input_offsets[i], axis=0)

    stacked = jax.vmap(chunk)(jnp.arange(d))          # [D, pad, ...]
    exchanged = lax.all_to_all(stacked, axis_name, 0, 0)  # [D, pad, ...]
    # receiver-side offsets of each incoming chunk: the all_to_all of the
    # senders' output_offsets (exactly the doc's recipe)
    my_offsets = lax.all_to_all(output_offsets, axis_name, 0, 0, tiled=True)

    rows = jnp.arange(pad)

    def write(i, out):
        tgt = my_offsets[i] + rows
        ok = rows < recv_sizes[i]
        # invalid rows point past the buffer; mode="drop" discards them
        tgt = jnp.where(ok, tgt, output.shape[0])
        return out.at[tgt].set(exchanged[i], mode="drop")

    return lax.fori_loop(0, d, write, output)
