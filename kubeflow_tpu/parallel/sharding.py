"""Logical-axis sharding rules: the single table that maps model-space axis
names to mesh axes.

The reference has no equivalent — parallelism layout lives inside user
containers (Megatron/DeepSpeed config); the operator only guarantees gang +
env (SURVEY.md §2.5).  Here layout is a first-class, typed policy: modules
annotate parameters/activations with *logical* names ("embed", "heads",
"batch", ...) and this table decides which mesh axis each rides, so the same
model code runs DP, FSDP, TP, SP or any mix purely by changing the mesh.

This is the scaling-book recipe ("pick a mesh, annotate shardings, let XLA
insert collectives") factored into one auditable table.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import BATCH_AXES, active_mesh

#: logical axis -> mesh axis (or tuple of mesh axes) the data rides.
#: Entries referencing mesh axes absent from the actual mesh are dropped at
#: lookup time, which is what makes one table serve every parallelism mix.
LOGICAL_RULES: tuple[tuple[str, Any], ...] = (
    # -- activations ----------------------------------------------------
    # expert doubles as a data axis outside MoE layers (GShard convention)
    ("batch", ("replica", "data", "fsdp", "expert")),
    # batch dim INSIDE expert groups (the expert axis is spent on the
    # expert dim there, so it must not reappear on batch)
    ("expert_batch", ("replica", "data", "fsdp")),
    ("act_seq", "seq"),                      # sequence dim under SP/CP
    ("act_embed", None),                     # residual stream feature dim
    ("act_heads", "model"),                  # per-head activations under TP
    ("act_kv_heads", "model"),
    ("act_mlp", "model"),                    # mlp hidden activations under TP
    ("act_vocab", "model"),                  # logits vocab dim under TP
    # -- parameters -----------------------------------------------------
    ("embed", "fsdp"),                       # ZeRO-3 shard of the feature dim
    ("vocab", "model"),                      # embedding/unembedding vocab dim
    ("heads", "model"),                      # attention heads under TP
    ("kv_heads", "model"),
    ("head_dim", None),
    ("mlp", "model"),                        # ffn hidden dim under TP
    ("layers", "pipeline"),                  # scanned layer stack
    ("norm", None),
    ("expert", "expert"),                    # MoE expert dim (params + groups)
    ("expert_dim", None),                    # router logits output dim
)


class shard_context:
    """Everything model code needs for logical shardings to take effect.

    Enters, together: (a) the flax logical-axis-rules context (without it
    every ``nn.with_logical_constraint`` silently no-ops), (b)
    ``jax.sharding.set_mesh`` — the abstract-mesh context flax's
    ``global_mesh_defined()`` actually checks; the plain ``with mesh:``
    resource env is NOT seen by flax on jax>=0.9 and the constraints would
    silently vanish from the HLO — and (c) this package's ``active_mesh``
    (so ring attention can find the physical mesh).  Wrap both init and the
    jit call site with it.
    """

    def __init__(self, mesh: Mesh, overrides: Optional[Sequence[tuple[str, Any]]] = None):
        self.mesh = mesh
        # jax < 0.6 has no jax.sharding.set_mesh; there the plain
        # ``with mesh:`` resource env IS what flax's global_mesh_defined()
        # checks, so the constraints land in the HLO either way
        set_mesh = getattr(jax.sharding, "set_mesh", None)
        self._ctxs = [
            set_mesh(mesh) if set_mesh is not None else mesh,
            nn.logical_axis_rules(rules_for_mesh(mesh, overrides)),
            active_mesh(mesh),
        ]

    def __enter__(self) -> Mesh:
        for c in self._ctxs:
            c.__enter__()
        return self.mesh

    def __exit__(self, *exc) -> None:
        for c in reversed(self._ctxs):
            c.__exit__(*exc)


def rules_for_mesh(
    mesh: Mesh, overrides: Optional[Sequence[tuple[str, Any]]] = None
) -> tuple[tuple[str, Any], ...]:
    """LOGICAL_RULES restricted to axes that exist in ``mesh``.

    A rule whose mesh axis is absent degrades to replication for that logical
    axis — e.g. on a pure-DP mesh every parameter rule melts away and the
    model is replicated, with zero model-code changes.
    """
    present = set(mesh.axis_names)

    def keep(target: Any) -> Any:
        if target is None:
            return None
        if isinstance(target, str):
            return target if target in present else None
        kept = tuple(t for t in target if t in present)
        return kept if kept else None

    merged: dict[str, Any] = {name: keep(t) for name, t in LOGICAL_RULES}
    for name, t in overrides or ():
        merged[name] = keep(t)
    return tuple(merged.items())


def logical_sharding(
    mesh: Mesh, *logical_axes: Optional[str], overrides=None
) -> NamedSharding:
    """NamedSharding for a value whose dims carry the given logical names."""
    spec = nn.logical_to_mesh_sharding(
        PartitionSpec(*logical_axes), mesh, rules_for_mesh(mesh, overrides)
    )
    return spec


def shard_constraint(x: jax.Array, mesh: Mesh, *logical_axes: Optional[str]):
    """Activation sharding constraint by logical names (use inside jit)."""
    return jax.lax.with_sharding_constraint(x, logical_sharding(mesh, *logical_axes))


def param_shardings(abstract_params: Any, mesh: Mesh, overrides=None) -> Any:
    """Tree of NamedShardings from flax param-metadata (with_logical_partitioning).

    ``abstract_params`` is the output of ``jax.eval_shape`` over ``model.init``
    (or the real variables) — anything whose leaves are ``nn.Partitioned``
    boxes carrying logical names.
    """
    logical_spec = nn.get_partition_spec(abstract_params)
    return nn.logical_to_mesh_sharding(logical_spec, mesh, rules_for_mesh(mesh, overrides))


def _spec_axes(entry: Any) -> list[str]:
    """Mesh axes one PartitionSpec entry names (str | tuple | None)."""
    if entry is None:
        return []
    if isinstance(entry, (tuple, list)):
        return [str(a) for a in entry]
    return [str(entry)]


def _shard_count(sharding: Optional[NamedSharding], dim: int) -> int:
    """How many ways ``dim`` splits under ``sharding`` (1 = replicated)."""
    if sharding is None:
        return 1
    spec = tuple(sharding.spec)
    if dim >= len(spec):
        return 1
    n = 1
    for ax in _spec_axes(spec[dim]):
        n *= int(dict(zip(sharding.mesh.axis_names,
                          sharding.mesh.devices.shape))[ax])
    return n


def _spec_json(sharding: Optional[NamedSharding], ndim: int) -> list:
    """PartitionSpec as a JSON-able per-dim list (str | [str] | None)."""
    if sharding is None:
        return [None] * ndim
    spec = list(sharding.spec) + [None] * (ndim - len(tuple(sharding.spec)))
    out: list = []
    for e in spec[:ndim]:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append([str(a) for a in e])
        else:
            out.append(str(e))
    return out


def reshard_plan(params: Any, src_shardings: Any, dst_shardings: Any) -> list[dict]:
    """Per-leaf repartition plan for moving a weight PyTree between two
    mesh layouts (the Tenplex-style degree change, ISSUE 10): each entry
    records the leaf's path, shape, dtype and its source/destination
    PartitionSpec as plain JSON values — the header the elastic-resize
    wire family (serving/resize.py) frames in front of raw numpy bytes,
    never pickle.

    ``src_shardings``/``dst_shardings`` are trees of NamedSharding (or
    None = replicated) matching ``params`` — for serving weights that is
    ``serving.sharded.llama_param_shardings(cfg, mesh)``, i.e. the SAME
    logical-rules table the trainer and every gang member already use.

    Validates feasibility up front: a destination spec that does not
    divide the leaf's dim (e.g. 8 heads resized onto a TP=3 mesh) raises
    ValueError naming the leaf — a resize to an illegal degree must fail
    at plan time, before anything is quiesced or torn down.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    src_leaves = jax.tree.leaves(
        src_shardings, is_leaf=lambda x: x is None or isinstance(
            x, NamedSharding))
    dst_leaves = jax.tree.leaves(
        dst_shardings, is_leaf=lambda x: x is None or isinstance(
            x, NamedSharding))
    if not (len(flat) == len(src_leaves) == len(dst_leaves)):
        raise ValueError(
            f"reshard_plan: tree mismatch — {len(flat)} params vs "
            f"{len(src_leaves)} src / {len(dst_leaves)} dst shardings")
    plan: list[dict] = []
    for (path, leaf), src, dst in zip(flat, src_leaves, dst_leaves):
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        for dim, size in enumerate(shape):
            n = _shard_count(dst, dim)
            if n > 1 and size % n:
                raise ValueError(
                    f"reshard_plan: leaf {name!r} dim {dim} (size {size}) "
                    f"does not divide into {n} destination shards — the "
                    "target degree is illegal for this model")
        plan.append({
            "path": name,
            "shape": list(shape),
            "dtype": str(jax.numpy.asarray(leaf).dtype
                         if not hasattr(leaf, "dtype") else leaf.dtype),
            "src": _spec_json(src, len(shape)),
            "dst": _spec_json(dst, len(shape)),
        })
    return plan


def constrain_microbatches(
    micro: jax.Array, mesh: Mesh, batch_sharding: NamedSharding
) -> jax.Array:
    """Sharding constraint for a [accum, batch/accum, ...] microbatch stack:
    the microbatch dim is replicated (lax.scan iterates it), the per-micro
    batch dim keeps the global batch sharding.  Used by gradient
    accumulation so each microbatch spans the full mesh instead of being
    gathered onto a fraction of it."""
    spec = PartitionSpec(
        None, *batch_sharding.spec, *([None] * (micro.ndim - 2)))
    return jax.lax.with_sharding_constraint(
        micro, NamedSharding(mesh, spec))
