"""Named-axis device mesh construction with ICI-vs-DCN placement.

The communication-backend equivalent (SURVEY.md §2.6): the reference
configures NCCL/MPI/gRPC rendezvous but never performs collectives; here,
after ``jax.distributed.initialize``, the mesh *is* the communication
backend — XLA lowers collectives onto ICI (intra-slice torus) or DCN
(inter-slice) purely from how axes are laid over devices.

Axis vocabulary (canonical order, outermost first):

- ``replica``  — pure data parallelism across slices (DCN-friendly: one
  gradient all-reduce per step amortized over the whole step)
- ``data``     — data parallelism (batch sharding)
- ``fsdp``     — data parallelism + ZeRO-3 weight sharding
- ``pipeline`` — pipeline stages (DCN-friendly: activations cross stages
  once per microbatch, collective-permute)
- ``expert``   — MoE expert parallelism (all-to-all dispatch)
- ``seq``      — sequence/context parallelism (ring attention KV permutes)
- ``model``    — tensor parallelism (per-layer all-reduce/all-gather —
  bandwidth-hungry, must ride ICI)

The placement rule the builder enforces: DCN-tolerant axes (``replica``,
``pipeline``) go over slice boundaries; bandwidth-hungry axes (``model``,
``seq``, ``expert``) must fit inside a slice.  This is the "pick a mesh,
annotate shardings, let XLA insert collectives" recipe of the scaling
playbook, made a typed policy.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

#: Canonical mesh-axis order, outermost (most DCN-tolerant) first.
AXIS_ORDER = ("replica", "data", "fsdp", "pipeline", "expert", "seq", "model")

#: Axes whose collectives amortize well over slow links (DCN).
DCN_TOLERANT_AXES = ("replica", "pipeline", "data")

#: Axes that shard the batch dimension (their product is the data-parallel
#: degree for input pipelines and loss scaling).  ``expert`` doubles as a
#: data axis outside MoE layers (the GShard convention: EP groups share
#: DP), which is what makes the MoE dispatch a true all-to-all instead of
#: a batch replication.
BATCH_AXES = ("replica", "data", "fsdp", "expert")


class MeshPlanError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A validated axis->size assignment plus its ICI/DCN split."""

    axes: dict[str, int]
    ici_axes: dict[str, int]
    dcn_axes: dict[str, int]

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.axes)

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.axes.values())

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    @property
    def batch_degree(self) -> int:
        return math.prod(s for a, s in self.axes.items() if a in BATCH_AXES)


def _ordered(axes: dict[str, int]) -> dict[str, int]:
    unknown = [a for a in axes if a not in AXIS_ORDER]
    if unknown:
        raise MeshPlanError(f"unknown mesh axes {unknown}; known: {AXIS_ORDER}")
    return {a: axes[a] for a in AXIS_ORDER if a in axes}


def plan_mesh(
    axes: dict[str, int],
    num_devices: Optional[int] = None,
    num_slices: int = 1,
) -> MeshPlan:
    """Validate axis sizes against the device count and split ICI vs DCN.

    With ``num_slices > 1`` the outermost axes (in canonical order) are
    assigned to DCN until the per-slice product fits one slice; a
    bandwidth-hungry axis landing on DCN is an error, not a warning —
    mis-placement silently destroys step time, so it must not compile.
    """
    axes = _ordered({a: s for a, s in axes.items() if s != 1} or {"data": 1})
    total = math.prod(axes.values())
    if num_devices is not None and total != num_devices:
        raise MeshPlanError(f"mesh {axes} needs {total} devices, have {num_devices}")
    ici: dict[str, int] = dict(axes)
    dcn: dict[str, int] = {}
    if num_slices > 1:
        remaining = num_slices
        # factor slices onto DCN-tolerant axes FIRST: {pipeline: 2, fsdp: 16}
        # on 2 slices must put pipeline (not fsdp) over DCN even though fsdp
        # precedes it in canonical mesh order
        order = [a for a in axes if a in DCN_TOLERANT_AXES] + [
            a for a in axes if a not in DCN_TOLERANT_AXES]
        for a in order:
            if remaining == 1:
                break
            s = axes[a]
            take = math.gcd(s, remaining)
            if take > 1:
                if a not in DCN_TOLERANT_AXES:
                    raise MeshPlanError(
                        f"axis {a!r} (size {s}) would span {take} slices over DCN; "
                        f"only {DCN_TOLERANT_AXES} may cross slice boundaries"
                    )
                dcn[a] = take
                ici[a] = s // take
                remaining //= take
        if remaining != 1:
            raise MeshPlanError(
                f"cannot factor {num_slices} slices into DCN-tolerant axes of {axes}"
            )
        ici = {a: s for a, s in ici.items() if s != 1}
    return MeshPlan(axes=axes, ici_axes=ici, dcn_axes=dcn)


def build_mesh(
    axes: dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
    num_slices: int = 1,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` for the plan.

    Single-slice: ``mesh_utils.create_device_mesh`` (ICI-topology-aware
    ordering on TPU; plain reshape on CPU).  Multi-slice:
    ``create_hybrid_device_mesh`` with the plan's DCN factors outermost.
    """
    devices = list(devices if devices is not None else jax.devices())
    plan = plan_mesh(axes, num_devices=len(devices), num_slices=num_slices)
    if plan.dcn_axes:
        per_slice = tuple(
            plan.ici_axes.get(a, 1) for a in plan.axis_names
        )
        dcn = tuple(plan.dcn_axes.get(a, 1) for a in plan.axis_names)
        if hasattr(devices[0], "slice_index"):
            dev_array = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=devices, allow_split_physical_axes=True
            )
        else:
            # CPU stand-in devices carry no slice_index attribute; build the
            # same dcn-outermost-per-axis layout by hand (slice-major device
            # order) so multi-slice plans stay testable on the virtual mesh.
            # Real TPU topology errors must surface, so this path is gated
            # on the attribute, not on catching ValueError.
            n = len(plan.axis_names)
            # devices are host-side topology handles, not device values:
            # np.array here is mesh layout math, no transfer happens
            # analysis: ok host-sync-in-dispatch — host topology math
            arr = np.array(devices).reshape(*dcn, *per_slice)
            order = [i for pair in ((k, k + n) for k in range(n)) for i in pair]
            dev_array = arr.transpose(order).reshape(plan.shape)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                # analysis: ok host-sync-in-dispatch — host topology math
                plan.shape, devices=np.array(devices), allow_split_physical_axes=True
            )
        except (ValueError, AssertionError):
            # analysis: ok host-sync-in-dispatch — host topology math
            dev_array = np.array(devices).reshape(plan.shape)
    return Mesh(dev_array, plan.axis_names)


_ACTIVE_MESH: list[Mesh] = []


class active_mesh:
    """Context manager making ``mesh`` discoverable by model internals
    (e.g. ring attention's shard_map needs the physical mesh, which flax
    module call signatures don't carry)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self) -> Mesh:
        _ACTIVE_MESH.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc) -> None:
        _ACTIVE_MESH.pop()


def current_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH[-1] if _ACTIVE_MESH else None


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input-batch sharding: batch dim over every batch-like axis present."""
    batch_axes = tuple(a for a in mesh.axis_names if a in BATCH_AXES)
    return NamedSharding(mesh, PartitionSpec(batch_axes if batch_axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    """Per-process batch share for input pipelines (SURVEY.md §2.5 DP row:
    per-host loading keyed by process index)."""
    n = jax.process_count()
    if global_batch % n:
        raise MeshPlanError(f"global batch {global_batch} not divisible by {n} processes")
    return global_batch // n
