"""Ring attention: blockwise causal attention over a sequence-sharded mesh.

Long-context capability the reference lacks entirely (SURVEY.md §5 "long
context / sequence parallelism: absent in the reference") but the north
star's Llama target demands.  Design is the ring-attention recipe on the TPU
ICI torus: each device owns one sequence block of Q/K/V; K/V blocks rotate
around the ``seq`` mesh axis with ``lax.ppermute`` while each device folds
every visiting block into a flash-style online-softmax accumulator.  Peak
memory is O(seq/ring) per device and the permute overlaps with the block
matmuls (XLA schedules the collective-permute async on TPU).

Also here: ``ulysses_attention`` — the all-to-all alternative (swap
sequence-sharding for head-sharding around the attention core), cheaper when
heads >= ring size and the full-sequence attention fits memory.

All functions are differentiable (ppermute/all_to_all have transpose rules;
the accumulator is a ``lax.scan``), so the same code path serves training.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import BATCH_AXES, current_mesh

_NEG_INF = -1e30


def _specs(mesh: Mesh, seq_axis: str):
    batch = tuple(a for a in mesh.axis_names if a in BATCH_AXES) or None
    model = "model" if "model" in mesh.axis_names else None
    q_spec = P(batch, seq_axis, model, None)
    kv_spec = P(batch, seq_axis, model, None)
    return q_spec, kv_spec


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    q_per_kv: int = 1,
    mesh: Optional[Mesh] = None,
    block_impl: str = "auto",
) -> jax.Array:
    """Causal GQA attention with sequence sharded on ``axis_name``.

    q: [b, s, h, d]; k, v: [b, s, kv, d] (global shapes; sharding constraints
    put the s dim on the ``seq`` mesh axis).  Falls back to dense attention
    when no seq axis is active, so models can enable it unconditionally.

    ``block_impl``: what computes each visiting K/V block —
    - "flash": the Pallas flash kernel per block (fully-masked blocks are
      skipped with lax.switch, earlier blocks run unmasked, the diagonal
      runs causal), folded across the ring by logsumexp;
    - "einsum": the plain XLA online-softmax fold;
    - "auto": flash when the per-device sequence is MXU-tileable.
    """
    mesh = mesh or current_mesh()
    if (
        mesh is None
        or axis_name not in mesh.axis_names
        or mesh.shape[axis_name] == 1
    ):
        from ..models.llama import _causal_attention

        return _causal_attention(q, k, v, q_per_kv)

    ring = mesh.shape[axis_name]
    per_dev_seq = q.shape[1] // ring
    if block_impl not in ("auto", "flash", "einsum"):
        raise ValueError(f"unknown block_impl {block_impl!r}")
    if block_impl == "auto":
        # flash blocks engage on real TPU with MXU-tileable shards; the CPU
        # stand-in keeps the einsum fold (pallas interpret mode is
        # correctness-only and slow — tests opt into "flash" explicitly)
        block_impl = (
            "flash"
            if jax.default_backend() == "tpu" and per_dev_seq % 128 == 0
            else "einsum"
        )
    body = _ring_forward_flash if block_impl == "flash" else _ring_forward

    q_spec, kv_spec = _specs(mesh, axis_name)
    fn = jax.shard_map(
        partial(
            body,
            axis_name=axis_name,
            ring_size=ring,
            q_per_kv=q_per_kv,
        ),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _block_fold(acc, qh, k_blk, v_blk, q_pos, k_pos, scale):
    """Fold one visiting K/V block into the online-softmax accumulator.

    qh: [b, sq, kv, g, d]; k_blk/v_blk: [b, sk, kv, d].
    acc = (m, l, o): running max [b,sq,kv,g], denom [b,sq,kv,g],
    numerator [b,sq,kv,g,d] — all float32.
    """
    m, l, o = acc
    logits = jnp.einsum("bqkgd,bskd->bqkgs", qh, k_blk.astype(jnp.float32)) * scale
    causal = q_pos[:, None] >= k_pos[None, :]  # [sq, sk]
    logits = jnp.where(causal[None, :, None, None, :], logits, _NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bqkgs,bskd->bqkgd", p, v_blk.astype(jnp.float32))
    return m_new, l_new, o_new


def _ring_forward(q, k, v, *, axis_name: str, ring_size: int, q_per_kv: int):
    """Per-shard body: local q stays put; k/v ride the ring."""
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    my = lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qh = q.reshape(b, sq, kvh, q_per_kv, d).astype(jnp.float32)
    q_pos = my * sq + jnp.arange(sq)

    m0 = jnp.full((b, sq, kvh, q_per_kv), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, sq, kvh, q_per_kv), jnp.float32)
    o0 = jnp.zeros((b, sq, kvh, q_per_kv, d), jnp.float32)
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    def step(carry, t):
        k_blk, v_blk, acc = carry
        src = (my - t) % ring_size  # whose block we hold at step t
        k_pos = src * sq + jnp.arange(sq)
        acc = _block_fold(acc, qh, k_blk, v_blk, q_pos, k_pos, scale)
        # rotate for the next step (the final rotate is dead code XLA drops)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, acc), None

    (_, _, (m, l, o)), _ = lax.scan(
        step, (k, v, (m0, l0, o0)), jnp.arange(ring_size))
    out = o / l[..., None]
    return out.reshape(b, sq, h, d).astype(q.dtype)


def _ring_forward_flash(q, k, v, *, axis_name: str, ring_size: int,
                        q_per_kv: int):
    """Per-shard body with the Pallas flash kernel computing each block.

    Each visiting K/V block is one of three cases by ring position:
    entirely-after my queries (fully masked — SKIPPED, no FLOPs at all),
    entirely-before (full unmasked attention), or the diagonal (causal).
    Normalized block outputs combine exactly through their logsumexps
    (``flash_attention_lse``); the combine is differentiable end to end,
    closing the r1 gap where ring attention's block math was plain einsum
    while the single-chip path had the kernel.
    """
    from ..ops.flash_attention import flash_attention_lse

    b, sq, h, d = q.shape
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    def step(carry, t):
        k_blk, v_blk, o_num, l_run, m_run = carry
        src = (my - t) % ring_size  # whose block we hold at step t

        def diag(_):
            return flash_attention_lse(q, k_blk, v_blk, q_per_kv=q_per_kv,
                                       causal=True)

        def full(_):
            return flash_attention_lse(q, k_blk, v_blk, q_per_kv=q_per_kv,
                                       causal=False)

        def skip(_):
            return (jnp.zeros((b, sq, h, d), q.dtype),
                    jnp.full((b, h, sq), _NEG_INF, jnp.float32))

        # 0 = src after me (skip), 1 = before me (full), 2 = diagonal
        case = jnp.where(src == my, 2, jnp.where(src < my, 1, 0))
        o_t, lse_t = lax.switch(case, [skip, full, diag], None)

        # exact combine via logsumexp weights, unnormalized accumulator
        # (one division after the scan); the _NEG_INF sentinel keeps empty
        # partials weightless once any real block lands (exp(-1e30-m) == 0)
        m_new = jnp.maximum(m_run, lse_t)
        corr = jnp.exp(m_run - m_new)
        w_t = jnp.exp(lse_t - m_new)
        o_new = (o_num * corr.transpose(0, 2, 1)[..., None]
                 + o_t.astype(jnp.float32) * w_t.transpose(0, 2, 1)[..., None])
        l_new = l_run * corr + w_t
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, o_new, l_new, m_new), None

    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    (_, _, o_num, l_run, _), _ = lax.scan(
        step, (k, v, o0, l0, m0), jnp.arange(ring_size))
    out = o_num / jnp.maximum(l_run, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    q_per_kv: int = 1,
    mesh: Optional[Mesh] = None,
    block_impl: str = "auto",
) -> jax.Array:
    """Ulysses-style SP: all-to-all heads<->sequence swap around dense attention.

    Each device trades its sequence shard of all heads for the full sequence
    of heads/ring_size heads, runs ordinary causal attention, and swaps back.
    Two all-to-alls per call; requires num_kv_heads % ring_size == 0.
    ``block_impl`` follows ring_attention's convention: "flash" runs the
    post-all-to-all core through the Pallas kernel, "einsum" the dense
    reference, "auto" = flash on real TPU with MXU-tileable sequences.
    """
    mesh = mesh or current_mesh()
    if (
        mesh is None
        or axis_name not in mesh.axis_names
        or mesh.shape[axis_name] == 1
    ):
        from ..models.llama import _causal_attention

        return _causal_attention(q, k, v, q_per_kv)

    ring = mesh.shape[axis_name]
    # head counts as seen inside shard_map: already divided by any TP axis
    tp = mesh.shape.get("model", 1) if "model" in mesh.axis_names else 1
    local_q, local_kv = q.shape[2] // tp, k.shape[2] // tp
    if (
        q.shape[2] % tp
        or k.shape[2] % tp
        or local_kv % ring
        or local_q % ring
    ):
        raise ValueError(
            f"ulysses needs per-shard head counts (q={q.shape[2]}/{tp}, "
            f"kv={k.shape[2]}/{tp}) divisible by seq axis size {ring}")
    q_spec, kv_spec = _specs(mesh, axis_name)

    # after the all-to-all the core is ordinary full-sequence causal
    # attention — run it through the Pallas kernel on real TPU (the CPU
    # stand-in keeps the dense einsum; interpret mode is correctness-only,
    # and tests force block_impl="flash" to cover the kernel path there)
    if block_impl not in ("auto", "flash", "einsum"):
        raise ValueError(f"unknown block_impl {block_impl!r}")
    full_seq = q.shape[1]
    if block_impl == "auto":
        block_impl = (
            "flash"
            if jax.default_backend() == "tpu" and full_seq % 128 == 0
            else "einsum"
        )
    use_flash = block_impl == "flash"

    def body(q, k, v):
        # [b, s/r, h, d] -> all_to_all -> [b, s, h/r, d]
        def gather_seq(x):
            return lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True)

        def scatter_seq(x):
            return lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True)

        if use_flash:
            from ..ops.flash_attention import flash_attention as attend

            out = attend(gather_seq(q), gather_seq(k), gather_seq(v),
                         q_per_kv=q_per_kv)
        else:
            from ..models.llama import _causal_attention

            out = _causal_attention(
                gather_seq(q), gather_seq(k), gather_seq(v), q_per_kv)
        return scatter_seq(out)

    return jax.shard_map(
        body, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec, check_vma=False,
    )(q, k, v)
