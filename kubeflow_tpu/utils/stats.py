"""Shared score/percentile helpers for benches and the sim twin.

One module, one definition (ISSUE 20 satellite): the nearest-rank
quantile and the median/p90 summary dict were copy-pasted across
``recovery_bench.py``, ``gang_startup_bench.py``, ``serving_bench.py``
and ``trace_bench.py`` — the PR 16 ``_percentiles["p50"]`` KeyError
was exactly the drift bug local copies invite.  Every bench and the
twin's scenario scorer import from here now, so a quantile-convention
change is one edit and every score row moves together.

Everything here is pure and deterministic (no clock, no rng) — the
twin's byte-identical-score-per-seed contract depends on that.
"""

from __future__ import annotations

import statistics


def pct(xs, q: float) -> float:
    """Nearest-rank percentile (the ONE quantile the benches share —
    three local copies drifted toward divergence before r11); 0.0 on
    an empty sample."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def percentiles(samples: list[float], digits: int = 3) -> dict:
    """The bench summary-row shape: ``value`` (median), ``p90``,
    ``min``, ``max`` — rounded, stable key order.  Raises on an empty
    sample the same way the local copies did (callers guard)."""
    samples = sorted(samples)
    return {
        "value": round(statistics.median(samples), digits),
        "p90": round(samples[int(0.9 * (len(samples) - 1))], digits),
        "min": round(samples[0], digits),
        "max": round(samples[-1], digits),
    }


def round_floats(obj, digits: int = 6):
    """Recursively round every float in a JSON-shaped object — the
    twin's score rows pass through this before ``json.dumps`` so a
    score is byte-stable against float-repr noise."""
    if isinstance(obj, float):
        return round(obj, digits)
    if isinstance(obj, dict):
        return {k: round_floats(v, digits) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [round_floats(v, digits) for v in obj]
    return obj
