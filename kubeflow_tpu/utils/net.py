"""Small networking helpers for the local runtime."""

from __future__ import annotations

import collections
import socket
import threading

_issued_lock = threading.Lock()
#: recently-issued ports, bounded: old entries age out so long-lived control
#: planes with replica churn can't exhaust the ephemeral range
_issued: "collections.deque[int]" = collections.deque(maxlen=2048)


def free_port() -> int:
    """Ask the kernel for an unused TCP port (coordinator rendezvous)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return s.getsockname()[1]


def allocate_port() -> int:
    """A free port that has not been issued to anyone else in this process.

    Control-plane port allocation (coordinator rendezvous for gangs, gRPC
    services) funnels through here so that concurrent reconciles — e.g. N
    parallel HPO trials submitted in the same tick — can never be handed the
    same port even if the kernel would recycle it between ``free_port`` calls.
    The reservation window is the deque's length, not forever.
    """
    with _issued_lock:
        for _ in range(128):
            p = free_port()
            if p not in _issued:
                _issued.append(p)
                return p
        raise OSError("could not allocate an unissued port after 128 attempts")
