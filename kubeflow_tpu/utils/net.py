"""Small networking helpers for the local runtime."""

from __future__ import annotations

import socket


def free_port() -> int:
    """Ask the kernel for an unused TCP port (coordinator rendezvous)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return s.getsockname()[1]
