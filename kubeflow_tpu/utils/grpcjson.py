"""Shared JSON-over-gRPC transport bits.

protoc stubs aren't available in this image (no grpcio-tools), so every
gRPC boundary here (hpo suggestion service, V2 inference service) rides
grpc's generic handler with JSON payloads.  The encoding and the bind
check live in one place so the wire fronts cannot drift.
"""

from __future__ import annotations

import json


def serialize(payload: dict) -> bytes:
    return json.dumps(payload).encode()


def deserialize(data: bytes) -> dict:
    return json.loads(data.decode())


def bind_insecure(server, host: str, port: int) -> None:
    """add_insecure_port with a loud failure: grpc signals a failed bind by
    returning 0, which would otherwise yield a silently dead server."""
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"could not bind gRPC port {host}:{port}")
