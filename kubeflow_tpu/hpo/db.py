"""Durable observation store — the katib-db-manager analog.

Katib persists observation logs in MySQL behind a gRPC facade
(``ReportObservationLog``/``GetObservationLog`` [upstream: kubeflow/katib ->
cmd/db-manager, pkg/db]) so trial history survives control-plane restarts.
Same shape here: a sqlite-backed store behind a real gRPC boundary (JSON
payloads over grpc's generic handler, matching kubeflow_tpu.hpo.service's
convention since protoc stubs aren't available in this image).

Consumers:
- TrialController reports each completed trial's objective observation;
- SuggestionController folds stored observations into algorithm history;
- ExperimentController REPLAYS stored observations on restart: completed
  trials from a previous incarnation of the control plane are recreated as
  Succeeded Trial objects, so a resumed experiment keeps its full history
  and does not re-run finished work.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from concurrent import futures
from typing import Optional

import grpc

from ..utils.net import allocate_port

SERVICE = "kubeflow_tpu.hpo.DbManager"
METHOD_REPORT = f"/{SERVICE}/ReportObservation"
METHOD_GET = f"/{SERVICE}/GetObservations"


class ObservationDb:
    """sqlite-backed observation log (one row per completed trial)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS observations (
                    experiment TEXT NOT NULL,
                    namespace TEXT NOT NULL DEFAULT 'default',
                    trial TEXT NOT NULL,
                    assignments TEXT NOT NULL,
                    value REAL,
                    phase TEXT NOT NULL DEFAULT 'Succeeded',
                    ts REAL DEFAULT (strftime('%s', 'now')),
                    PRIMARY KEY (experiment, namespace, trial)
                )"""
            )
            self._conn.commit()

    def report(
        self,
        experiment: str,
        trial: str,
        assignments: dict,
        value: Optional[float],
        namespace: str = "default",
        phase: str = "Succeeded",
    ) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO observations "
                "(experiment, namespace, trial, assignments, value, phase) "
                "VALUES (?, ?, ?, ?, ?, ?)",
                (experiment, namespace, trial, json.dumps(assignments), value, phase),
            )
            self._conn.commit()

    def observations(self, experiment: str, namespace: str = "default") -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT trial, assignments, value, phase FROM observations "
                "WHERE experiment = ? AND namespace = ? ORDER BY trial",
                (experiment, namespace),
            ).fetchall()
        return [
            {
                "trial": t,
                "assignments": json.loads(a),
                "value": v,
                "phase": ph,
            }
            for t, a, v, ph in rows
        ]

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def _serialize(payload: dict) -> bytes:
    return json.dumps(payload).encode()


def _deserialize(data: bytes) -> dict:
    return json.loads(data.decode())


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, db: ObservationDb) -> None:
        self._db = db
        self._methods = {
            METHOD_REPORT: grpc.unary_unary_rpc_method_handler(
                self._report,
                request_deserializer=_deserialize,
                response_serializer=_serialize,
            ),
            METHOD_GET: grpc.unary_unary_rpc_method_handler(
                self._get,
                request_deserializer=_deserialize,
                response_serializer=_serialize,
            ),
        }

    def service(self, handler_call_details):
        return self._methods.get(handler_call_details.method)

    def _report(self, request: dict, context) -> dict:
        try:
            self._db.report(
                experiment=request["experiment"],
                trial=request["trial"],
                assignments=request.get("assignments", {}),
                value=request.get("value"),
                namespace=request.get("namespace", "default"),
                phase=request.get("phase", "Succeeded"),
            )
            return {"ok": True}
        except Exception as e:  # noqa: BLE001 — surface as RPC error
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

    def _get(self, request: dict, context) -> dict:
        try:
            obs = self._db.observations(
                request["experiment"], request.get("namespace", "default"))
            return {"observations": obs}
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")


class DbManagerServer:
    """The katib-db-manager deployment analog: one per control plane."""

    def __init__(self, db_path: str, port: Optional[int] = None):
        self.db = ObservationDb(db_path)
        self.port = port or allocate_port()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self._server.add_generic_rpc_handlers((_Handler(self.db),))
        self._server.add_insecure_port(f"127.0.0.1:{self.port}")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "DbManagerServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1.0)
        self.db.close()


class DbManagerClient:
    def __init__(self, address: str):
        self._channel = grpc.insecure_channel(address)
        self._report = self._channel.unary_unary(
            METHOD_REPORT, request_serializer=_serialize,
            response_deserializer=_deserialize)
        self._get = self._channel.unary_unary(
            METHOD_GET, request_serializer=_serialize,
            response_deserializer=_deserialize)

    def report_observation(
        self,
        experiment: str,
        trial: str,
        assignments: dict,
        value: Optional[float],
        namespace: str = "default",
        phase: str = "Succeeded",
        timeout: float = 10.0,
    ) -> None:
        self._report(
            {
                "experiment": experiment,
                "namespace": namespace,
                "trial": trial,
                "assignments": assignments,
                "value": value,
                "phase": phase,
            },
            timeout=timeout,
        )

    def get_observations(
        self, experiment: str, namespace: str = "default", timeout: float = 10.0
    ) -> list[dict]:
        return self._get(
            {"experiment": experiment, "namespace": namespace}, timeout=timeout
        )["observations"]

    def close(self) -> None:
        self._channel.close()
