"""Durable observation store — the katib-db-manager analog.

Katib persists observation logs in MySQL behind a gRPC facade
(``ReportObservationLog``/``GetObservationLog`` [upstream: kubeflow/katib ->
cmd/db-manager, pkg/db]) so trial history survives control-plane restarts.
Same shape here: a sqlite-backed store behind a real gRPC boundary (JSON
payloads over grpc's generic handler, matching kubeflow_tpu.hpo.service's
convention since protoc stubs aren't available in this image).

Consumers:
- TrialController reports each completed trial's objective observation;
- SuggestionController folds stored observations into algorithm history;
- ExperimentController REPLAYS stored observations on restart: completed
  trials from a previous incarnation of the control plane are recreated as
  Succeeded Trial objects, so a resumed experiment keeps its full history
  and does not re-run finished work.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from concurrent import futures
from typing import Optional

import grpc

from ..utils.net import allocate_port

SERVICE = "kubeflow_tpu.hpo.DbManager"
METHOD_REPORT = f"/{SERVICE}/ReportObservation"
METHOD_GET = f"/{SERVICE}/GetObservations"
METHOD_LOG = f"/{SERVICE}/GetObservationLog"


class ObservationDb:
    """sqlite-backed observation log (one row per completed trial)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS observations (
                    experiment TEXT NOT NULL,
                    namespace TEXT NOT NULL DEFAULT 'default',
                    trial TEXT NOT NULL,
                    assignments TEXT NOT NULL,
                    value REAL,
                    phase TEXT NOT NULL DEFAULT 'Succeeded',
                    step INTEGER NOT NULL DEFAULT -1,
                    ts REAL DEFAULT (strftime('%s', 'now')),
                    PRIMARY KEY (experiment, namespace, trial, step)
                )"""
            )
            # migrate pre-step-column DBs (PK was (exp, ns, trial)): the
            # PK can't be ALTERed, so rebuild — existing rows become the
            # final (step=-1) observations, which is exactly what they were
            cols = [r[1] for r in self._conn.execute(
                "PRAGMA table_info(observations)")]
            if "step" not in cols:
                self._conn.executescript(
                    """ALTER TABLE observations RENAME TO observations_v1;
                    CREATE TABLE observations (
                        experiment TEXT NOT NULL,
                        namespace TEXT NOT NULL DEFAULT 'default',
                        trial TEXT NOT NULL,
                        assignments TEXT NOT NULL,
                        value REAL,
                        phase TEXT NOT NULL DEFAULT 'Succeeded',
                        step INTEGER NOT NULL DEFAULT -1,
                        ts REAL DEFAULT (strftime('%s', 'now')),
                        PRIMARY KEY (experiment, namespace, trial, step)
                    );
                    INSERT INTO observations
                        (experiment, namespace, trial, assignments, value,
                         phase, step, ts)
                    SELECT experiment, namespace, trial, assignments, value,
                           phase, -1, ts FROM observations_v1;
                    DROP TABLE observations_v1;"""
                )
            self._conn.commit()

    def report(
        self,
        experiment: str,
        trial: str,
        assignments: dict,
        value: Optional[float],
        namespace: str = "default",
        phase: str = "Succeeded",
        step: int = -1,
    ) -> None:
        """``step = -1`` is the FINAL observation (what suggesters replay);
        ``step >= 0`` rows are the per-step metric log behind the
        experiment-curves view (Katib's ReportObservationLog keeps the
        full timestamped series the same way)."""
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO observations "
                "(experiment, namespace, trial, assignments, value, phase, step) "
                "VALUES (?, ?, ?, ?, ?, ?, ?)",
                (experiment, namespace, trial, json.dumps(assignments), value,
                 phase, step),
            )
            self._conn.commit()

    def observations(self, experiment: str, namespace: str = "default") -> list[dict]:
        """Final observation per trial (the replay surface): the step=-1
        row, or the latest step if only per-step rows exist."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT trial, assignments, value, phase, step FROM observations "
                "WHERE experiment = ? AND namespace = ? ORDER BY trial, step",
                (experiment, namespace),
            ).fetchall()
        final: dict[str, dict] = {}
        for t, a, v, ph, step in rows:
            prev = final.get(t)
            # -1 sorts first but wins; otherwise the max step wins
            if prev is None or prev["_step"] != -1:
                final[t] = {
                    "trial": t, "assignments": json.loads(a),
                    "value": v, "phase": ph, "_step": step,
                }
        return [
            {k: v for k, v in rec.items() if k != "_step"}
            for rec in final.values()
        ]

    def report_series(
        self,
        experiment: str,
        trial: str,
        assignments: dict,
        series: list[tuple[int, float]],
        namespace: str = "default",
        phase: str = "Succeeded",
    ) -> None:
        """Whole per-step metric series in ONE transaction (a row per step
        via the reconcile path would stall the workqueue on long runs)."""
        with self._lock:
            self._conn.executemany(
                "INSERT OR REPLACE INTO observations "
                "(experiment, namespace, trial, assignments, value, phase, step) "
                "VALUES (?, ?, ?, ?, ?, ?, ?)",
                [
                    (experiment, namespace, trial, json.dumps(assignments),
                     value, phase, step)
                    for step, value in series
                ],
            )
            self._conn.commit()

    def observation_log(
        self, experiment: str, namespace: str = "default"
    ) -> list[dict]:
        """EVERY observation row incl. per-step metrics, step-ordered per
        trial (the experiment-curves surface)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT trial, assignments, value, phase, step FROM observations "
                "WHERE experiment = ? AND namespace = ? ORDER BY trial, step",
                (experiment, namespace),
            ).fetchall()
        return [
            {
                "trial": t,
                "assignments": json.loads(a),
                "value": v,
                "phase": ph,
                "step": step,
            }
            for t, a, v, ph, step in rows
        ]

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def _serialize(payload: dict) -> bytes:
    return json.dumps(payload).encode()


def _deserialize(data: bytes) -> dict:
    return json.loads(data.decode())


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, db: ObservationDb) -> None:
        self._db = db
        self._methods = {
            METHOD_REPORT: grpc.unary_unary_rpc_method_handler(
                self._report,
                request_deserializer=_deserialize,
                response_serializer=_serialize,
            ),
            METHOD_GET: grpc.unary_unary_rpc_method_handler(
                self._get,
                request_deserializer=_deserialize,
                response_serializer=_serialize,
            ),
            METHOD_LOG: grpc.unary_unary_rpc_method_handler(
                self._log,
                request_deserializer=_deserialize,
                response_serializer=_serialize,
            ),
        }

    def service(self, handler_call_details):
        return self._methods.get(handler_call_details.method)

    def _report(self, request: dict, context) -> dict:
        try:
            if "series" in request:
                # batched per-step log: one RPC, one transaction
                self._db.report_series(
                    experiment=request["experiment"],
                    trial=request["trial"],
                    assignments=request.get("assignments", {}),
                    series=[
                        (int(s), float(v)) for s, v in request["series"]],
                    namespace=request.get("namespace", "default"),
                    phase=request.get("phase", "Succeeded"),
                )
                return {"ok": True}
            self._db.report(
                experiment=request["experiment"],
                trial=request["trial"],
                assignments=request.get("assignments", {}),
                value=request.get("value"),
                namespace=request.get("namespace", "default"),
                phase=request.get("phase", "Succeeded"),
                step=int(request.get("step", -1)),
            )
            return {"ok": True}
        except Exception as e:  # noqa: BLE001 — surface as RPC error
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

    def _get(self, request: dict, context) -> dict:
        try:
            obs = self._db.observations(
                request["experiment"], request.get("namespace", "default"))
            return {"observations": obs}
        except Exception as e:  # noqa: BLE001 — surface as RPC error
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

    def _log(self, request: dict, context) -> dict:
        try:
            return {
                "observations": self._db.observation_log(
                    request["experiment"],
                    namespace=request.get("namespace", "default"),
                )
            }
        except Exception as e:  # noqa: BLE001 — surface as RPC error
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")


class DbManagerServer:
    """The katib-db-manager deployment analog: one per control plane."""

    def __init__(self, db_path: str, port: Optional[int] = None):
        self.db = ObservationDb(db_path)
        self.port = port or allocate_port()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self._server.add_generic_rpc_handlers((_Handler(self.db),))
        self._server.add_insecure_port(f"127.0.0.1:{self.port}")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "DbManagerServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1.0)
        self.db.close()


class DbManagerClient:
    def __init__(self, address: str):
        self._channel = grpc.insecure_channel(address)
        self._report = self._channel.unary_unary(
            METHOD_REPORT, request_serializer=_serialize,
            response_deserializer=_deserialize)
        self._get = self._channel.unary_unary(
            METHOD_GET, request_serializer=_serialize,
            response_deserializer=_deserialize)
        self._getlog = self._channel.unary_unary(
            METHOD_LOG, request_serializer=_serialize,
            response_deserializer=_deserialize)

    def report_observation(
        self,
        experiment: str,
        trial: str,
        assignments: dict,
        value: Optional[float],
        namespace: str = "default",
        phase: str = "Succeeded",
        step: int = -1,
        timeout: float = 10.0,
    ) -> None:
        self._report(
            {
                "experiment": experiment,
                "namespace": namespace,
                "trial": trial,
                "assignments": assignments,
                "value": value,
                "phase": phase,
                "step": step,
            },
            timeout=timeout,
        )

    def report_observation_series(
        self,
        experiment: str,
        trial: str,
        assignments: dict,
        series: list[tuple[int, float]],
        namespace: str = "default",
        timeout: float = 30.0,
    ) -> None:
        """Whole per-step metric curve in one RPC."""
        self._report(
            {
                "experiment": experiment,
                "namespace": namespace,
                "trial": trial,
                "assignments": assignments,
                "series": list(series),
            },
            timeout=timeout,
        )

    def get_observations(
        self, experiment: str, namespace: str = "default", timeout: float = 10.0
    ) -> list[dict]:
        return self._get(
            {"experiment": experiment, "namespace": namespace}, timeout=timeout
        )["observations"]

    def get_observation_log(
        self, experiment: str, namespace: str = "default", timeout: float = 10.0
    ) -> list[dict]:
        """Every observation incl. per-step rows (experiment curves)."""
        return self._getlog(
            {"experiment": experiment, "namespace": namespace}, timeout=timeout
        )["observations"]

    def close(self) -> None:
        self._channel.close()
