"""One-shot NAS: DARTS-style weight-sharing supernet over Llama shapes.

SURVEY §2.3 lists NAS (ENAS/DARTS) among the reference's suggestion
services [upstream: kubeflow/katib -> pkg/suggestion/v1beta1/nas/...];
rounds 1-2 covered architecture search only as HPO over shape ints (a
reduction: every candidate trains from scratch).  This module is the
one-shot capability: ONE supernet trains with continuous architecture
parameters, and good discrete architectures read off the learned mixture
— trial-steps-to-quality beats the from-scratch reduction because weight
sharing amortizes training across the whole space (tested closed-loop
against TPE at equal step budget).

TPU-first formulation (everything static-shaped, one jitted train step):

- **depth**: the supernet runs all ``L_max`` blocks and mixes the
  per-depth hidden states with ``softmax(alpha_depth)`` — the DARTS
  continuous relaxation of "how many layers".
- **FFN width**: width choices nest, so mixing over masked widths
  collapses to one elementwise column gate: ``gate_j = sum of
  softmax(alpha_ffn)[c] over choices c wider than j``.  No per-choice
  branches, no dynamic shapes — the mixture costs ONE max-width MLP.
- first-order BILEVEL DARTS: weights step on training batches, alphas
  step on held-out batches (the alpha-overfitting mitigation; still no
  second-order unrolled weight step in the alpha gradient — that is the
  remaining gap to full DARTS, stated rather than implied).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ..models import llama as llamalib


@dataclasses.dataclass(frozen=True)
class ArchSpace:
    """The searched slice of the Llama shape space."""

    max_layers: int = 6
    ffn_widths: tuple[int, ...] = (64, 128)  # intermediate sizes, ascending

    def __post_init__(self):
        if list(self.ffn_widths) != sorted(set(self.ffn_widths)):
            raise ValueError("ffn_widths must be ascending and unique")


class _GatedMlp(nn.Module):
    """Llama gated MLP with a per-column width gate (the nested-mask
    mixture over FFN width choices)."""

    cfg: llamalib.LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, gate: jax.Array) -> jax.Array:
        cfg = self.cfg
        h_dim = x.shape[-1]
        from functools import partial

        proj = partial(
            llamalib.Einsum, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        g = proj("bse,em->bsm", (h_dim, cfg.intermediate_size),
                 ("embed", "mlp"), name="w_gate")(x)
        up = proj("bse,em->bsm", (h_dim, cfg.intermediate_size),
                  ("embed", "mlp"), name="w_up")(x)
        hidden = nn.silu(g) * up * gate  # gate: [m] soft width mask
        return proj("bsm,me->bse", (cfg.intermediate_size, h_dim),
                    ("mlp", "embed"), name="w_down")(hidden)


class _SuperBlock(nn.Module):
    cfg: llamalib.LlamaConfig

    @nn.compact
    def __call__(self, x, positions, gate):
        cfg = self.cfg
        h = llamalib.RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="attn_norm")(x)
        x = x + llamalib.Attention(cfg, name="attn")(h, positions)
        h = llamalib.RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="mlp_norm")(x)
        x = x + _GatedMlp(cfg, name="mlp")(h, gate)
        return x


class SupernetLM(nn.Module):
    """Weight-sharing Llama supernet with architecture parameters.

    ``alpha_depth`` [L_max] and ``alpha_ffn`` [len(ffn_widths)] live in
    the ``arch`` param collection so the optimizer can treat them
    separately from weights.
    """

    cfg: llamalib.LlamaConfig  # at max shape (intermediate_size = widest)
    space: ArchSpace

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        cfg, space = self.cfg, self.space
        positions = jnp.arange(tokens.shape[-1])[None, :]
        alpha_d = self.param(
            "alpha_depth", nn.initializers.zeros, (space.max_layers,),
            jnp.float32)
        alpha_f = self.param(
            "alpha_ffn", nn.initializers.zeros, (len(space.ffn_widths),),
            jnp.float32)

        # nested width masks -> one soft column gate
        widths = jnp.asarray(space.ffn_widths)
        cols = jnp.arange(cfg.intermediate_size)
        nested = (cols[None, :] < widths[:, None]).astype(jnp.float32)
        gate = jax.nn.softmax(alpha_f) @ nested  # [intermediate_size]

        x = llamalib.Embedder(cfg, name="embedder")(tokens)
        depth_w = jax.nn.softmax(alpha_d)
        mix = jnp.zeros_like(x)
        for layer in range(space.max_layers):
            x = _SuperBlock(cfg, name=f"layer_{layer}")(x, positions, gate)
            mix = mix + depth_w[layer] * x
        return llamalib.Head(cfg, name="head")(mix)


@dataclasses.dataclass
class NasResult:
    alpha_depth: np.ndarray
    alpha_ffn: np.ndarray
    #: (layers, ffn_width) ranked by joint architecture probability
    ranked: list[tuple[int, int]]
    final_loss: float


def darts_search(
    base_cfg: llamalib.LlamaConfig,
    space: ArchSpace,
    batches: Iterator[Any],
    *,
    steps: int = 200,
    weights_lr: float = 3e-3,
    arch_lr: float = 3e-2,
    seed: int = 0,
    val_batches: Optional[Iterator[Any]] = None,
) -> NasResult:
    """Train the supernet for ``steps`` and read off ranked architectures.

    ``batches`` yields int32 [b, s] token arrays (next-token LM objective,
    same as the trainer's).  Architecture params get their own learning
    rate (DARTS convention: alphas move faster than weights but start
    uniform).

    First-order BILEVEL optimization (r3 verdict weak #5): weights update
    on training batches, alphas update on HELD-OUT batches
    (``val_batches``; defaults to alternating draws from ``batches``, a
    proper split for i.i.d. streams) — alphas trained on the same batches
    as weights is the classic DARTS alpha-overfitting failure mode.
    Still first-order (no unrolled weight step in the alpha gradient);
    the closed-loop bar in tests/test_nas.py is what keeps this honest.
    """
    cfg = dataclasses.replace(
        base_cfg,
        num_layers=space.max_layers,
        intermediate_size=space.ffn_widths[-1],
        scan_layers=False, remat=False,
    )
    model = SupernetLM(cfg, space)
    first = next(batches)
    params = model.init(jax.random.PRNGKey(seed), jnp.asarray(first))["params"]

    def is_arch(path: tuple) -> bool:
        return any(getattr(k, "key", None) in ("alpha_depth", "alpha_ffn")
                   for k in path)

    label = jax.tree_util.tree_map_with_path(
        lambda p, _: "arch" if is_arch(p) else "weights", params)
    # two optimizers, alternated (bilevel): each phase freezes the other
    # group via set_to_zero so its moments never see the wrong batches
    tx_w = optax.multi_transform(
        {"weights": optax.adamw(weights_lr), "arch": optax.set_to_zero()},
        label)
    tx_a = optax.multi_transform(
        {"weights": optax.set_to_zero(), "arch": optax.adam(arch_lr)},
        label)
    st_w = tx_w.init(params)
    st_a = tx_a.init(params)

    def loss_fn(params, tokens):
        logits = model.apply({"params": params}, tokens[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tokens[:, 1:]).mean()

    @jax.jit
    def step_weights(params, st, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, st = tx_w.update(grads, st, params)
        return optax.apply_updates(params, updates), st, loss

    @jax.jit
    def step_arch(params, st, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, st = tx_a.update(grads, st, params)
        return optax.apply_updates(params, updates), st, loss

    if val_batches is None:
        # alternate draws from the one stream: train/val never share a
        # batch (a real split for i.i.d. streams)
        train_stream, val_stream = batches, batches
    else:
        train_stream, val_stream = batches, val_batches

    loss = jnp.inf
    tokens = jnp.asarray(first)
    for i in range(steps):
        params, st_w, loss = step_weights(params, st_w, tokens)
        val_tok = jnp.asarray(next(val_stream))
        params, st_a, _ = step_arch(params, st_a, val_tok)
        tokens = jnp.asarray(next(train_stream))

    a_d = np.asarray(params["alpha_depth"], np.float64)
    a_f = np.asarray(params["alpha_ffn"], np.float64)
    p_d = np.exp(a_d - a_d.max()); p_d /= p_d.sum()
    p_f = np.exp(a_f - a_f.max()); p_f /= p_f.sum()
    combos = [
        (int(layer + 1), int(w), float(p_d[layer] * p_f[c]))
        for layer in range(space.max_layers)
        for c, w in enumerate(space.ffn_widths)
    ]
    combos.sort(key=lambda t: -t[2])
    return NasResult(
        alpha_depth=a_d, alpha_ffn=a_f,
        ranked=[(layers, w) for layers, w, _ in combos],
        final_loss=float(loss),
    )


# -- suggester integration ----------------------------------------------------

#: task registry: experiments point the darts suggester at a supernet
#: task via settings {"task_ref": "<key>"}; the value is a zero-arg
#: callable -> (base_cfg, ArchSpace, batch_iterator)
_TASKS: dict[str, Callable[[], tuple]] = {}


def register_task(key: str, factory: Callable[[], tuple]) -> str:
    _TASKS[key] = factory
    return key


#: supernet runs keyed by (task, steps, seed) — MODULE level, because the
#: suggestion service constructs a fresh suggester per RPC; a per-instance
#: cache would retrain the supernet on every GetSuggestions call
_RANKING_CACHE: dict[str, list[tuple[int, int]]] = {}


class OneShotNas:
    """Katib-style suggester façade over ``darts_search``.

    The reference's DARTS suggestion service receives the search space
    and the trial trains the supernet; here the (in-process) suggestion
    service runs the supernet itself on first call — one shot — and then
    suggests architectures in ranked order for verification trials.
    Stateless-replay safe: same settings + seed -> same supernet run ->
    same ranking (cached per settings fingerprint).
    """

    name = "darts"

    def suggest(self, req) -> list[dict[str, object]]:
        settings = req.settings
        key = settings.get("task_ref", "")
        if key not in _TASKS:
            raise ValueError(
                f"darts suggester needs settings.task_ref naming a "
                f"registered nas task; got {key!r}")
        fp = f"{key}:{settings.get('supernet_steps', '')}:{req.seed}"
        if fp not in _RANKING_CACHE:
            base_cfg, space, batches = _TASKS[key]()
            result = darts_search(
                base_cfg, space, batches,
                steps=int(settings.get("supernet_steps", 200)),
                seed=req.seed or 0,
            )
            _RANKING_CACHE[fp] = result.ranked
        ranked = _RANKING_CACHE[fp]
        out = []
        # finite space: stop at the end instead of cycling — returning
        # fewer than requested is the suggester-exhausted contract
        # (GridSearch does the same), so the experiment doesn't burn its
        # budget re-evaluating duplicate architectures
        for i in range(req.count):
            pos = req.issued + i
            if pos >= len(ranked):
                break
            layers, width = ranked[pos]
            out.append({"layers": layers, "ffn_width": width})
        return out
