"""ASHA early stopping (Asynchronous Successive Halving).

The biggest practical Katib win for expensive TPU trials [upstream: Katib
early-stopping services, pkg/earlystopping/; ASHA per Li et al. 2018]:
instead of running every trial to completion, trials are compared at
exponentially-spaced resource milestones ("rungs", ``min_resource *
reduction_factor^k`` steps) and only the top ``1/reduction_factor`` at each
rung continue.  Asynchronous: a trial is judged against whatever peer
results exist at its rung right now — no synchronized brackets, no waiting,
which is what makes it fit a parallel-trial control loop.

Wiring: trials stream per-step metrics through ``bootstrap.emit_metric``
(the ``step`` extra); the TrialController records the objective at each
rung milestone into ``Trial.status.rung_values`` and consults this policy.
A stopped trial becomes phase ``EarlyStopped`` with its last observation —
it counts toward the experiment budget but not the optimum.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ..api.experiment import EarlyStoppingSpec, ObjectiveType


@dataclasses.dataclass(frozen=True)
class Asha:
    min_resource: int = 2
    reduction_factor: int = 3
    #: rungs below this index never stop a trial (grace period)
    start_rung: int = 0

    @classmethod
    def from_spec(cls, spec: EarlyStoppingSpec) -> "Asha":
        s = spec.settings
        return cls(
            min_resource=int(s.get("min_resource", "2")),
            reduction_factor=int(s.get("reduction_factor", "3")),
            start_rung=int(s.get("start_rung", "0")),
        )

    def rung_for(self, step: int) -> Optional[int]:
        """Highest rung index whose milestone is <= step (None below rung 0)."""
        if step < self.min_resource:
            return None
        rung, milestone = 0, self.min_resource
        while milestone * self.reduction_factor <= step:
            milestone *= self.reduction_factor
            rung += 1
        return rung

    def milestone(self, rung: int) -> int:
        return self.min_resource * self.reduction_factor ** rung

    def should_stop(
        self,
        objective_type: ObjectiveType,
        rung: int,
        value: float,
        peer_values: Sequence[float],
    ) -> bool:
        """Asynchronous promotion rule: continue only if ``value`` is in the
        top ``1/reduction_factor`` of all values recorded at this rung
        (including itself).  With fewer than ``reduction_factor`` records
        the trial always continues — ASHA promotes optimistically early."""
        if rung < self.start_rung:
            return False
        values = [*peer_values, value]
        if len(values) < self.reduction_factor:
            return False
        reverse = objective_type == ObjectiveType.MAXIMIZE
        ranked = sorted(values, reverse=reverse)
        k = max(1, len(values) // self.reduction_factor)
        threshold = ranked[k - 1]
        return value < threshold if reverse else value > threshold
