"""HPO plane: Experiment/Suggestion/Trial reconcilers + algorithm services
(the Katib capability tier, SURVEY.md §2.3)."""

from .algorithms import (
    BayesianOptimization,
    GridSearch,
    Observation,
    RandomSearch,
    SuggestRequest,
    Tpe,
    get_suggester,
)
from .controllers import ExperimentController, SuggestionController, TrialController
from .service import SuggestionClient, SuggestionServer

__all__ = [
    "BayesianOptimization",
    "ExperimentController",
    "GridSearch",
    "Observation",
    "RandomSearch",
    "SuggestRequest",
    "SuggestionClient",
    "SuggestionController",
    "SuggestionServer",
    "Tpe",
    "TrialController",
    "get_suggester",
]
