"""HPO reconcilers: Experiment -> Suggestion -> Trial -> JaxJob.

Katib's controller triple rebuilt on this control plane (SURVEY.md §2.3,
§3.4) [upstream: kubeflow/katib -> pkg/controller.v1beta1/{experiment,
suggestion,trial}]:

- ExperimentController keeps ``parallel_trial_count`` trials in flight until
  ``max_trial_count`` or the objective goal is reached; tracks the optimum.
- SuggestionController "deploys" the algorithm service (a real gRPC server
  per experiment, kubeflow_tpu.hpo.service) and fills assignment requests,
  feeding back completed-trial observations — the GetSuggestions loop.
- TrialController materializes each trial's JaxJob from the experiment's
  trial template (``${trialParameters.x}`` substituted), follows its
  conditions, and scrapes the objective metric the way Katib's metrics
  collector does: from the pods' metric streams (status-dir jsonl written by
  ``bootstrap.emit_metric``; stdout ``name=value`` lines as fallback).
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Callable, Optional

from ..api.common import (
    JobCondition,
    JobConditionType,
    ObjectMeta,
    OwnerReference,
    has_condition,
    replica_pod_name,
    set_condition,
)
from ..api.experiment import (
    KIND_EXPERIMENT,
    KIND_SUGGESTION,
    KIND_TRIAL,
    Experiment,
    ObjectiveType,
    Suggestion,
    SuggestionSpec,
    Trial,
    TrialAssignment,
    TrialSpec,
    substitute_parameters,
)
from ..api.jaxjob import KIND_JAXJOB, JaxJob
from ..api.yaml_io import from_dict
from ..controlplane.controller import Controller, Result
from ..controlplane.store import AlreadyExists, NotFound, Store
from . import algorithms
from .db import DbManagerClient
from .early_stopping import Asha
from .service import SuggestionClient, SuggestionServer

log = logging.getLogger("kubeflow_tpu.hpo")

_METRIC_LINE_RE = re.compile(r"^([A-Za-z0-9_.\-]+)=([-+0-9.eE]+)\s*$")


def _trial_name(exp: str, index: int) -> str:
    return f"{exp}-t{index:04d}"


class ExperimentController(Controller):
    kind = KIND_EXPERIMENT
    owned_kinds = (KIND_TRIAL, KIND_SUGGESTION)

    def __init__(self, store: Store, db: Optional["DbManagerClient"] = None) -> None:
        super().__init__(store)
        self.db = db

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        exp = self.store.try_get(KIND_EXPERIMENT, name, namespace)
        if exp is None:
            return None
        assert isinstance(exp, Experiment)
        if exp.status.completed:
            return None

        if self.db is not None and not exp.status.replayed:
            exp = self._replay_observations(exp)

        trials = [
            t
            for t in self.store.list(KIND_TRIAL, namespace)
            if isinstance(t, Trial) and t.spec.experiment_name == name
        ]
        succeeded = [t for t in trials if t.status.phase == "Succeeded"]
        failed = [t for t in trials if t.status.phase == "Failed"]
        early = [t for t in trials if t.status.phase == "EarlyStopped"]
        running = [t for t in trials if t.status.phase in ("Pending", "Running")]

        optimal_name, optimal_value, optimal_assign = self._optimum(exp, succeeded)

        done_reason = self._done_reason(
            exp, len(trials), succeeded, failed, early, optimal_value)
        if done_reason and not running:
            self._finish(
                exp, done_reason, trials, succeeded, failed, early,
                optimal_name, optimal_value, optimal_assign)
            return None

        # how many fresh trials to keep the pipeline full
        want = 0
        if not done_reason:
            budget = exp.spec.max_trial_count - len(trials)
            slots = exp.spec.parallel_trial_count - len(running)
            want = max(0, min(budget, slots))

        sugg = self._ensure_suggestion(exp, requests=len(trials) + want)
        available = sugg.status.assignments
        created = 0
        for i in range(len(trials), min(len(trials) + want, len(available))):
            if self._create_trial(exp, i, available[i]):
                created += 1

        self._update_status(
            exp, trials, succeeded, failed, early, running,
            optimal_name, optimal_value, optimal_assign)
        # requeue while in flight: metric scraping + suggestion fills are async
        return Result(requeue_after=0.05 if (running or want > created) else None)

    # -- pieces ---------------------------------------------------------------

    def _replay_observations(self, exp: Experiment) -> Experiment:
        """Rebuild Succeeded Trials from the durable observation store.

        After a control-plane restart the in-memory Trial objects are gone
        but the db-manager still has every completed observation; recreating
        them as terminal Trials restores full history — counters, optimum
        tracking, and algorithm history all work unchanged — without
        re-running finished trials (katib-db-manager capability, SURVEY
        §2.3)."""
        ns, name = exp.metadata.namespace, exp.metadata.name
        replayed = 0
        try:
            records = self.db.get_observations(name, ns)
        except Exception:  # noqa: BLE001 — db unavailable: retry next pass
            return exp
        for rec in records:
            if (
                rec.get("phase") not in ("Succeeded", "EarlyStopped")
                or rec.get("value") is None
            ):
                continue
            if self.store.try_get(KIND_TRIAL, rec["trial"], ns) is not None:
                continue
            trial = Trial(
                metadata=ObjectMeta(
                    name=rec["trial"], namespace=ns,
                    owner_references=[
                        OwnerReference(kind=KIND_EXPERIMENT, name=name,
                                       uid=exp.metadata.uid)],
                ),
                spec=TrialSpec(
                    experiment_name=name,
                    assignments=[
                        TrialAssignment(name=k, value=v)
                        for k, v in rec["assignments"].items()
                    ],
                    objective_metric_name=exp.spec.objective.objective_metric_name,
                ),
            )
            trial.status.phase = rec["phase"]
            trial.status.observation = rec["value"]
            try:
                self.store.create(trial)
                replayed += 1
            except AlreadyExists:
                pass

        def mut(o):
            assert isinstance(o, Experiment)
            o.status.replayed = True

        try:
            exp = self.store.update_with_retry(KIND_EXPERIMENT, name, ns, mut)
        except NotFound:
            pass
        if replayed:
            self.emit_event(
                exp, "ObservationsReplayed",
                f"{replayed} completed trials restored from the observation store")
        assert isinstance(exp, Experiment)
        return exp

    def _optimum(self, exp: Experiment, succeeded: list[Trial]):
        best_name, best_val, best_assign = None, None, []
        sign = 1.0 if exp.spec.objective.type == ObjectiveType.MAXIMIZE else -1.0
        for t in succeeded:
            if t.status.observation is None:
                continue
            v = t.status.observation
            if best_val is None or sign * v > sign * best_val:
                best_name, best_val, best_assign = t.metadata.name, v, t.spec.assignments
        return best_name, best_val, best_assign

    def _done_reason(self, exp, n_trials, succeeded, failed, early, optimal_value) -> str:
        goal = exp.spec.objective.goal
        terminal = len(succeeded) + len(failed) + len(early)
        if goal is not None and optimal_value is not None:
            if exp.spec.objective.type == ObjectiveType.MAXIMIZE and optimal_value >= goal:
                return "GoalReached"
            if exp.spec.objective.type == ObjectiveType.MINIMIZE and optimal_value <= goal:
                return "GoalReached"
        if exp.spec.max_failed_trial_count and len(failed) >= exp.spec.max_failed_trial_count:
            return "MaxFailedTrialsReached"
        if terminal >= exp.spec.max_trial_count:
            return "MaxTrialsReached"
        sugg = self.store.try_get(KIND_SUGGESTION, exp.metadata.name, exp.metadata.namespace)
        if (
            isinstance(sugg, Suggestion)
            and sugg.status.exhausted
            and terminal >= len(sugg.status.assignments)
        ):
            return "SearchSpaceExhausted"
        return ""

    def _ensure_suggestion(self, exp: Experiment, requests: int) -> Suggestion:
        ns, name = exp.metadata.namespace, exp.metadata.name
        sugg = self.store.try_get(KIND_SUGGESTION, name, ns)
        if sugg is None:
            sugg = Suggestion(
                metadata=ObjectMeta(
                    name=name, namespace=ns,
                    owner_references=[
                        OwnerReference(kind=KIND_EXPERIMENT, name=name,
                                       uid=exp.metadata.uid)],
                ),
                spec=SuggestionSpec(
                    experiment_name=name,
                    algorithm=exp.spec.algorithm,
                    requests=requests,
                ),
            )
            try:
                created = self.store.create(sugg)
                self.emit_event(exp, "SuggestionCreated",
                                f"algorithm {exp.spec.algorithm.algorithm_name}")
                return created  # type: ignore[return-value]
            except AlreadyExists:
                sugg = self.store.try_get(KIND_SUGGESTION, name, ns)
        assert isinstance(sugg, Suggestion)
        if sugg.spec.requests < requests:
            def bump(o):
                assert isinstance(o, Suggestion)
                o.spec.requests = max(o.spec.requests, requests)

            try:
                sugg = self.store.update_with_retry(KIND_SUGGESTION, name, ns, bump)
            except NotFound:
                pass
        return sugg

    def _create_trial(self, exp: Experiment, index: int, assignment: dict) -> bool:
        ns = exp.metadata.namespace
        tname = _trial_name(exp.metadata.name, index)
        tmpl = exp.spec.trial_template
        manifest = substitute_parameters(tmpl.job_manifest, assignment) if tmpl else {}
        trial = Trial(
            metadata=ObjectMeta(
                name=tname, namespace=ns,
                owner_references=[
                    OwnerReference(kind=KIND_EXPERIMENT, name=exp.metadata.name,
                                   uid=exp.metadata.uid)],
            ),
            spec=TrialSpec(
                experiment_name=exp.metadata.name,
                assignments=[
                    TrialAssignment(name=k, value=v) for k, v in assignment.items()
                ],
                job_manifest=manifest,
                objective_metric_name=exp.spec.objective.objective_metric_name,
            ),
        )
        try:
            self.store.create(trial)
            self.emit_event(exp, "TrialCreated", f"{tname}: {assignment}")
            return True
        except AlreadyExists:
            return False

    def _finish(
        self, exp, reason, trials, succeeded, failed, early,
        opt_name, opt_value, opt_assign,
    ) -> None:
        def mut(o):
            assert isinstance(o, Experiment)
            o.status.completed = True
            o.status.trials_created = len(trials)
            o.status.trials_succeeded = len(succeeded)
            o.status.trials_failed = len(failed)
            o.status.trials_early_stopped = len(early)
            o.status.trials_running = 0
            o.status.current_optimal_trial = opt_name
            o.status.current_optimal_value = opt_value
            o.status.current_optimal_assignments = list(opt_assign)

        try:
            self.store.update_with_retry(
                KIND_EXPERIMENT, exp.metadata.name, exp.metadata.namespace, mut)
            self.emit_event(
                exp, reason,
                f"optimal {opt_name}={opt_value} {[(a.name, a.value) for a in opt_assign]}")
        except NotFound:
            pass
        # delete the Suggestion: its deletion event reaches the suggestion
        # controller, which tears down the algorithm gRPC server (otherwise
        # one server+channel+port leaks per finished experiment)
        self.store.try_delete(
            KIND_SUGGESTION, exp.metadata.name, exp.metadata.namespace)

    def _update_status(
        self, exp, trials, succeeded, failed, early, running,
        opt_name, opt_value, opt_assign,
    ) -> None:
        def mut(o):
            assert isinstance(o, Experiment)
            o.status.trials_created = len(trials)
            o.status.trials_succeeded = len(succeeded)
            o.status.trials_failed = len(failed)
            o.status.trials_early_stopped = len(early)
            o.status.trials_running = len(running)
            o.status.current_optimal_trial = opt_name
            o.status.current_optimal_value = opt_value
            o.status.current_optimal_assignments = list(opt_assign)

        try:
            self.store.update_with_retry(
                KIND_EXPERIMENT, exp.metadata.name, exp.metadata.namespace, mut)
        except NotFound:
            pass


class SuggestionController(Controller):
    """Runs the algorithm services and answers assignment requests.

    The Katib suggestion controller deploys a gRPC Deployment per experiment
    and calls GetSuggestions on it; here the "Deployment" is an in-process
    grpc server (real socket, real RPC) whose address lands in
    ``Suggestion.status.service_address``.
    """

    kind = KIND_SUGGESTION

    def __init__(self, store: Store, db: Optional[DbManagerClient] = None) -> None:
        super().__init__(store)
        self.db = db
        self._servers: dict[str, SuggestionServer] = {}
        self._clients: dict[str, SuggestionClient] = {}

    def stop(self) -> None:
        super().stop()
        for c in self._clients.values():
            c.close()
        for s in self._servers.values():
            s.stop()
        self._servers.clear()
        self._clients.clear()

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        key = f"{namespace}/{name}"
        sugg = self.store.try_get(KIND_SUGGESTION, name, namespace)
        if sugg is None:
            self._teardown(key)
            return None
        assert isinstance(sugg, Suggestion)
        exp = self.store.try_get(KIND_EXPERIMENT, sugg.spec.experiment_name, namespace)
        if exp is None or (isinstance(exp, Experiment) and exp.status.completed):
            self._teardown(key)
            return None
        assert isinstance(exp, Experiment)

        server = self._servers.get(key)
        if server is None:
            server = SuggestionServer().start()
            self._servers[key] = server
            self._clients[key] = SuggestionClient(server.address)

        have = len(sugg.status.assignments)
        need = sugg.spec.requests - have
        if need <= 0 and sugg.status.service_address:
            return None

        new: list[dict] = []
        exhausted = sugg.status.exhausted
        if need > 0 and not exhausted:
            history = self._history(namespace, sugg.spec.experiment_name)
            new = self._clients[key].get_suggestions(
                algorithm=sugg.spec.algorithm.algorithm_name,
                parameters=exp.spec.parameters,
                objective_type=exp.spec.objective.type,
                history=history,
                count=need,
                settings=sugg.spec.algorithm.settings,
                issued=have,
            )
            if len(new) < need:
                exhausted = True  # finite space walked out (grid)

        def mut(o):
            assert isinstance(o, Suggestion)
            o.status.service_address = server.address
            o.status.assignments = o.status.assignments + new
            o.status.exhausted = exhausted

        try:
            self.store.update_with_retry(KIND_SUGGESTION, name, namespace, mut)
        except NotFound:
            self._teardown(key)
        return None

    def _history(self, namespace: str, exp_name: str) -> list[algorithms.Observation]:
        # EarlyStopped trials carry a real observation (their value at the
        # cut) and feed the optimizer like Katib's early-stopped trials do;
        # only observation-less Failed trials are invisible to it
        observed = ("Succeeded", "EarlyStopped")
        seen: dict[str, algorithms.Observation] = {}
        for t in self.store.list(KIND_TRIAL, namespace):
            if (
                isinstance(t, Trial)
                and t.spec.experiment_name == exp_name
                and t.status.phase in observed
                and t.status.observation is not None
            ):
                seen[t.metadata.name] = algorithms.Observation(
                    assignments={a.name: a.value for a in t.spec.assignments},
                    value=t.status.observation,
                    trial=t.metadata.name,
                )
        # fold in the durable store (keyed by trial name, live objects win):
        # after a restart the algorithm keeps its full optimization history
        if self.db is not None:
            try:
                for rec in self.db.get_observations(exp_name, namespace):
                    if (
                        rec.get("phase") in observed
                        and rec.get("value") is not None
                        and rec["trial"] not in seen
                    ):
                        seen[rec["trial"]] = algorithms.Observation(
                            assignments=rec["assignments"], value=rec["value"],
                            trial=rec["trial"])
            except Exception:  # noqa: BLE001 — db unavailable: use live view
                pass
        # issue order (trial names are zero-padded, so name order == issue
        # order): generation-replay algorithms (cmaes) need history in the
        # order assignments were handed out, restart or not
        return [seen[k] for k in sorted(seen)]

    def _teardown(self, key: str) -> None:
        client = self._clients.pop(key, None)
        if client:
            client.close()
        server = self._servers.pop(key, None)
        if server:
            server.stop()


class TrialController(Controller):
    """Trial -> JaxJob -> observation (SURVEY.md §3.4 inner composition)."""

    kind = KIND_TRIAL
    owned_kinds = (KIND_JAXJOB,)

    def __init__(
        self,
        store: Store,
        metrics_root: Optional[str] = None,
        log_path_for: Optional[Callable[[str, str], str]] = None,
        db: Optional[DbManagerClient] = None,
    ) -> None:
        super().__init__(store)
        #: root of the kubelet's per-pod status dirs (metrics.jsonl files)
        self.metrics_root = metrics_root
        #: (namespace, pod_name) -> stdout log path (Katib stdout collector)
        self.log_path_for = log_path_for
        #: durable observation store client (katib-db-manager analog)
        self.db = db

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        trial = self.store.try_get(KIND_TRIAL, name, namespace)
        if trial is None:
            self.store.try_delete(KIND_JAXJOB, name, namespace)
            return None
        assert isinstance(trial, Trial)
        # EarlyStopped is terminal too: its reconcile fires once more when
        # ASHA deletes the owned job, and recreating the job here would
        # resurrect the trial and overwrite the phase with Succeeded
        if trial.status.phase in ("Succeeded", "Failed", "EarlyStopped"):
            return None

        job = self.store.try_get(KIND_JAXJOB, name, namespace)
        if job is None:
            manifest = dict(trial.spec.job_manifest)
            manifest.setdefault("kind", KIND_JAXJOB)
            manifest.setdefault("metadata", {})
            manifest["metadata"].update({"name": name, "namespace": namespace})
            obj = from_dict(manifest)
            assert isinstance(obj, JaxJob)
            obj.metadata.owner_references = [
                OwnerReference(kind=KIND_TRIAL, name=name, uid=trial.metadata.uid)
            ]
            try:
                self.store.create(obj)
                self.emit_event(trial, "JobCreated", name)
            except AlreadyExists:
                pass
            self._set_phase(trial, "Running")
            return Result(requeue_after=0.05)
        assert isinstance(job, JaxJob)

        if has_condition(job.status.conditions, JobConditionType.SUCCEEDED):
            # one pass over the metric streams: final values AND the
            # objective's per-step series (re-reading the jsonl for the
            # series would double the reconcile-thread IO on long runs)
            metrics, series = self._scrape_with_series(
                namespace, job, trial.spec.objective_metric_name)
            objective = metrics.get(trial.spec.objective_metric_name)
            if objective is None:
                # grace period for scrape latency; then fail loudly rather
                # than count a metric-less trial as Succeeded (Katib's
                # MetricsUnavailable semantics)
                completed = job.status.completion_time or time.time()
                if time.time() - completed < 2.0:
                    return Result(requeue_after=0.1)
                self._set_phase(trial, "Failed", metrics=metrics)
                self.emit_event(
                    trial, "MetricsUnavailable",
                    f"objective {trial.spec.objective_metric_name!r} never "
                    "observed in any worker's metrics", type_="Warning")
                return None
            self._set_phase(trial, "Succeeded", observation=objective, metrics=metrics)
            if self.db is not None:
                try:
                    assignments = {
                        a.name: a.value for a in trial.spec.assignments}
                    self.db.report_observation(
                        experiment=trial.spec.experiment_name,
                        trial=name,
                        assignments=assignments,
                        value=objective,
                        namespace=namespace,
                    )
                    # per-step series of the objective behind the
                    # experiment-curves view (Katib's observation log) —
                    # ONE batched RPC, not one per step
                    if series:
                        self.db.report_observation_series(
                            experiment=trial.spec.experiment_name,
                            trial=name,
                            assignments=assignments,
                            series=series,
                            namespace=namespace,
                        )
                except Exception:  # noqa: BLE001 — db down: trial still valid
                    self.emit_event(
                        trial, "ObservationReportFailed",
                        "db-manager unreachable", type_="Warning")
            self.emit_event(
                trial, "TrialSucceeded",
                f"{trial.spec.objective_metric_name}={objective}")
            return None
        if has_condition(job.status.conditions, JobConditionType.FAILED):
            self._set_phase(trial, "Failed")
            self.emit_event(trial, "TrialFailed", "job failed", type_="Warning")
            return None
        if self._maybe_early_stop(namespace, name, trial, job):
            return None
        self._set_phase(trial, "Running")
        return Result(requeue_after=0.05)

    # -- ASHA early stopping (SURVEY §2.3 suggestion/early-stopping zoo) ------

    def _maybe_early_stop(
        self, namespace: str, name: str, trial: Trial, job: JaxJob
    ) -> bool:
        """Record rung crossings and stop under-performing trials.

        Returns True when the trial was early-stopped (job deleted, phase
        EarlyStopped with the last observation recorded)."""
        exp = self.store.try_get(
            KIND_EXPERIMENT, trial.spec.experiment_name, namespace)
        if (
            not isinstance(exp, Experiment)
            or exp.spec.early_stopping is None
            or exp.spec.early_stopping.algorithm_name != "asha"
        ):
            return False
        asha = Asha.from_spec(exp.spec.early_stopping)
        metrics, steps = self._scrape_with_steps(namespace, job)
        value = metrics.get(trial.spec.objective_metric_name)
        step = steps.get(trial.spec.objective_metric_name)
        if value is None or step is None:
            return False
        rung = asha.rung_for(int(step))
        if rung is None or str(rung) in trial.status.rung_values:
            return False
        rkey = str(rung)

        def mut(o):
            assert isinstance(o, Trial)
            o.status.rung_values[rkey] = value

        try:
            trial = self.store.update_with_retry(KIND_TRIAL, name, namespace, mut)
        except NotFound:
            return False
        # asynchronous decision: judge against whatever peers have recorded
        # at this rung so far (no bracket synchronization)
        peers = [
            t.status.rung_values[rkey]
            for t in self.store.list(KIND_TRIAL, namespace)
            if isinstance(t, Trial)
            and t.spec.experiment_name == trial.spec.experiment_name
            and t.metadata.name != name
            and rkey in t.status.rung_values
        ]
        if not asha.should_stop(exp.spec.objective.type, rung, value, peers):
            return False
        self.store.try_delete(KIND_JAXJOB, name, namespace)
        self._set_phase(trial, "EarlyStopped", observation=value, metrics=metrics)
        if self.db is not None:
            try:
                self.db.report_observation(
                    experiment=trial.spec.experiment_name,
                    trial=name,
                    assignments={a.name: a.value for a in trial.spec.assignments},
                    value=value,
                    namespace=namespace,
                    phase="EarlyStopped",
                )
            except Exception:  # noqa: BLE001 — db unavailable: the stop
                # decision stands, only the durable record is lost
                log.debug("early-stop observation report for %s failed",
                          name, exc_info=True)
        self.emit_event(
            trial, "TrialEarlyStopped",
            f"ASHA rung {rung} (step {step}): "
            f"{trial.spec.objective_metric_name}={value} below promotion cut")
        return True

    # -- metrics collection (SURVEY.md §5 observability) ----------------------

    def _scrape(self, namespace: str, job: JaxJob) -> dict[str, float]:
        """Last value wins per metric name, scanning every worker pod:
        structured jsonl first, stdout ``name=value`` lines as fallback."""
        return self._scrape_with_steps(namespace, job)[0]

    def _scrape_with_steps(
        self, namespace: str, job: JaxJob
    ) -> tuple[dict[str, float], dict[str, int]]:
        """(metrics, steps): steps carries each metric's latest ``step``
        extra from the jsonl stream — the resource axis ASHA rungs use."""
        metrics: dict[str, float] = {}
        steps: dict[str, int] = {}
        for rtype, rspec in job.spec.replica_specs.items():
            for idx in range(rspec.replicas):
                pod = replica_pod_name(job.metadata.name, rtype, idx)
                if self.metrics_root:
                    path = os.path.join(
                        self.metrics_root, "status", namespace, pod, "metrics.jsonl")
                    vals, stps, _ = self._read_jsonl(path)
                    metrics.update(vals)
                    steps.update(stps)
                if self.log_path_for:
                    metrics.update(
                        self._read_stdout(self.log_path_for(namespace, pod)))
        return metrics, steps

    def _scrape_with_series(
        self, namespace: str, job: JaxJob, metric_name: str
    ) -> tuple[dict[str, float], list[tuple[int, float]]]:
        """One pass over every worker's metric streams: final metric values
        plus ``metric_name``'s full (step, value) series (the per-step
        observation log; last value wins per step)."""
        metrics: dict[str, float] = {}
        series: dict[int, float] = {}
        for rtype, rspec in job.spec.replica_specs.items():
            for idx in range(rspec.replicas):
                pod = replica_pod_name(job.metadata.name, rtype, idx)
                if self.metrics_root:
                    path = os.path.join(
                        self.metrics_root, "status", namespace, pod,
                        "metrics.jsonl")
                    vals, _, s = self._read_jsonl(path, series_for=metric_name)
                    metrics.update(vals)
                    series.update(s)
                if self.log_path_for:
                    metrics.update(
                        self._read_stdout(self.log_path_for(namespace, pod)))
        return metrics, sorted(series.items())

    @staticmethod
    def _read_jsonl(
        path: str, series_for: Optional[str] = None
    ) -> tuple[dict[str, float], dict[str, int], dict[int, float]]:
        """One pass over a metrics stream: (last values, last steps, and —
        when ``series_for`` names a metric — its full per-step series)."""
        values: dict[str, float] = {}
        steps: dict[str, int] = {}
        series: dict[int, float] = {}
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                        name = str(rec["name"])
                        values[name] = float(rec["value"])
                        if "step" in rec:
                            steps[name] = int(rec["step"])
                            if name == series_for:
                                series[int(rec["step"])] = float(rec["value"])
                    except (ValueError, KeyError):
                        continue
        except OSError:
            pass
        return values, steps, series

    @staticmethod
    def _read_stdout(path: str) -> dict[str, float]:
        out: dict[str, float] = {}
        try:
            with open(path) as f:
                for line in f:
                    m = _METRIC_LINE_RE.match(line)
                    if m:
                        try:
                            out[m.group(1)] = float(m.group(2))
                        except ValueError:
                            continue
        except OSError:
            pass
        return out

    def _set_phase(self, trial: Trial, phase: str, observation=None, metrics=None) -> None:
        if trial.status.phase == phase and observation is None:
            return

        def mut(o):
            assert isinstance(o, Trial)
            o.status.phase = phase
            if observation is not None:
                o.status.observation = observation
            if metrics:
                o.status.metrics = dict(metrics)
            ctype = {
                "Running": JobConditionType.RUNNING,
                "Succeeded": JobConditionType.SUCCEEDED,
                "Failed": JobConditionType.FAILED,
            }.get(phase)
            if ctype:
                o.status.conditions = set_condition(
                    o.status.conditions, JobCondition(type=ctype, reason=phase))

        try:
            self.store.update_with_retry(
                KIND_TRIAL, trial.metadata.name, trial.metadata.namespace, mut)
        except NotFound:
            pass
