"""Suggestion algorithms: the Katib algorithm-service zoo, numpy-native.

Capability parity with the reference's suggestion services [upstream:
kubeflow/katib -> pkg/suggestion/v1beta1/{random,grid,hyperopt,skopt,...}]:
``random``, ``grid``, ``tpe`` (tree-structured Parzen estimator, the
hyperopt default), and ``bayesianoptimization`` (GP + expected improvement,
the skopt default named in baseline config 4).  The reference shells out to
hyperopt/optuna/skopt pips; none are installed here, so the estimators are
implemented directly (numpy/scipy) behind the same GetSuggestions contract.

All suggesters are pure: (search space, observation history, count) ->
assignments.  State lives in the Experiment's trial history, so the service
can restart at any time — same property Katib gets by re-sending full
history on every GetSuggestions call.
"""

from __future__ import annotations

import math
import random as pyrandom
import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..api.experiment import (
    FeasibleSpace,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)


@dataclass
class Observation:
    """One completed trial: assignments + objective value."""

    assignments: dict[str, object]
    value: float
    #: trial name (issue-ordered); population-based algorithms use it to
    #: name checkpoint-fork parents
    trial: Optional[str] = None


@dataclass
class SuggestRequest:
    parameters: list[ParameterSpec]
    objective_type: ObjectiveType
    history: list[Observation] = field(default_factory=list)
    count: int = 1
    settings: dict[str, str] = field(default_factory=dict)
    seed: Optional[int] = None
    #: how many assignments have ALREADY been issued for this experiment
    #: (not just completed) — the dedup cursor for enumerative algorithms;
    #: parallel trials mean issued > len(history)
    issued: int = 0


class Suggester:
    name = "base"

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        raise NotImplementedError


# -- parameter-space encoding ------------------------------------------------


def _sample_one(p: ParameterSpec, rng: pyrandom.Random) -> object:
    fs = p.feasible_space
    if p.parameter_type == ParameterType.DOUBLE:
        if fs.log_scale:
            lo, hi = math.log(fs.min), math.log(fs.max)
            return math.exp(rng.uniform(lo, hi))
        return rng.uniform(fs.min, fs.max)
    if p.parameter_type == ParameterType.INT:
        return rng.randint(int(fs.min), int(fs.max))
    return rng.choice(list(fs.list_))


def _to_unit(p: ParameterSpec, v: object) -> float:
    """Map a parameter value into [0,1] for continuous surrogate models."""
    fs = p.feasible_space
    if p.parameter_type == ParameterType.DOUBLE:
        if fs.log_scale:
            return (math.log(float(v)) - math.log(fs.min)) / (
                math.log(fs.max) - math.log(fs.min) or 1.0)
        return (float(v) - fs.min) / ((fs.max - fs.min) or 1.0)
    if p.parameter_type == ParameterType.INT:
        return (float(v) - fs.min) / ((fs.max - fs.min) or 1.0)
    values = list(fs.list_)
    return values.index(v) / max(len(values) - 1, 1)


def _from_unit(p: ParameterSpec, u: float) -> object:
    fs = p.feasible_space
    u = min(max(u, 0.0), 1.0)
    if p.parameter_type == ParameterType.DOUBLE:
        if fs.log_scale:
            return math.exp(
                math.log(fs.min) + u * (math.log(fs.max) - math.log(fs.min)))
        return fs.min + u * (fs.max - fs.min)
    if p.parameter_type == ParameterType.INT:
        return int(round(fs.min + u * (fs.max - fs.min)))
    values = list(fs.list_)
    return values[min(int(u * len(values)), len(values) - 1)]


# -- algorithms ---------------------------------------------------------------


class RandomSearch(Suggester):
    name = "random"

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        # explicit seed -> reproducible; otherwise OS entropy, so repeated
        # calls at the same history length don't replay identical points
        # (e.g. re-suggesting after a failed trial)
        rng = pyrandom.Random(req.seed)
        return [
            {p.name: _sample_one(p, rng) for p in req.parameters}
            for _ in range(req.count)
        ]


class GridSearch(Suggester):
    """Cartesian grid; continuous params discretized by step (or a default
    resolution), same contract as Katib's grid suggester."""

    name = "grid"
    #: points per continuous axis when the spec gives no ``step`` — kept
    #: deliberately coarse because grid cost is resolution^d (Katib's grid
    #: suggester simply REQUIRES step for doubles; defaulting is kinder).
    #: Override per experiment with settings["resolution"].
    DEFAULT_RESOLUTION = 4

    def _axis(self, p: ParameterSpec, resolution: int) -> list[object]:
        fs = p.feasible_space
        if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            return list(fs.list_)
        if p.parameter_type == ParameterType.INT:
            step = int(fs.step or 1)
            return list(range(int(fs.min), int(fs.max) + 1, step))
        n = int((fs.max - fs.min) / fs.step) + 1 if fs.step else resolution
        return [fs.min + i * (fs.max - fs.min) / max(n - 1, 1) for i in range(n)]

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        resolution = int(req.settings.get(
            "resolution", self.DEFAULT_RESOLUTION))
        axes = [(p.name, self._axis(p, resolution)) for p in req.parameters]
        total = math.prod(len(v) for _, v in axes)
        # cursor = assignments already issued (running trials included), NOT
        # completed history — else parallel trials revisit cells
        start = max(req.issued, len(req.history))
        out = []
        for flat in range(start, min(start + req.count, total)):
            point, rem = {}, flat
            for name, values in axes:
                point[name] = values[rem % len(values)]
                rem //= len(values)
            out.append(point)
        return out


class Tpe(Suggester):
    """Tree-structured Parzen estimator (hyperopt's default algorithm).

    Split history at the gamma-quantile into good/bad sets, model each with
    a Parzen window (per-dimension Gaussian KDE in unit space), and pick the
    candidate maximizing the density ratio l(x)/g(x).
    """

    name = "tpe"
    N_STARTUP = 5
    N_CANDIDATES = 32
    GAMMA = 0.25
    #: Parzen-window bandwidth FLOOR in unit space.  The working bandwidth
    #: is per-dimension Scott's-rule (std(centers_d) * n^(-1/(d+4))),
    #: floored here so early history (few points, zero spread on a dim)
    #: still explores; override with settings["bandwidth"].
    BANDWIDTH = 0.15

    def _bandwidths(self, centers: np.ndarray, floor: float) -> np.ndarray:
        """Scott's-rule per-dimension bandwidths — adapts to history
        spread and dimensionality instead of one magic constant (r2
        advisor: fixed 0.15 degrades past ~4 dims)."""
        n, d = centers.shape
        scott = centers.std(axis=0) * n ** (-1.0 / (d + 4))
        return np.clip(scott, floor, 0.5)

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        if len(req.history) < self.N_STARTUP:
            return RandomSearch().suggest(req)
        rng = pyrandom.Random(req.seed)
        nprng = np.random.default_rng(rng.randrange(2**31))
        sign = -1.0 if req.objective_type == ObjectiveType.MAXIMIZE else 1.0
        pts = np.array(
            [[_to_unit(p, ob.assignments[p.name]) for p in req.parameters]
             for ob in req.history])
        vals = sign * np.array([ob.value for ob in req.history])
        n_good = max(1, int(self.GAMMA * len(vals)))
        order = np.argsort(vals)
        good, bad = pts[order[:n_good]], pts[order[n_good:]]
        floor = float(req.settings.get("bandwidth", self.BANDWIDTH))
        bw_good = self._bandwidths(good, floor)   # [d]
        bw_all = self._bandwidths(pts, floor)

        def density(x: np.ndarray, centers: np.ndarray) -> np.ndarray:
            # product over dims of mean-of-gaussians (Parzen window)
            d2 = (x[:, None, :] - centers[None, :, :]) ** 2
            kern = np.exp(-0.5 * d2 / bw_all**2)
            return np.log(kern.mean(axis=1) + 1e-12).sum(axis=-1)

        out = []
        bad_aug = bad  # grows with each pick so a batch doesn't collapse
        for _ in range(req.count):
            # candidates drawn around the good set
            idx = nprng.integers(0, len(good), self.N_CANDIDATES)
            cand = good[idx] + nprng.normal(
                0, 1.0, (self.N_CANDIDATES, pts.shape[1])) * bw_good
            cand = np.clip(cand, 0.0, 1.0)
            score = density(cand, good) - density(cand, bad_aug)
            best = cand[int(np.argmax(score))]
            # treat the chosen point as "bad" for the rest of the batch:
            # the l/g ratio then penalizes re-picking its neighborhood, so
            # count>1 returns diverse assignments (Katib's TPE batches via
            # hyperopt get this from sequential model updates)
            bad_aug = np.concatenate([bad_aug, best[None, :]], axis=0)
            out.append({
                p.name: _from_unit(p, float(best[i]))
                for i, p in enumerate(req.parameters)
            })
        return out


class BayesianOptimization(Suggester):
    """GP surrogate + expected improvement (the skopt-backed Katib algorithm
    named in baseline config 4), with an RBF kernel in unit space."""

    name = "bayesianoptimization"
    N_STARTUP = 4
    N_CANDIDATES = 256
    #: RBF length-scale FLOOR; the working scale is the median pairwise
    #: distance of the history in unit space (the standard median
    #: heuristic), so it adapts to dimensionality — median distance grows
    #: ~sqrt(d) and a fixed 0.2 would make every point look far in high d.
    #: Override with settings["length_scale"].
    LENGTH_SCALE = 0.2
    NOISE = 1e-6

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        if len(req.history) < self.N_STARTUP:
            return RandomSearch().suggest(req)
        from scipy.stats import norm

        rng = np.random.default_rng(req.seed)
        sign = -1.0 if req.objective_type == ObjectiveType.MAXIMIZE else 1.0
        x = np.array(
            [[_to_unit(p, ob.assignments[p.name]) for p in req.parameters]
             for ob in req.history])
        y = sign * np.array([ob.value for ob in req.history])
        y_mean, y_std = y.mean(), y.std() or 1.0
        yn = (y - y_mean) / y_std

        if "length_scale" in req.settings:
            scale = float(req.settings["length_scale"])
        else:
            diff2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
            pair = np.sqrt(diff2[np.triu_indices(len(x), k=1)])
            med = float(np.median(pair)) if len(pair) else 0.0
            scale = max(med, self.LENGTH_SCALE)

        def kernel(a: np.ndarray, b: np.ndarray) -> np.ndarray:
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / scale**2)

        k_xx = kernel(x, x) + self.NOISE * np.eye(len(x))
        l_chol = np.linalg.cholesky(k_xx)
        alpha = np.linalg.solve(l_chol.T, np.linalg.solve(l_chol, yn))

        out = []
        for _ in range(req.count):
            cand = rng.uniform(0, 1, (self.N_CANDIDATES, x.shape[1]))
            k_s = kernel(cand, x)
            mu = k_s @ alpha
            v = np.linalg.solve(l_chol, k_s.T)
            var = np.clip(1.0 - (v**2).sum(axis=0), 1e-12, None)
            sd = np.sqrt(var)
            best_y = yn.min()
            # expected improvement (minimization in normalized space)
            z = (best_y - mu) / sd
            ei = (best_y - mu) * norm.cdf(z) + sd * norm.pdf(z)
            best = cand[int(np.argmax(ei))]
            out.append({
                p.name: _from_unit(p, float(best[i]))
                for i, p in enumerate(req.parameters)
            })
            # avoid duplicate suggestions within one batch
            x = np.vstack([x, best[None, :]])
            yn = np.append(yn, mu[int(np.argmax(ei))])
            k_xx = kernel(x, x) + self.NOISE * np.eye(len(x))
            l_chol = np.linalg.cholesky(k_xx)
            alpha = np.linalg.solve(l_chol.T, np.linalg.solve(l_chol, yn))
        return out


class CmaEs(Suggester):
    """(mu/mu_w, lambda)-CMA-ES [Hansen's standard strategy; reference
    analog: Katib's goptuna/cmaes suggestion service].

    Stateless like every suggester here: the evolution state (mean, step
    size, covariance, evolution paths) is reconstructed by replaying the
    observation history in generation-sized chunks, so the gRPC service can
    restart mid-experiment and continue the same trajectory — the property
    Katib gets by re-sending full history per GetSuggestions call.  The
    controller feeds history in issue order including early-stopped trials'
    observations; a trial that fails with NO observation shifts generation
    boundaries, degrading adaptation gracefully (chunks still track the
    recent selection mean) rather than crashing.

    settings: population_size (default 4+floor(3 ln d)), sigma (initial
    step size in unit space, default 0.3).
    """

    name = "cmaes"

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        params = req.parameters
        d = len(params)
        lam = int(req.settings.get(
            "population_size", 4 + int(3 * math.log(max(d, 1) + 1e-12))))
        lam = max(lam, 2)
        sigma0 = float(req.settings.get("sigma", 0.3))
        seed = req.seed if req.seed is not None else 0

        mu = lam // 2
        w = np.log(lam / 2 + 0.5) - np.log(np.arange(1, mu + 1))
        w = w / w.sum()
        mu_eff = 1.0 / float(np.square(w).sum())
        c_sigma = (mu_eff + 2) / (d + mu_eff + 5)
        d_sigma = 1 + 2 * max(0.0, math.sqrt((mu_eff - 1) / (d + 1)) - 1) + c_sigma
        c_c = (4 + mu_eff / d) / (d + 4 + 2 * mu_eff / d)
        c_1 = 2 / ((d + 1.3) ** 2 + mu_eff)
        c_mu = min(1 - c_1, 2 * (mu_eff - 2 + 1 / mu_eff) / ((d + 2) ** 2 + mu_eff))
        chi_n = math.sqrt(d) * (1 - 1 / (4 * d) + 1 / (21 * d * d))

        mean = np.full(d, 0.5)
        sigma = sigma0
        cov = np.eye(d)
        p_sigma = np.zeros(d)
        p_c = np.zeros(d)

        # internal objective is MINIMIZED
        flip = -1.0 if req.objective_type == ObjectiveType.MAXIMIZE else 1.0
        hist = req.history
        n_gens = len(hist) // lam
        for g in range(n_gens):
            gen = hist[g * lam : (g + 1) * lam]
            xs = np.array([
                [_to_unit(p, o.assignments[p.name]) for p in params]
                for o in gen
            ])
            fs = np.array([flip * o.value for o in gen])
            order = np.argsort(fs)  # best first
            x_sel = xs[order[:mu]]
            old_mean = mean
            mean = w @ x_sel
            # evolution paths in the whitened frame
            c_inv_sqrt = _inv_sqrt(cov)
            y = (mean - old_mean) / max(sigma, 1e-12)
            p_sigma = (1 - c_sigma) * p_sigma + math.sqrt(
                c_sigma * (2 - c_sigma) * mu_eff) * (c_inv_sqrt @ y)
            h_sigma = float(
                np.linalg.norm(p_sigma)
                / math.sqrt(1 - (1 - c_sigma) ** (2 * (g + 1)))
                < (1.4 + 2 / (d + 1)) * chi_n
            )
            p_c = (1 - c_c) * p_c + h_sigma * math.sqrt(
                c_c * (2 - c_c) * mu_eff) * y
            arts = (x_sel - old_mean) / max(sigma, 1e-12)
            rank_mu = (w[:, None] * arts).T @ arts
            cov = (
                (1 - c_1 - c_mu) * cov
                + c_1 * (np.outer(p_c, p_c)
                         + (1 - h_sigma) * c_c * (2 - c_c) * cov)
                + c_mu * rank_mu
            )
            sigma = sigma * math.exp(
                (c_sigma / d_sigma) * (np.linalg.norm(p_sigma) / chi_n - 1))
            sigma = float(min(max(sigma, 1e-8), 1.0))

        # sample the current generation's candidates deterministically;
        # the cursor past complete generations indexes into this stream so
        # parallel suggest() calls hand out distinct members.  Like grid's
        # cursor, it defends with len(history): a driver that never sets
        # `issued` must still advance, not replay one point all generation.
        rng = np.random.default_rng(seed + 7919 * n_gens)
        issued_in_gen = max(max(req.issued, len(hist)) - n_gens * lam, 0)
        n_draw = issued_in_gen + req.count
        try:
            chol = np.linalg.cholesky(
                cov + 1e-12 * np.eye(d))
        except np.linalg.LinAlgError:
            chol = np.eye(d)
        z = rng.standard_normal((n_draw, d))
        points = mean[None, :] + sigma * (z @ chol.T)
        out = []
        for row in points[issued_in_gen:]:
            out.append({
                p.name: _from_unit(p, float(u)) for p, u in zip(params, row)})
        return out


def _inv_sqrt(mat: np.ndarray) -> np.ndarray:
    vals, vecs = np.linalg.eigh(mat)
    vals = np.maximum(vals, 1e-12)
    return vecs @ np.diag(vals ** -0.5) @ vecs.T


#: reserved assignment key PBT emits: name of the trial whose checkpoint
#: the new trial forks from ("" = fresh start).  Trial templates map it to
#: the runtime's resume env (e.g. KFT_RESUME_FROM).
PBT_PARENT_KEY = "__parent"


class Pbt(Suggester):
    """Population Based Training [Jade+ 2017; reference analog: Katib's PBT
    suggestion service, pkg/suggestion/v1beta1/pbt].

    Trials form generations of ``population_size``.  Each member of
    generation g+1 continues SOME generation-g member's training from its
    checkpoint: survivors (top 1-truncation by objective) continue
    themselves with unchanged hyperparameters; the bottom ``truncation``
    fraction is replaced by exploit+explore — fork a random top member's
    checkpoint and perturb its hyperparameters (continuous: x1.2 / /1.2;
    categorical: resampled with ``resample_prob``).

    The fork edge travels as the reserved ``__parent`` assignment
    (PBT_PARENT_KEY): the trial template maps it into the trainer's
    resume-from env, and the trainer copies the parent's checkpoint before
    training (train/llm.py KFT_PBT_ROOT contract).  Stateless like every
    suggester: generations are reconstructed from issue-ordered history.

    settings: population_size (default 4), truncation (default 0.25),
    perturb_factor (default 1.2), resample_prob (default 0.25).
    """

    name = "pbt"

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        pop = max(2, int(req.settings.get("population_size", "4")))
        truncation = float(req.settings.get("truncation", "0.25"))
        factor = float(req.settings.get("perturb_factor", "1.2"))
        resample_prob = float(req.settings.get("resample_prob", "0.25"))
        seed = req.seed if req.seed is not None else 0
        cursor = max(req.issued, len(req.history))

        # slot-index observations by the trial name's issue index, so a
        # Failed trial (absent from history) is just a hole in its
        # generation rather than a permanent misalignment of every chunk
        by_index: dict[int, Observation] = {}
        for pos, ob in enumerate(req.history):
            m = re.search(r"(\d+)$", ob.trial or "")
            by_index[int(m.group(1)) if m else pos] = ob

        out: list[dict[str, object]] = []
        sign = -1.0 if req.objective_type == ObjectiveType.MINIMIZE else 1.0
        for i in range(req.count):
            slot_index = cursor + i
            gen, slot = divmod(slot_index, pop)
            rng = pyrandom.Random(seed * 1_000_003 + slot_index)
            prev = {
                j: by_index.get((gen - 1) * pop + j) for j in range(pop)
            } if gen > 0 else {}
            present = [j for j, ob in prev.items() if ob is not None]
            if gen == 0 or len(present) < 2:
                # first generation, or too few survivors to rank: fresh
                a = {p.name: _sample_one(p, rng) for p in req.parameters}
                a[PBT_PARENT_KEY] = ""
                out.append(a)
                continue
            ranked = sorted(
                present, key=lambda j: sign * prev[j].value, reverse=True)
            n_cut = max(1, int(round(len(present) * truncation)))
            rank_of = {j: r for r, j in enumerate(ranked)}
            member = prev.get(slot)
            if member is None or rank_of[slot] >= len(present) - n_cut:
                # exploit (slot's lineage failed, or ranked in the bottom
                # truncation): fork a random top member + explore
                donor = prev[rng.choice(ranked[:n_cut])]
                a = self._explore(
                    donor.assignments, req.parameters, rng, factor,
                    resample_prob)
                a[PBT_PARENT_KEY] = donor.trial or ""
            else:
                # survivor: continue own lineage unchanged
                a = {
                    p.name: member.assignments[p.name] for p in req.parameters
                }
                a[PBT_PARENT_KEY] = member.trial or ""
            out.append(a)
        return out

    def _explore(
        self,
        assignments: dict[str, object],
        parameters: list[ParameterSpec],
        rng: pyrandom.Random,
        factor: float,
        resample_prob: float,
    ) -> dict[str, object]:
        out: dict[str, object] = {}
        for p in parameters:
            v = assignments[p.name]
            fs = p.feasible_space
            if p.parameter_type == ParameterType.DOUBLE:
                f = factor if rng.random() < 0.5 else 1.0 / factor
                out[p.name] = min(max(float(v) * f, fs.min), fs.max)
            elif p.parameter_type == ParameterType.INT:
                f = factor if rng.random() < 0.5 else 1.0 / factor
                out[p.name] = int(min(max(round(int(v) * f), fs.min), fs.max))
            else:
                out[p.name] = (
                    rng.choice(list(fs.list_))
                    if rng.random() < resample_prob else v
                )
        return out


REGISTRY: dict[str, type] = {
    cls.name: cls
    for cls in (RandomSearch, GridSearch, Tpe, BayesianOptimization, CmaEs, Pbt)
}


def get_suggester(name: str) -> Suggester:
    if name == "darts":  # one-shot NAS lives in nas.py (heavy jax deps)
        from .nas import OneShotNas

        return OneShotNas()
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; available: "
            f"{sorted(REGISTRY) + ['darts']}"
        ) from None
