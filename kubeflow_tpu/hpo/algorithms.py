"""Suggestion algorithms: the Katib algorithm-service zoo, numpy-native.

Capability parity with the reference's suggestion services [upstream:
kubeflow/katib -> pkg/suggestion/v1beta1/{random,grid,hyperopt,skopt,...}]:
``random``, ``grid``, ``tpe`` (tree-structured Parzen estimator, the
hyperopt default), and ``bayesianoptimization`` (GP + expected improvement,
the skopt default named in baseline config 4).  The reference shells out to
hyperopt/optuna/skopt pips; none are installed here, so the estimators are
implemented directly (numpy/scipy) behind the same GetSuggestions contract.

All suggesters are pure: (search space, observation history, count) ->
assignments.  State lives in the Experiment's trial history, so the service
can restart at any time — same property Katib gets by re-sending full
history on every GetSuggestions call.
"""

from __future__ import annotations

import math
import random as pyrandom
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..api.experiment import (
    FeasibleSpace,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)


@dataclass
class Observation:
    """One completed trial: assignments + objective value."""

    assignments: dict[str, object]
    value: float


@dataclass
class SuggestRequest:
    parameters: list[ParameterSpec]
    objective_type: ObjectiveType
    history: list[Observation] = field(default_factory=list)
    count: int = 1
    settings: dict[str, str] = field(default_factory=dict)
    seed: Optional[int] = None
    #: how many assignments have ALREADY been issued for this experiment
    #: (not just completed) — the dedup cursor for enumerative algorithms;
    #: parallel trials mean issued > len(history)
    issued: int = 0


class Suggester:
    name = "base"

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        raise NotImplementedError


# -- parameter-space encoding ------------------------------------------------


def _sample_one(p: ParameterSpec, rng: pyrandom.Random) -> object:
    fs = p.feasible_space
    if p.parameter_type == ParameterType.DOUBLE:
        if fs.log_scale:
            lo, hi = math.log(fs.min), math.log(fs.max)
            return math.exp(rng.uniform(lo, hi))
        return rng.uniform(fs.min, fs.max)
    if p.parameter_type == ParameterType.INT:
        return rng.randint(int(fs.min), int(fs.max))
    return rng.choice(list(fs.list_))


def _to_unit(p: ParameterSpec, v: object) -> float:
    """Map a parameter value into [0,1] for continuous surrogate models."""
    fs = p.feasible_space
    if p.parameter_type == ParameterType.DOUBLE:
        if fs.log_scale:
            return (math.log(float(v)) - math.log(fs.min)) / (
                math.log(fs.max) - math.log(fs.min) or 1.0)
        return (float(v) - fs.min) / ((fs.max - fs.min) or 1.0)
    if p.parameter_type == ParameterType.INT:
        return (float(v) - fs.min) / ((fs.max - fs.min) or 1.0)
    values = list(fs.list_)
    return values.index(v) / max(len(values) - 1, 1)


def _from_unit(p: ParameterSpec, u: float) -> object:
    fs = p.feasible_space
    u = min(max(u, 0.0), 1.0)
    if p.parameter_type == ParameterType.DOUBLE:
        if fs.log_scale:
            return math.exp(
                math.log(fs.min) + u * (math.log(fs.max) - math.log(fs.min)))
        return fs.min + u * (fs.max - fs.min)
    if p.parameter_type == ParameterType.INT:
        return int(round(fs.min + u * (fs.max - fs.min)))
    values = list(fs.list_)
    return values[min(int(u * len(values)), len(values) - 1)]


# -- algorithms ---------------------------------------------------------------


class RandomSearch(Suggester):
    name = "random"

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        # explicit seed -> reproducible; otherwise OS entropy, so repeated
        # calls at the same history length don't replay identical points
        # (e.g. re-suggesting after a failed trial)
        rng = pyrandom.Random(req.seed)
        return [
            {p.name: _sample_one(p, rng) for p in req.parameters}
            for _ in range(req.count)
        ]


class GridSearch(Suggester):
    """Cartesian grid; continuous params discretized by step (or a default
    resolution), same contract as Katib's grid suggester."""

    name = "grid"
    DEFAULT_RESOLUTION = 4

    def _axis(self, p: ParameterSpec) -> list[object]:
        fs = p.feasible_space
        if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            return list(fs.list_)
        if p.parameter_type == ParameterType.INT:
            step = int(fs.step or 1)
            return list(range(int(fs.min), int(fs.max) + 1, step))
        n = int((fs.max - fs.min) / fs.step) + 1 if fs.step else self.DEFAULT_RESOLUTION
        return [fs.min + i * (fs.max - fs.min) / max(n - 1, 1) for i in range(n)]

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        axes = [(p.name, self._axis(p)) for p in req.parameters]
        total = math.prod(len(v) for _, v in axes)
        # cursor = assignments already issued (running trials included), NOT
        # completed history — else parallel trials revisit cells
        start = max(req.issued, len(req.history))
        out = []
        for flat in range(start, min(start + req.count, total)):
            point, rem = {}, flat
            for name, values in axes:
                point[name] = values[rem % len(values)]
                rem //= len(values)
            out.append(point)
        return out


class Tpe(Suggester):
    """Tree-structured Parzen estimator (hyperopt's default algorithm).

    Split history at the gamma-quantile into good/bad sets, model each with
    a Parzen window (per-dimension Gaussian KDE in unit space), and pick the
    candidate maximizing the density ratio l(x)/g(x).
    """

    name = "tpe"
    N_STARTUP = 5
    N_CANDIDATES = 32
    GAMMA = 0.25
    BANDWIDTH = 0.15

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        if len(req.history) < self.N_STARTUP:
            return RandomSearch().suggest(req)
        rng = pyrandom.Random(req.seed)
        nprng = np.random.default_rng(rng.randrange(2**31))
        sign = -1.0 if req.objective_type == ObjectiveType.MAXIMIZE else 1.0
        pts = np.array(
            [[_to_unit(p, ob.assignments[p.name]) for p in req.parameters]
             for ob in req.history])
        vals = sign * np.array([ob.value for ob in req.history])
        n_good = max(1, int(self.GAMMA * len(vals)))
        order = np.argsort(vals)
        good, bad = pts[order[:n_good]], pts[order[n_good:]]

        def density(x: np.ndarray, centers: np.ndarray) -> np.ndarray:
            # product over dims of mean-of-gaussians (Parzen window)
            d2 = (x[:, None, :] - centers[None, :, :]) ** 2
            kern = np.exp(-0.5 * d2 / self.BANDWIDTH**2)
            return np.log(kern.mean(axis=1) + 1e-12).sum(axis=-1)

        out = []
        bad_aug = bad  # grows with each pick so a batch doesn't collapse
        for _ in range(req.count):
            # candidates drawn around the good set
            idx = nprng.integers(0, len(good), self.N_CANDIDATES)
            cand = good[idx] + nprng.normal(0, self.BANDWIDTH, (self.N_CANDIDATES, pts.shape[1]))
            cand = np.clip(cand, 0.0, 1.0)
            score = density(cand, good) - density(cand, bad_aug)
            best = cand[int(np.argmax(score))]
            # treat the chosen point as "bad" for the rest of the batch:
            # the l/g ratio then penalizes re-picking its neighborhood, so
            # count>1 returns diverse assignments (Katib's TPE batches via
            # hyperopt get this from sequential model updates)
            bad_aug = np.concatenate([bad_aug, best[None, :]], axis=0)
            out.append({
                p.name: _from_unit(p, float(best[i]))
                for i, p in enumerate(req.parameters)
            })
        return out


class BayesianOptimization(Suggester):
    """GP surrogate + expected improvement (the skopt-backed Katib algorithm
    named in baseline config 4), with an RBF kernel in unit space."""

    name = "bayesianoptimization"
    N_STARTUP = 4
    N_CANDIDATES = 256
    LENGTH_SCALE = 0.2
    NOISE = 1e-6

    def suggest(self, req: SuggestRequest) -> list[dict[str, object]]:
        if len(req.history) < self.N_STARTUP:
            return RandomSearch().suggest(req)
        from scipy.stats import norm

        rng = np.random.default_rng(req.seed)
        sign = -1.0 if req.objective_type == ObjectiveType.MAXIMIZE else 1.0
        x = np.array(
            [[_to_unit(p, ob.assignments[p.name]) for p in req.parameters]
             for ob in req.history])
        y = sign * np.array([ob.value for ob in req.history])
        y_mean, y_std = y.mean(), y.std() or 1.0
        yn = (y - y_mean) / y_std

        def kernel(a: np.ndarray, b: np.ndarray) -> np.ndarray:
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / self.LENGTH_SCALE**2)

        k_xx = kernel(x, x) + self.NOISE * np.eye(len(x))
        l_chol = np.linalg.cholesky(k_xx)
        alpha = np.linalg.solve(l_chol.T, np.linalg.solve(l_chol, yn))

        out = []
        for _ in range(req.count):
            cand = rng.uniform(0, 1, (self.N_CANDIDATES, x.shape[1]))
            k_s = kernel(cand, x)
            mu = k_s @ alpha
            v = np.linalg.solve(l_chol, k_s.T)
            var = np.clip(1.0 - (v**2).sum(axis=0), 1e-12, None)
            sd = np.sqrt(var)
            best_y = yn.min()
            # expected improvement (minimization in normalized space)
            z = (best_y - mu) / sd
            ei = (best_y - mu) * norm.cdf(z) + sd * norm.pdf(z)
            best = cand[int(np.argmax(ei))]
            out.append({
                p.name: _from_unit(p, float(best[i]))
                for i, p in enumerate(req.parameters)
            })
            # avoid duplicate suggestions within one batch
            x = np.vstack([x, best[None, :]])
            yn = np.append(yn, mu[int(np.argmax(ei))])
            k_xx = kernel(x, x) + self.NOISE * np.eye(len(x))
            l_chol = np.linalg.cholesky(k_xx)
            alpha = np.linalg.solve(l_chol.T, np.linalg.solve(l_chol, yn))
        return out


REGISTRY: dict[str, type[Suggester]] = {
    cls.name: cls
    for cls in (RandomSearch, GridSearch, Tpe, BayesianOptimization)
}


def get_suggester(name: str) -> Suggester:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; available: {sorted(REGISTRY)}"
        ) from None
