"""Suggestion service: the algorithm behind a real gRPC boundary.

Katib runs each algorithm as a per-experiment gRPC Deployment the suggestion
controller calls ``GetSuggestions`` on [upstream: kubeflow/katib ->
pkg/apis/manager/v1beta1/api.proto, pkg/suggestion/v1beta1/].  Same shape
here: a gRPC server per experiment, spoken to over localhost.  protoc stubs
aren't available in this image (no grpcio-tools), so the service uses
grpc's generic handler with JSON payloads — still a real network RPC with
the same request/response content as Katib's proto.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from ..api.experiment import ObjectiveType, ParameterSpec
from ..utils.grpcjson import bind_insecure
from ..utils.grpcjson import deserialize as _deserialize
from ..utils.grpcjson import serialize as _serialize
from ..utils.net import allocate_port
from . import algorithms

SERVICE = "kubeflow_tpu.hpo.Suggestion"
METHOD = f"/{SERVICE}/GetSuggestions"


class _Handler(grpc.GenericRpcHandler):
    def __init__(self) -> None:
        self._methods = {
            METHOD: grpc.unary_unary_rpc_method_handler(
                self._get_suggestions,
                request_deserializer=_deserialize,
                response_serializer=_serialize,
            )
        }

    def service(self, handler_call_details):
        return self._methods.get(handler_call_details.method)

    def _get_suggestions(self, request: dict, context) -> dict:
        try:
            req = algorithms.SuggestRequest(
                parameters=[ParameterSpec(**p) for p in request["parameters"]],
                objective_type=ObjectiveType(request["objective_type"]),
                history=[
                    algorithms.Observation(**ob) for ob in request.get("history", [])
                ],
                count=int(request.get("count", 1)),
                settings=request.get("settings", {}),
                seed=request.get("seed"),
                issued=int(request.get("issued", 0)),
            )
            suggester = algorithms.get_suggester(request["algorithm"])
            return {"assignments": suggester.suggest(req)}
        except Exception as e:  # noqa: BLE001 — surface as RPC error
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"{type(e).__name__}: {e}")


class SuggestionServer:
    """One algorithm service instance (the Katib suggestion Deployment analog)."""

    def __init__(self, port: Optional[int] = None, max_workers: int = 2):
        self.port = port or allocate_port()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((_Handler(),))
        bind_insecure(self._server, "127.0.0.1", self.port)

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "SuggestionServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1.0)


class SuggestionClient:
    def __init__(self, address: str):
        self._channel = grpc.insecure_channel(address)
        self._call = self._channel.unary_unary(
            METHOD, request_serializer=_serialize, response_deserializer=_deserialize)

    def get_suggestions(
        self,
        algorithm: str,
        parameters: list[ParameterSpec],
        objective_type: ObjectiveType,
        history: list[algorithms.Observation],
        count: int,
        settings: Optional[dict[str, str]] = None,
        issued: int = 0,
        timeout: float = 30.0,
    ) -> list[dict[str, object]]:
        resp = self._call(
            {
                "algorithm": algorithm,
                "parameters": [p.model_dump(mode="json") for p in parameters],
                "objective_type": objective_type.value,
                "history": [
                    {"assignments": ob.assignments, "value": ob.value,
                     "trial": ob.trial}
                    for ob in history
                ],
                "count": count,
                "settings": settings or {},
                "issued": issued,
            },
            timeout=timeout,
        )
        return resp["assignments"]

    def close(self) -> None:
        self._channel.close()
