"""LocalPlatform: a whole "cluster" in one process tree.

Cluster (store + admission + gang scheduler + reconcilers) + LocalKubelet
(real OS processes) — the fully-wired stack the SDK talks to, standing in
for {k8s apiserver + Volcano + training-operator + kubelet} (SURVEY.md §4c).
Every JaxJob submitted here runs real multi-process
``jax.distributed.initialize`` rendezvous on the CPU backend: the same XLA
code path a real multi-host TPU slice exercises.
"""

from __future__ import annotations

import tempfile
from typing import Optional

from ..controlplane.cluster import Cluster
from .launcher import LocalKubelet


class LocalPlatform:
    def __init__(
        self,
        num_hosts: int = 1,
        chips_per_host: int = 4,
        num_slices: int = 1,
        root_dir: Optional[str] = None,
        env_overrides: Optional[dict[str, str]] = None,
    ) -> None:
        self.cluster = Cluster()
        for s in range(num_slices):
            self.cluster.add_tpu_slice(f"slice-{s}", num_hosts, chips_per_host)
        self.root_dir = root_dir or tempfile.mkdtemp(prefix="kft-")
        self.kubelet = LocalKubelet(
            self.cluster.store, self.root_dir, env_overrides=env_overrides
        )
        self.cluster.enable_hpo(
            metrics_root=self.root_dir, log_path_for=self.kubelet.pod_log_path
        )
        self.cluster.enable_serving()

    @property
    def store(self):
        return self.cluster.store

    def start(self) -> "LocalPlatform":
        self.cluster.start()
        self.kubelet.start()
        return self

    def stop(self) -> None:
        self.kubelet.stop()
        self.cluster.stop()

    def __enter__(self) -> "LocalPlatform":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
