"""In-pod runtime bootstrap: env contract -> jax.distributed -> mesh.

The L3 runtime-glue layer (SURVEY.md §1) done TPU-natively: where the
reference injects ``MASTER_ADDR``/``RANK``/``WORLD_SIZE`` for
``torch.distributed.init_process_group("nccl")`` or ``TF_CONFIG`` for TF
[upstream: kubeflow/training-operator -> pkg/controller.v1/pytorch/envvar.go,
tensorflow/], this module consumes the ``jax.distributed.initialize`` triple
the JaxJob controller injects and stands up the global device mesh.  After
``initialize`` returns, XLA owns every collective over ICI/DCN — there is no
NCCL, hostfile, or ssh equivalent to manage (SURVEY.md §2.6).

Also home of the gang-startup probe: ``barrier()`` runs the first global
collective and stamps a status file the kubelet folds into
``Pod.status.barrier_time`` -> ``JaxJob.status.gang_startup_seconds``.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Optional

#: Env contract — must match kubeflow_tpu.controlplane.jaxjob_controller.
ENV_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"
ENV_JOB_NAME = "KFT_JOB_NAME"
ENV_JOB_NAMESPACE = "KFT_JOB_NAMESPACE"
ENV_REPLICA_TYPE = "KFT_REPLICA_TYPE"
ENV_REPLICA_INDEX = "KFT_REPLICA_INDEX"
ENV_MESH = "KFT_MESH"
ENV_STATUS_DIR = "KFT_STATUS_DIR"
ENV_ENTRYPOINT = "KFT_ENTRYPOINT"
#: persistent XLA compilation cache dir (per-node or per-job volume).
#: Warm gang restarts: a restarted gang pays import + CACHED compile
#: instead of a full recompile — on a real slice a 7B train-step compile
#: is minutes, and every gang restart repays it without this.
ENV_COMPILE_CACHE = "KFT_COMPILE_CACHE"

BARRIER_FILE = "barrier"
METRICS_FILE = "metrics.jsonl"


@dataclass
class PodContext:
    """Everything a training process knows about itself, parsed from env."""

    job_name: str = "local"
    namespace: str = "default"
    replica_type: str = "worker"
    replica_index: int = 0
    process_id: int = 0
    num_processes: int = 1
    coordinator_address: Optional[str] = None
    mesh_axes: dict[str, int] = field(default_factory=dict)
    status_dir: Optional[str] = None

    @classmethod
    def from_env(cls, env: Optional[dict[str, str]] = None) -> "PodContext":
        e = dict(os.environ if env is None else env)
        mesh = {}
        if e.get(ENV_MESH):
            mesh = {k: int(v) for k, v in json.loads(e[ENV_MESH]).items()}
        return cls(
            job_name=e.get(ENV_JOB_NAME, "local"),
            namespace=e.get(ENV_JOB_NAMESPACE, "default"),
            replica_type=e.get(ENV_REPLICA_TYPE, "worker"),
            replica_index=int(e.get(ENV_REPLICA_INDEX, "0")),
            process_id=int(e.get(ENV_PROCESS_ID, "0")),
            num_processes=int(e.get(ENV_NUM_PROCESSES, "1")),
            coordinator_address=e.get(ENV_COORDINATOR_ADDRESS),
            mesh_axes=mesh,
            status_dir=e.get(ENV_STATUS_DIR),
        )

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def resolve_coordinator(address: str) -> str:
    """Map cluster DNS to something dialable.  In-cluster, the headless
    Service name resolves naturally; under the local process runtime,
    ``<pod>.<ns>.svc`` hosts all live on this machine -> 127.0.0.1."""
    host, _, port = address.rpartition(":")
    if host.endswith(".svc") or host.endswith(".svc.cluster.local"):
        host = "127.0.0.1"
    return f"{host}:{port}"


def initialize(ctx: Optional[PodContext] = None) -> PodContext:
    """Join the job's collective: the TPU-native rendezvous.

    Single-process jobs skip the coordination service entirely (the TFJob
    MNIST smoke-config path).  Multi-process jobs dial the coordinator;
    process 0 *is* the coordinator (rank-0-as-coordinator, the JAXJob
    controller convention).
    """
    ctx = ctx or PodContext.from_env()
    cache_dir = os.environ.get(ENV_COMPILE_CACHE)
    if cache_dir:
        # must be configured BEFORE the first compilation; thresholds
        # zeroed so even small programs (smoke jobs, CPU stand-in) cache —
        # the default min-compile-time gate would skip exactly the
        # restart-critical entries on fast backends
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    if ctx.num_processes > 1:
        import jax

        assert ctx.coordinator_address, "multi-process job missing coordinator address"
        jax.distributed.initialize(
            coordinator_address=resolve_coordinator(ctx.coordinator_address),
            num_processes=ctx.num_processes,
            process_id=ctx.process_id,
        )
    return ctx


def barrier(ctx: PodContext) -> float:
    """First global collective; stamps the gang-startup probe file."""
    # a real global collective across every process, not just a
    # coordination-service ping: proving device-level collectives work is
    # what "the gang is up" means
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(f"{ctx.job_name}-gang-barrier")
    t = time.time()
    if ctx.status_dir:
        os.makedirs(ctx.status_dir, exist_ok=True)
        with open(os.path.join(ctx.status_dir, BARRIER_FILE), "w") as f:
            f.write(repr(t))
    return t


def emit_metric(ctx: PodContext, name: str, value: float, **extra) -> None:
    """Append a metric line to the pod's status stream AND stdout.

    Stdout is the Katib-style collector contract (``name=value``); the
    status-dir jsonl is the structured channel the metrics collector scrapes
    without parsing logs (SURVEY.md §5 observability).
    """
    print(f"{name}={value}", flush=True)
    if ctx.status_dir:
        os.makedirs(ctx.status_dir, exist_ok=True)
        with open(os.path.join(ctx.status_dir, METRICS_FILE), "a") as f:
            f.write(json.dumps({"name": name, "value": value, "ts": time.time(), **extra}) + "\n")
