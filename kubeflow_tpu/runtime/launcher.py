"""LocalKubelet: runs bound Pods as real OS processes.

The kubelet tier the reference outsources to Kubernetes (SURVEY.md §4:
"multi-process JAX e2e on CPU ... the honest stand-in for multi-host TPU").
Responsibilities, mirroring a real kubelet + the operator's pod watching:

- spawn a process per bound pod (env from the pod template + the status-dir
  contract), capture stdout/stderr to per-pod log files (the ``kubectl
  logs`` surface the SDK and the HPO metrics collector read);
- poll liveness; fold exit codes into ``Pod.status`` (phase, exit_code);
- surface the gang-barrier stamp from the status dir into
  ``Pod.status.barrier_time`` (gang-startup metric source);
- kill processes whose pods are deleted (suspend, gang restart, cleanup) —
  the SIGTERM-then-SIGKILL grace path.
"""

from __future__ import annotations

import logging
import os
import queue
import shutil
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..controlplane.objects import KIND_POD, Pod, PodPhase
from ..controlplane.store import DELETED, TOO_OLD, NotFound, Store, WatchEvent
from . import bootstrap

log = logging.getLogger("kubeflow_tpu.kubelet")

GRACE_SECONDS = 3.0


@dataclass
class _Proc:
    popen: subprocess.Popen
    pod_uid: str
    status_dir: str
    log_path: str
    barrier_reported: bool = False


class LocalKubelet:
    def __init__(
        self,
        store: Store,
        root_dir: str,
        node_names: Optional[set[str]] = None,
        interval: float = 0.03,
        env_overrides: Optional[dict[str, str]] = None,
    ) -> None:
        self.store = store
        self.root_dir = root_dir
        self.node_names = node_names  # None = adopt every bound pod
        self.interval = interval
        self.env_overrides = env_overrides or {}
        self._procs: dict[str, _Proc] = {}  # ns/name -> proc
        self._kill_threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watch = None
        os.makedirs(self.logs_dir, exist_ok=True)

    @property
    def logs_dir(self) -> str:
        return os.path.join(self.root_dir, "logs")

    def pod_log_path(self, namespace: str, name: str) -> str:
        return os.path.join(self.logs_dir, namespace, f"{name}.log")

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._watch = self.store.watch([KIND_POD])
        self._thread = threading.Thread(target=self._loop, name="local-kubelet", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self._watch is not None:
            self.store.stop_watch(self._watch)
        for key in list(self._procs):
            self._kill(key)
        for t in list(self._kill_threads):
            t.join(timeout=2 * GRACE_SECONDS + 1)
        self._kill_threads.clear()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._drain_deletions()
                self.step()
            except Exception:  # noqa: BLE001
                log.exception("kubelet step failed")
            self._stop.wait(self.interval)

    def _drain_deletions(self) -> None:
        assert self._watch is not None
        while True:
            try:
                ev: WatchEvent = self._watch.q.get_nowait()
            except queue.Empty:
                return
            if ev.type == TOO_OLD:
                # the bounded watch overflowed and closed: events were
                # dropped, so re-subscribe THEN relist — a pod deleted in
                # the lost window has no store object; its process must
                # still die, never linger unkilled
                self._watch = self.store.watch([KIND_POD])
                live = {p.key for p in self.store.list(KIND_POD)}
                for key in [k for k in self._procs if k not in live]:
                    self._kill(key)
                continue
            if ev.type == DELETED and ev.obj.kind == KIND_POD:
                self._kill(ev.obj.key)

    # -- core ------------------------------------------------------------------

    def step(self) -> None:
        for pod in self.store.list(KIND_POD):
            assert isinstance(pod, Pod)
            if self.node_names is not None and pod.spec.node_name not in self.node_names:
                continue
            key = pod.key
            if pod.status.phase == PodPhase.PENDING and pod.spec.node_name:
                if key not in self._procs:
                    self._spawn(pod)
            elif pod.status.phase == PodPhase.RUNNING:
                self._check(pod)

    def _build_env(self, pod: Pod, status_dir: str) -> dict[str, str]:
        base_keys = ("PATH", "HOME", "PYTHONPATH", "TMPDIR", "LD_LIBRARY_PATH")
        env = {k: os.environ[k] for k in base_keys if k in os.environ}
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.getcwd(), env.get("PYTHONPATH")) if p
        )
        # default the pod to the CPU backend unless its template says
        # otherwise — N pod processes sharing one TPU chip would all try to
        # grab it; TPU execution is the flagship trainer's direct path
        env.setdefault("JAX_PLATFORMS", os.environ.get("KFT_POD_JAX_PLATFORMS", "cpu"))
        env.update(pod.spec.container.env)
        env.update(self.env_overrides)
        env[bootstrap.ENV_STATUS_DIR] = status_dir
        if pod.spec.container.entrypoint:
            env[bootstrap.ENV_ENTRYPOINT] = pod.spec.container.entrypoint
        return env

    def _spawn(self, pod: Pod) -> None:
        status_dir = os.path.join(
            self.root_dir, "status", pod.metadata.namespace, pod.metadata.name
        )
        shutil.rmtree(status_dir, ignore_errors=True)
        os.makedirs(status_dir, exist_ok=True)
        log_path = self.pod_log_path(pod.metadata.namespace, pod.metadata.name)
        os.makedirs(os.path.dirname(log_path), exist_ok=True)

        c = pod.spec.container
        if c.entrypoint:
            argv = [sys.executable, "-m", "kubeflow_tpu.runtime.pod_main"]
        elif c.command:
            argv = list(c.command) + list(c.args)
        else:
            self._set_status(pod, PodPhase.FAILED, exit_code=2, message="no command/entrypoint")
            return

        env = self._build_env(pod, status_dir)
        logf = open(log_path, "ab", buffering=0)
        try:
            popen = subprocess.Popen(
                argv,
                env=env,
                stdout=logf,
                stderr=subprocess.STDOUT,
                cwd=c.working_dir or os.getcwd(),
                start_new_session=True,  # own process group -> clean gang kill
            )
        except OSError as e:
            self._set_status(pod, PodPhase.FAILED, exit_code=2, message=str(e))
            return
        finally:
            logf.close()  # child holds its own dup of the fd
        self._procs[pod.key] = _Proc(
            popen=popen,
            pod_uid=pod.metadata.uid or "",
            status_dir=status_dir,
            log_path=log_path,
        )
        self._set_status(
            pod, PodPhase.RUNNING, pid=popen.pid, start_time=time.time()
        )
        log.info("spawned %s pid=%s", pod.key, popen.pid)

    def _check(self, pod: Pod) -> None:
        proc = self._procs.get(pod.key)
        if proc is None or proc.pod_uid != (pod.metadata.uid or ""):
            return
        # surface the gang-barrier stamp as soon as it exists
        if not proc.barrier_reported:
            bfile = os.path.join(proc.status_dir, bootstrap.BARRIER_FILE)
            if os.path.exists(bfile):
                try:
                    with open(bfile) as f:
                        t = float(f.read().strip())
                    self._set_status(pod, None, barrier_time=t)
                    proc.barrier_reported = True
                except (ValueError, OSError):
                    pass
        # surface activity heartbeats (notebook culling signal); only write
        # through when the stamp moved, to keep status churn low
        afile = os.path.join(proc.status_dir, "activity")
        try:
            t = float(open(afile).read().strip())
            if t > (pod.status.last_activity or 0.0) + 0.5:
                self._set_status(pod, None, last_activity=t)
        except (ValueError, OSError):
            pass
        code = proc.popen.poll()
        if code is None:
            return
        del self._procs[pod.key]
        if code < 0:
            # Popen reports signal deaths as -N; real kubelets report
            # 128+N (SIGKILL -> 137, SIGTERM -> 143), which is what the
            # RestartPolicy ExitCode allowlist treats as retryable.
            code = 128 - code
        phase = PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
        self._set_status(
            pod, phase, exit_code=code, finish_time=time.time()
        )
        log.info("pod %s exited code=%s", pod.key, code)

    def _kill(self, key: str) -> None:
        """SIGTERM -> grace -> SIGKILL, OFF the kubelet loop thread: the
        grace wait used to block the single-threaded loop, delaying the
        NEXT incarnation's spawn by up to GRACE_SECONDS whenever a gang
        restart's survivor was wedged in a collective with its dead peer
        (measured as a 3.1s respawn phase in the restart decomposition,
        scripts/gang_startup_bench.py)."""
        proc = self._procs.pop(key, None)
        if proc is None:
            return
        popen = proc.popen
        if popen.poll() is not None:
            return

        def grace_kill():
            try:
                os.killpg(os.getpgid(popen.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                popen.wait(timeout=GRACE_SECONDS)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(popen.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    popen.wait(timeout=GRACE_SECONDS)
                except subprocess.TimeoutExpired:
                    pass

        t = threading.Thread(
            target=grace_kill, name=f"pod-kill-{popen.pid}", daemon=True)
        t.start()
        # prune finished grace threads as we go — a long-lived kubelet
        # restarting gangs must not accumulate one dead Thread per kill
        self._kill_threads = [
            x for x in self._kill_threads if x.is_alive()]
        self._kill_threads.append(t)

    # -- status writes ---------------------------------------------------------

    def _set_status(self, pod: Pod, phase: Optional[PodPhase], **fields) -> None:
        def mut(o):
            assert isinstance(o, Pod)
            if phase is not None:
                o.status.phase = phase
            for k, v in fields.items():
                if k == "message":
                    o.status.message = str(v)
                else:
                    setattr(o.status, k, v)

        try:
            self.store.update_with_retry(
                KIND_POD, pod.metadata.name, pod.metadata.namespace, mut
            )
        except NotFound:
            self._kill(pod.key)
