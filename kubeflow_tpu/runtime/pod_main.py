"""Pod process entrypoint: ``python -m kubeflow_tpu.runtime.pod_main``.

What the kubelet execs for containers that declare a python ``entrypoint``
(``module:function``).  Sequence: parse the env contract -> join the
collective (``jax.distributed``) -> pass the gang barrier (stamping the
startup probe) -> run the user function.  The user function receives the
``PodContext`` and its return value is ignored; failures map to exit codes
the controller's RestartPolicy understands (SURVEY.md §5 failure detection).
"""

from __future__ import annotations

import importlib
import os
import sys
import traceback

from . import bootstrap


def resolve_target(spec: str):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(f"entrypoint {spec!r} must be 'module:function'")
    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


def main() -> int:
    ctx = bootstrap.PodContext.from_env()
    target_spec = os.environ.get(bootstrap.ENV_ENTRYPOINT)
    if not target_spec:
        print("pod_main: no KFT_ENTRYPOINT set", file=sys.stderr)
        return 2
    try:
        fn = resolve_target(target_spec)
    except Exception:
        traceback.print_exc()
        return 2
    try:
        bootstrap.initialize(ctx)
        bootstrap.barrier(ctx)
    except Exception:
        traceback.print_exc()
        # rendezvous failures are retryable by convention (another rank may
        # have died first; a gang restart can heal it)
        return 42
    try:
        fn(ctx)
        return 0
    except SystemExit as e:
        return int(e.code or 0)
    except Exception:
        traceback.print_exc()
        return 1


if __name__ == "__main__":
    sys.exit(main())
