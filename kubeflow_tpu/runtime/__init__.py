"""In-pod runtime: bootstrap, local kubelet, platform facade."""

from .bootstrap import PodContext, barrier, emit_metric, initialize
from .launcher import LocalKubelet
from .platform import LocalPlatform

__all__ = [k for k in dir() if not k.startswith("_")]
