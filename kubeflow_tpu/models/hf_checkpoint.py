"""Published-Llama checkpoint interop: safetensors -> this repo's params.

The reference's fine-tune/serve UX starts from STOCK published
checkpoints — ``train(model="hf://meta-llama/Llama-2-7b")`` hands the
job a safetensors snapshot in the transformers layout [upstream:
kubeflow/training-operator -> sdk train() v1.9 LLM path; kserve
huggingfaceserver storage initializer; SURVEY.md §3.5, §2.2 storage
row].  This repo's own snapshot format (``save_pretrained``:
config.json + weights.msgpack) round-trips only itself, so a genuine
published Llama could not load (r4 verdict missing #2).  This module
closes that: a pure-numpy safetensors reader (the format is an 8-byte
little-endian header length + JSON header + raw tensor bytes — no
dependency needed, and zero-egress-safe since it only ever touches
local files) plus the name/layout map onto the scanned flax tree.

Layout notes (verified against the flax module tree in llama.py):

- torch ``nn.Linear`` stores ``[out, in]``; every Einsum kernel here is
  input-major, so projections transpose.  Attention out dims unfold
  head-major: ``q_proj [H*D, E] -> wq.kernel [E, H, D]`` (HF's
  ``.view(num_heads, head_dim)`` order), ``o_proj [E, H*D] ->
  wo.kernel [H, D, E]``.
- rotary needs NO re-permutation: HF applies ``rotate_half`` over a
  split-at-half layout (the GPT-NeoX convention its conversion script
  permutes Meta weights into), and ``llama.rope`` uses the same
  split-half form — ``[x1*cos - x2*sin, x2*cos + x1*sin]``.
- per-layer tensors stack along a leading layer axis (``nn.scan``'s
  stacked layout, llama.py ``metadata_params: layers``).
- ``lm_head.weight`` absent + ``tie_word_embeddings`` true -> the
  config maps to ``tie_embeddings`` and the head reuses the table.
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}


def _decode(raw: bytes, dtype: str, shape: list[int]) -> np.ndarray:
    if dtype == "BF16":
        # numpy has no bfloat16: widen via the bit pattern (bf16 is the
        # top 16 bits of f32)
        u16 = np.frombuffer(raw, dtype="<u2")
        return (u16.astype(np.uint32) << 16).view(np.float32).reshape(shape)
    if dtype not in _DTYPES:
        raise ValueError(f"unsupported safetensors dtype {dtype!r}")
    return np.frombuffer(
        raw, dtype=np.dtype(_DTYPES[dtype]).newbyteorder("<")
    ).reshape(shape)


class SafetensorsView:
    """Lazy, mmap-backed view over one or more safetensors files.

    A 7B bf16 snapshot is ~13.5 GB; eagerly decoding every tensor while
    also building the stacked f32 param tree would peak at several times
    the model size in host RSS.  Files mmap instead (pages stream in on
    access and are evictable), and ``__getitem__`` decodes ONE tensor per
    call — non-BF16 tensors come back as zero-copy views into the map,
    BF16 widens per tensor.  The converter touches each tensor exactly
    once, so peak = final params + one tensor.
    """

    def __init__(self) -> None:
        self._entries: dict[str, tuple[memoryview, dict]] = {}
        self._maps: list = []  # keep mmaps alive

    def add_file(self, path: str) -> None:
        import mmap as mmaplib

        f = open(path, "rb")
        try:
            mm = mmaplib.mmap(f.fileno(), 0, access=mmaplib.ACCESS_READ)
        finally:
            f.close()  # the map holds its own reference
        self._maps.append(mm)
        if len(mm) < 8:
            raise ValueError(f"{path}: not a safetensors file")
        (hlen,) = np.frombuffer(mm[:8], dtype="<u8")
        hlen = int(hlen)
        if 8 + hlen > len(mm):
            raise ValueError(f"{path}: header length {hlen} exceeds file")
        header = json.loads(bytes(mm[8 : 8 + hlen]))
        data = memoryview(mm)[8 + hlen :]
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            begin, end = meta["data_offsets"]
            if not (0 <= begin <= end <= len(data)):
                raise ValueError(
                    f"{path}: tensor {name!r} offsets out of range")
            self._entries[name] = (data[begin:end], meta)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __iter__(self):
        return iter(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    def keys(self):
        return self._entries.keys()

    def __getitem__(self, name: str) -> np.ndarray:
        raw, meta = self._entries[name]
        return _decode(raw, meta["dtype"], meta["shape"])


def read_safetensors(path: str) -> dict[str, np.ndarray]:
    """One ``*.safetensors`` file -> {name: array}, eager (small files /
    tests; the checkpoint-sized path goes through SafetensorsView)."""
    view = SafetensorsView()
    view.add_file(path)
    return {name: np.array(view[name]) for name in view}


def load_safetensors_dir(path: str) -> SafetensorsView:
    """All tensors of a snapshot directory — single ``model.safetensors``
    or the sharded ``model-XXXXX-of-YYYYY.safetensors`` + index layout —
    as one lazy mmap-backed view."""
    view = SafetensorsView()
    index = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map: dict[str, str] = json.load(f)["weight_map"]
        for fname in sorted(set(weight_map.values())):
            view.add_file(os.path.join(path, fname))
        missing = set(weight_map) - set(view.keys())
        if missing:
            raise ValueError(
                f"index names missing tensors: {sorted(missing)[:5]}")
        return view
    files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for fname in files:
        view.add_file(os.path.join(path, fname))
    return view


def is_hf_snapshot(path: str) -> bool:
    """Transformers-layout detector: a ``model_type`` key in config.json
    (this repo's ``save_pretrained`` writes the LlamaConfig dataclass,
    which has none) or any safetensors file."""
    cfg_path = os.path.join(path, "config.json")
    if os.path.exists(cfg_path):
        try:
            with open(cfg_path) as f:
                if "model_type" in json.load(f):
                    return True
        except (OSError, json.JSONDecodeError):
            pass
    return any(
        f.endswith(".safetensors") for f in os.listdir(path)
    ) if os.path.isdir(path) else False


def config_from_hf(path: str):
    """transformers ``config.json`` -> LlamaConfig (architecture fields
    only; TPU-side knobs — dtype, remat, attention_impl — keep this
    repo's defaults and remain overridable via dataclasses.replace)."""
    from . import llama as llamalib

    with open(os.path.join(path, "config.json")) as f:
        d = json.load(f)
    mt = d.get("model_type", "llama")
    if mt not in ("llama", "mistral"):
        raise ValueError(
            f"unsupported checkpoint model_type {mt!r} (llama-family only)")
    sw = d.get("sliding_window")
    if sw and int(sw) < int(d.get("max_position_embeddings", sw)):
        # attending past the trained window silently degrades output —
        # refuse loudly like the rope_scaling guard below
        raise ValueError(
            f"sliding_window={sw} attention is not implemented by "
            "models/llama.py; refusing a checkpoint that would silently "
            "mis-generate past the window")
    rs = d.get("rope_scaling") or {}
    if rs and rs.get("rope_type", rs.get("type")) not in (None, "default"):
        # silently dropping llama3/linear/yarn rope scaling would load a
        # model that runs but generates garbage — fail loudly instead
        raise ValueError(
            f"rope_scaling {rs.get('rope_type', rs.get('type'))!r} is not "
            "implemented by models/llama.py rope(); refusing to load a "
            "checkpoint that would silently mis-generate")
    heads = int(d["num_attention_heads"])
    hidden = int(d["hidden_size"])
    return llamalib.LlamaConfig(
        vocab_size=int(d["vocab_size"]),
        hidden_size=hidden,
        intermediate_size=int(d["intermediate_size"]),
        num_layers=int(d["num_hidden_layers"]),
        num_heads=heads,
        num_kv_heads=int(d.get("num_key_value_heads", heads)),
        head_dim=int(d.get("head_dim", hidden // heads)),
        max_seq_len=int(d.get("max_position_embeddings", 4096)),
        rope_theta=float(d.get("rope_theta", 10000.0)),
        rms_norm_eps=float(d.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(d.get("tie_word_embeddings", False)),
    )


def llama_params_from_hf(cfg, tensors) -> Any:
    """HF tensor mapping (dict or SafetensorsView) -> this repo's
    (scan-stacked) param tree, in ``cfg.param_dtype``."""
    import jax.numpy as jnp

    E, M = cfg.hidden_size, cfg.intermediate_size
    H, KV, D, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    pd = np.dtype(jnp.dtype(cfg.param_dtype).name)

    def t(name: str, shape: tuple[int, ...]) -> np.ndarray:
        if name not in tensors:
            raise KeyError(f"checkpoint missing tensor {name!r}")
        arr = tensors[name]
        if tuple(arr.shape) != shape:
            raise ValueError(
                f"{name}: shape {arr.shape} != expected {shape}")
        return arr.astype(pd)

    def stack(fmt: str, shape, reshape=None, transpose=False):
        rows = []
        for layer in range(L):
            arr = t(fmt.format(layer), shape)
            if transpose:
                arr = arr.T
            if reshape is not None:
                arr = arr.reshape(reshape)
            rows.append(arr)
        return np.stack(rows)

    p = "model.layers.{}."
    block = {
        "attn_norm": {"scale": stack(p + "input_layernorm.weight", (E,))},
        "mlp_norm": {
            "scale": stack(p + "post_attention_layernorm.weight", (E,))},
        "attn": {
            "wq": {"kernel": stack(
                p + "self_attn.q_proj.weight", (H * D, E),
                reshape=(E, H, D), transpose=True)},
            "wk": {"kernel": stack(
                p + "self_attn.k_proj.weight", (KV * D, E),
                reshape=(E, KV, D), transpose=True)},
            "wv": {"kernel": stack(
                p + "self_attn.v_proj.weight", (KV * D, E),
                reshape=(E, KV, D), transpose=True)},
            # o_proj [E, H*D] -> [H*D, E] -> [H, D, E]
            "wo": {"kernel": stack(
                p + "self_attn.o_proj.weight", (E, H * D),
                reshape=(H, D, E), transpose=True)},
        },
        "mlp": {
            "w_gate": {"kernel": stack(
                p + "mlp.gate_proj.weight", (M, E), transpose=True)},
            "w_up": {"kernel": stack(
                p + "mlp.up_proj.weight", (M, E), transpose=True)},
            "w_down": {"kernel": stack(
                p + "mlp.down_proj.weight", (E, M), transpose=True)},
        },
    }
    params: dict[str, Any] = {
        "embedder": {
            "embedding": t("model.embed_tokens.weight", (cfg.vocab_size, E))},
        "layers": {"block": block},
        "head": {"final_norm": {"scale": t("model.norm.weight", (E,))}},
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" not in tensors:
            raise KeyError(
                "checkpoint has no lm_head.weight but config does not tie "
                "embeddings")
        params["head"]["unembedding"] = t(
            "lm_head.weight", (cfg.vocab_size, E)).T.copy()
    return params


def load_hf_llama(path: str):
    """(LlamaConfig, params) from a transformers-layout snapshot dir."""
    cfg = config_from_hf(path)
    params = llama_params_from_hf(cfg, load_safetensors_dir(path))
    return cfg, params
