"""MNIST-class smoke model: tiny MLP, data-parallel pjit training.

The baseline config-1 equivalent (BASELINE.md row 1: "TFJob MNIST
single-worker" — here a JaxJob on any world size).  Synthetic data from a
fixed linear teacher keeps the e2e hermetic (no dataset downloads; the
reference's MNIST examples fetch from the network, which this environment
forbids).  The ``train_main`` entrypoint is what JaxJob manifests reference
as ``kubeflow_tpu.models.mnist:train_main``.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from ..parallel import mesh as meshlib
from ..runtime import bootstrap

IMAGE_DIM = 64
NUM_CLASSES = 10


class MLP(nn.Module):
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(NUM_CLASSES)(x)
        return x


def synthetic_batch(key: jax.Array, batch: int):
    """Deterministic teacher: labels from a fixed random projection."""
    kx, _ = jax.random.split(key)
    x = jax.random.normal(kx, (batch, IMAGE_DIM))
    teacher = jax.random.normal(jax.random.PRNGKey(7), (IMAGE_DIM, NUM_CLASSES))
    y = jnp.argmax(x @ teacher, axis=-1)
    return x, y


def train_main(ctx: "bootstrap.PodContext") -> None:
    """Entrypoint for JaxJob pods: DP training over the job's global mesh."""
    steps = int(os.environ.get("KFT_STEPS", "30"))
    global_batch = int(os.environ.get("KFT_BATCH", "64"))
    lr = float(os.environ.get("KFT_LR", "0.05"))

    mesh = meshlib.build_mesh(ctx.mesh_axes or {"data": jax.device_count()})
    x_shard = meshlib.batch_sharding(mesh)
    model = MLP()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, IMAGE_DIM)))
    tx = optax.sgd(lr, momentum=0.9)
    opt_state = tx.init(params)

    # replicate params/opt-state across the mesh
    rep = meshlib.replicated(mesh)
    params = jax.device_put(params, rep)
    opt_state = jax.device_put(opt_state, rep)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
            acc = (jnp.argmax(logits, -1) == y).mean()
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    local_bs = meshlib.local_batch_size(mesh, global_batch)
    loss = acc = None
    for i in range(steps):
        key = jax.random.fold_in(jax.random.PRNGKey(1), i * ctx.num_processes + ctx.process_id)
        x_local, y_local = synthetic_batch(key, local_bs)
        x = jax.make_array_from_process_local_data(x_shard, jax.device_get(x_local))
        y = jax.make_array_from_process_local_data(x_shard, jax.device_get(y_local))
        params, opt_state, loss, acc = step(params, opt_state, x, y)
    bootstrap.emit_metric(ctx, "loss", float(loss))
    bootstrap.emit_metric(ctx, "accuracy", float(acc))
