"""BERT-family bidirectional encoder, TPU-first.

Capability target: baseline config 3 — "KServe BERT-base InferenceService
(GPU/Triton) -> TPU ServingRuntime" [local: BASELINE.json configs].  The
reference serves BERT from a Triton container; this is the native encoder
the ``tpu`` runtime compiles with XLA instead (serving/runtimes.py
``BertClassifierModel``), and it trains under the same trainer/mesh stack
as the Llama family.

TPU-first choices (mirroring models/llama.py):
- bfloat16 activations / float32 params; LayerNorm in float32.
- the same *logical* axis vocabulary (parallel/sharding.py LOGICAL_RULES):
  ``vocab``/``embed`` on embeddings, ``heads``/``mlp`` on the ``model``
  axis, activations on ``batch``/``act_seq`` — so DP/FSDP/TP/SP apply by
  mesh choice with zero model-code changes.
- optional ``nn.scan`` over layers + remat, same as Llama.
- attention is bidirectional (padding mask only) — encoders have no causal
  structure, so the whole [b, h, s, s] score tensor tiles the MXU densely.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .llama import Einsum

Dtype = Any


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_classes: int = 2
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    remat: bool = False
    scan_layers: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def __post_init__(self):
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide by num_heads")


def tiny(**kw) -> BertConfig:
    """Test/smoke config: one CPU device, <1s."""
    return BertConfig(**{**dict(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position=64, dtype=jnp.float32,
        scan_layers=False,
    ), **kw})


def bert_base(**kw) -> BertConfig:
    return BertConfig(**kw)


def bert_large(**kw) -> BertConfig:
    return BertConfig(**{**dict(
        hidden_size=1024, num_layers=24, num_heads=16,
        intermediate_size=4096,
    ), **kw})


PRESETS = {"tiny": tiny, "bert-base": bert_base, "bert-large": bert_large}


class LayerNorm(nn.Module):
    eps: float
    dtype: Dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param(
            "scale", nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (x.shape[-1],), jnp.float32)
        bias = self.param(
            "bias", nn.with_logical_partitioning(nn.initializers.zeros, ("norm",)),
            (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + self.eps) * scale + bias
        return y.astype(self.dtype)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.cfg
        h, d = cfg.num_heads, cfg.head_dim
        q = Einsum("bse,ehd->bshd", (cfg.hidden_size, h, d),
                   ("embed", "heads", "head_dim"), cfg.dtype, cfg.param_dtype,
                   name="q")(x)
        k = Einsum("bse,ehd->bshd", (cfg.hidden_size, h, d),
                   ("embed", "heads", "head_dim"), cfg.dtype, cfg.param_dtype,
                   name="k")(x)
        v = Einsum("bse,ehd->bshd", (cfg.hidden_size, h, d),
                   ("embed", "heads", "head_dim"), cfg.dtype, cfg.param_dtype,
                   name="v")(x)
        q = nn.with_logical_constraint(q, ("batch", "act_seq", "act_heads", "head_dim"))
        k = nn.with_logical_constraint(k, ("batch", "act_seq", "act_heads", "head_dim"))
        v = nn.with_logical_constraint(v, ("batch", "act_seq", "act_heads", "head_dim"))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores / jnp.sqrt(d).astype(jnp.float32)
        # padding mask: [b, 1, 1, k] additive
        neg = jnp.finfo(jnp.float32).min
        scores = jnp.where(mask[:, None, None, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = nn.with_logical_constraint(
            out, ("batch", "act_seq", "act_heads", "head_dim"))
        return Einsum("bshd,hde->bse", (h, d, cfg.hidden_size),
                      ("heads", "head_dim", "embed"), cfg.dtype,
                      cfg.param_dtype, in_axes=(0, 1), name="o")(out)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.cfg
        attn = SelfAttention(cfg, name="attention")(x, mask)
        x = LayerNorm(cfg.layer_norm_eps, cfg.dtype, name="attn_norm")(x + attn)
        h = Einsum("bse,em->bsm", (cfg.hidden_size, cfg.intermediate_size),
                   ("embed", "mlp"), cfg.dtype, cfg.param_dtype, name="ffn_in")(x)
        h = nn.gelu(h)
        h = nn.with_logical_constraint(h, ("batch", "act_seq", "act_mlp"))
        h = Einsum("bsm,me->bse", (cfg.intermediate_size, cfg.hidden_size),
                   ("mlp", "embed"), cfg.dtype, cfg.param_dtype,
                   name="ffn_out")(h)
        x = LayerNorm(cfg.layer_norm_eps, cfg.dtype, name="ffn_norm")(x + h)
        return nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))


class BertEncoder(nn.Module):
    """Token ids -> (sequence_output [b,s,e], pooled [b,e])."""

    cfg: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        token_type_ids: Optional[jax.Array] = None,
    ) -> tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), jnp.bool_)
        else:
            attention_mask = attention_mask.astype(jnp.bool_)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, s), jnp.int32)

        tok = self.param(
            "token_embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        pos = self.param(
            "position_embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_position, cfg.hidden_size), cfg.param_dtype)
        seg = self.param(
            "segment_embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, "embed")),
            (cfg.type_vocab_size, cfg.hidden_size), cfg.param_dtype)
        x = (tok[input_ids] + pos[jnp.arange(s)][None, :, :]
             + seg[token_type_ids]).astype(cfg.dtype)
        x = LayerNorm(cfg.layer_norm_eps, cfg.dtype, name="embed_norm")(x)
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))

        layer_cls = EncoderLayer
        if cfg.remat:
            layer_cls = nn.remat(
                layer_cls, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (mdl(carry, attention_mask), None),
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(layer_cls(cfg, name="layers"), x, None)
        else:
            for i in range(cfg.num_layers):
                x = layer_cls(cfg, name=f"layer_{i}")(x, attention_mask)

        pooled = jnp.tanh(Einsum(
            "be,ef->bf", (cfg.hidden_size, cfg.hidden_size),
            ("embed", None), cfg.dtype, cfg.param_dtype,
            name="pooler")(x[:, 0, :]))
        return x, pooled


class BertClassifier(nn.Module):
    """Pooled [CLS] -> class logits (the sequence-classification head)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        _, pooled = BertEncoder(self.cfg, name="encoder")(
            input_ids, attention_mask, token_type_ids)
        return Einsum("be,ec->bc", (self.cfg.hidden_size, self.cfg.num_classes),
                      ("embed", None), self.cfg.dtype, self.cfg.param_dtype,
                      name="classifier")(pooled.astype(self.cfg.dtype))


# -- pretrained snapshot IO (HF-layout directories) -------------------------


def save_pretrained(path: str, cfg: BertConfig, params: Any) -> None:
    """Write an HF-layout snapshot: ``config.json`` + ``weights.msgpack``
    (flax serialization).  What ``hf://`` snapshots under $KFT_HF_HOME
    contain, and what ``load_pretrained`` reads back."""
    import json
    import os

    from flax import serialization

    os.makedirs(path, exist_ok=True)
    d = dataclasses.asdict(cfg)
    d["dtype"] = jnp.dtype(cfg.dtype).name
    d["param_dtype"] = jnp.dtype(cfg.param_dtype).name
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(d, f, indent=1)
    with open(os.path.join(path, "weights.msgpack"), "wb") as f:
        f.write(serialization.msgpack_serialize(
            jax.tree.map(lambda x: jax.device_get(x), nn.meta.unbox(params))))


def load_pretrained(path: str) -> tuple[BertConfig, Any]:
    """Read a snapshot written by ``save_pretrained`` (or any directory in
    that layout) into (config, params)."""
    import json
    import os

    from flax import serialization

    with open(os.path.join(path, "config.json")) as f:
        d = json.load(f)
    d["dtype"] = jnp.dtype(d["dtype"])
    d["param_dtype"] = jnp.dtype(d["param_dtype"])
    cfg = BertConfig(**d)
    with open(os.path.join(path, "weights.msgpack"), "rb") as f:
        params = serialization.msgpack_restore(f.read())
    return cfg, params
