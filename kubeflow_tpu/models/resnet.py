"""ResNet family (v1.5 bottlenecks), TPU-first.

Capability target: baseline config 2 — "PyTorchJob DDP ResNet-50, 2
replicas, NCCL allreduce" [local: BASELINE.json configs]; here the same
model trains data-parallel over the job mesh with XLA's psum taking NCCL's
place, launched as an ordinary JaxJob (``train_main`` entrypoint).

TPU-first choices:
- NHWC layout (XLA:TPU's native conv layout; NCHW would transpose on every
  conv) and bfloat16 activations with float32 params.
- GroupNorm instead of BatchNorm: no mutable batch statistics, no
  cross-replica variance sync, jit-pure — the standard trick for clean
  SPMD conv nets (and accuracy-neutral at ResNet scale).
- stride-2 convs exactly where v1.5 puts them (in the 3x3), so the FLOP
  profile matches the reference model the benchmark names.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

Dtype = Any


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # resnet-50
    num_filters: int = 64
    num_classes: int = 1000
    bottleneck: bool = True
    norm_groups: int = 32
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32


def tiny(**kw) -> ResNetConfig:
    """Test/smoke config: 2 stages of basic blocks, tiny widths."""
    return ResNetConfig(**{**dict(
        stage_sizes=(1, 1), num_filters=8, num_classes=10,
        bottleneck=False, norm_groups=4, dtype=jnp.float32,
    ), **kw})


def resnet18(**kw) -> ResNetConfig:
    return ResNetConfig(**{**dict(
        stage_sizes=(2, 2, 2, 2), bottleneck=False), **kw})


def resnet50(**kw) -> ResNetConfig:
    return ResNetConfig(**kw)


def resnet101(**kw) -> ResNetConfig:
    return ResNetConfig(**{**dict(stage_sizes=(3, 4, 23, 3)), **kw})


PRESETS = {"tiny": tiny, "resnet-18": resnet18, "resnet-50": resnet50,
           "resnet-101": resnet101}


def _norm(cfg: ResNetConfig, features: int, name: str):
    groups = min(cfg.norm_groups, features)
    while features % groups:
        groups -= 1
    return nn.GroupNorm(num_groups=groups, dtype=cfg.dtype, name=name)


class Block(nn.Module):
    """Basic residual block (3x3 + 3x3)."""

    cfg: ResNetConfig
    filters: int
    strides: int

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        residual = x
        y = nn.Conv(self.filters, (3, 3), (self.strides, self.strides),
                    use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, name="conv1")(x)
        y = _norm(cfg, self.filters, "norm1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, name="conv2")(y)
        y = _norm(cfg, self.filters, "norm2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), (self.strides, self.strides),
                use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="proj")(residual)
            residual = _norm(cfg, self.filters, "proj_norm")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """v1.5 bottleneck: 1x1 reduce, 3x3 (stride here), 1x1 expand."""

    cfg: ResNetConfig
    filters: int
    strides: int

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        residual = x
        out = self.filters * 4
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, name="conv1")(x)
        y = nn.relu(_norm(cfg, self.filters, "norm1")(y))
        y = nn.Conv(self.filters, (3, 3), (self.strides, self.strides),
                    use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, name="conv2")(y)
        y = nn.relu(_norm(cfg, self.filters, "norm2")(y))
        y = nn.Conv(out, (1, 1), use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, name="conv3")(y)
        y = _norm(cfg, out, "norm3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                out, (1, 1), (self.strides, self.strides), use_bias=False,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="proj")(residual)
            residual = _norm(cfg, out, "proj_norm")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """NHWC images [b, h, w, 3] -> class logits [b, num_classes]."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.num_filters, (7, 7), (2, 2), use_bias=False,
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                    name="stem")(x)
        x = nn.relu(_norm(cfg, cfg.num_filters, "stem_norm")(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        block_cls = BottleneckBlock if cfg.bottleneck else Block
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for b in range(n_blocks):
                x = block_cls(
                    cfg,
                    filters=cfg.num_filters * 2 ** stage,
                    strides=2 if stage > 0 and b == 0 else 1,
                    name=f"stage{stage}_block{b}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, name="head")(x)


# -- JaxJob entrypoint (baseline config 2) ----------------------------------

IMAGE_SIZE = 32  # synthetic-data default; real ImageNet would use 224


def synthetic_batch(key: jax.Array, batch: int, num_classes: int):
    """Deterministic teacher labels from a fixed projection of the image."""
    kx, _ = jax.random.split(key)
    x = jax.random.normal(kx, (batch, IMAGE_SIZE, IMAGE_SIZE, 3))
    teacher = jax.random.normal(
        jax.random.PRNGKey(11), (IMAGE_SIZE * IMAGE_SIZE * 3, num_classes))
    y = jnp.argmax(x.reshape(batch, -1) @ teacher, axis=-1)
    return x, y


def train_main(ctx) -> None:
    """DDP-ResNet entrypoint for JaxJob pods (BASELINE config 2 analog):
    data-parallel over the job's global mesh, per-step loss on stdout."""
    from ..parallel import mesh as meshlib
    from ..runtime import bootstrap

    steps = int(os.environ.get("KFT_STEPS", "10"))
    global_batch = int(os.environ.get("KFT_BATCH", "32"))
    lr = float(os.environ.get("KFT_LR", "0.1"))
    preset = os.environ.get("KFT_RESNET", "tiny")

    cfg = PRESETS[preset](num_classes=10)
    mesh = meshlib.build_mesh(ctx.mesh_axes or {"data": jax.device_count()})
    x_shard = meshlib.batch_sharding(mesh)
    model = ResNet(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3)))
    tx = optax.sgd(lr, momentum=0.9)
    opt_state = tx.init(params)
    rep = meshlib.replicated(mesh)
    params = jax.device_put(params, rep)
    opt_state = jax.device_put(opt_state, rep)

    def loss_fn(p, x, y):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def step(p, o, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    local_bs = meshlib.local_batch_size(mesh, global_batch)
    loss = None
    for i in range(steps):
        key = jax.random.fold_in(
            jax.random.PRNGKey(1), i * ctx.num_processes + ctx.process_id)
        x_local, y_local = synthetic_batch(key, local_bs, cfg.num_classes)
        x = jax.make_array_from_process_local_data(x_shard, jax.device_get(x_local))
        y = jax.make_array_from_process_local_data(x_shard, jax.device_get(y_local))
        params, opt_state, loss = step(params, opt_state, x, y)
        bootstrap.emit_metric(ctx, "loss", float(loss), step=i)
