"""Mixture-of-Experts MLP with expert-parallel dispatch.

The SURVEY §2.5 EP row ("mesh ``expert`` axis + all-to-all dispatch") the
round-1 verdict flagged as missing.  Design is the GShard/Switch dense
dispatch formulated for XLA:

- routing, capacity assignment, and combine are all static-shaped einsums
  over one-hot dispatch tensors — no ragged shapes, no data-dependent
  control flow, so the whole layer jits and shards;
- expert weights are stacked [E, ...] and carry the ``expert`` logical
  axis; grouped activations inside the expert computation carry
  ``expert_batch`` on their batch dim (the ``expert`` mesh axis is spent
  on the expert dim there).  Tokens are batch-sharded over the ``expert``
  axis OUTSIDE the layer (GShard convention: EP groups share DP), so
  GSPMD lowers the dispatch/return reshardings to real all-to-all
  collectives (asserted in tests by inspecting the compiled HLO);
- capacity-factor token dropping bounds the per-expert group size (the
  ragged_all_to_all upgrade path can land later without changing the
  routing contract);
- the Switch load-balancing auxiliary loss is sown into the
  ``intermediates`` collection under ``moe_aux_loss``.

With top-k probabilities renormalized (default) and identical expert
weights, the layer is exactly the dense MLP — the equivalence the unit
tests pin down.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .llama import LlamaConfig


class MoeMlp(nn.Module):
    """Drop-in MoE replacement for the Llama gated MLP."""

    cfg: "LlamaConfig"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        e = cfg.moe_experts
        k = cfg.moe_top_k
        b, s, h = x.shape
        m = cfg.intermediate_size

        router = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "expert_dim")),
            (h, e), jnp.float32,
        )
        init = nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal",
                                                in_axis=(1,), out_axis=(2,))
        w_gate = self.param(
            "w_gate",
            nn.with_logical_partitioning(init, ("expert", "embed", "mlp")),
            (e, h, m), cfg.param_dtype,
        )
        w_up = self.param(
            "w_up",
            nn.with_logical_partitioning(init, ("expert", "embed", "mlp")),
            (e, h, m), cfg.param_dtype,
        )
        init_down = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=(1,), out_axis=(2,))
        w_down = self.param(
            "w_down",
            nn.with_logical_partitioning(init_down, ("expert", "mlp", "embed")),
            (e, m, h), cfg.param_dtype,
        )

        # -- routing (f32 for a stable softmax) ---------------------------
        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32), router)
        probs = jax.nn.softmax(logits, axis=-1)                  # [b, s, e]
        gate_vals, idx = jax.lax.top_k(probs, k)                 # [b, s, k]
        if cfg.moe_normalize_topk:
            gate_vals = gate_vals / (
                gate_vals.sum(axis=-1, keepdims=True) + 1e-9)

        # -- capacity assignment (sequence-major priority) ----------------
        capacity = max(1, int(cfg.moe_capacity_factor * k * s / e))
        expert_mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [b, s, k, e]
        flat = expert_mask.transpose(0, 2, 1, 3).reshape(b, k * s, e)
        pos_flat = jnp.cumsum(flat, axis=1) - flat               # queue index
        pos = pos_flat.reshape(b, k, s, e).transpose(0, 2, 1, 3)  # [b, s, k, e]
        keep = (pos < capacity).astype(jnp.float32)
        dispatch_k = expert_mask * keep                          # [b, s, k, e]
        cpos = (pos * dispatch_k).sum(-1).astype(jnp.int32)      # [b, s, k]
        cap_onehot = jax.nn.one_hot(cpos, capacity, dtype=jnp.float32)
        # [b, s, e, c]
        dispatch = jnp.einsum("bske,bskc->bsec", dispatch_k, cap_onehot)
        combine = jnp.einsum(
            "bske,bskc,bsk->bsec", dispatch_k, cap_onehot, gate_vals)

        # -- load-balance aux loss (Switch) -------------------------------
        # fraction of ASSIGNMENTS per expert, pre-capacity (expert_mask,
        # not dispatch_k): counting only kept tokens would make dropping
        # lower the loss — the optimizer then prefers collapse-with-drops
        # over balance.  Normalized by s*k so fractions sum to 1; uniform
        # routing gives aux = 1, full collapse ~ e.
        frac_tokens = expert_mask.sum(axis=(1, 2)).mean(axis=0) / (s * k)
        mean_prob = probs.mean(axis=(0, 1))                         # [e]
        aux = e * jnp.sum(frac_tokens * mean_prob)
        self.sow("intermediates", "moe_aux_loss", aux)

        # -- expert computation (all-to-all inserted by GSPMD here) -------
        xin = jnp.einsum(
            "bsec,bsh->ebch", dispatch.astype(cfg.dtype), x)     # [e, b, c, h]
        xin = nn.with_logical_constraint(
            xin, ("expert", "expert_batch", None, "act_embed"))
        gate = jnp.einsum("ebch,ehm->ebcm", xin, w_gate.astype(cfg.dtype))
        up = jnp.einsum("ebch,ehm->ebcm", xin, w_up.astype(cfg.dtype))
        hidden = nn.silu(gate) * up
        hidden = nn.with_logical_constraint(
            hidden, ("expert", "expert_batch", None, "act_mlp"))
        out_e = jnp.einsum("ebcm,emh->ebch", hidden, w_down.astype(cfg.dtype))
        out_e = nn.with_logical_constraint(
            out_e, ("expert", "expert_batch", None, "act_embed"))
        out = jnp.einsum("bsec,ebch->bsh", combine.astype(cfg.dtype), out_e)
        return nn.with_logical_constraint(out, ("batch", "act_seq", "act_embed"))
