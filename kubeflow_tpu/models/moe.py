"""Mixture-of-Experts MLP with expert-parallel dispatch.

The SURVEY §2.5 EP row ("mesh ``expert`` axis + all-to-all dispatch") the
round-1 verdict flagged as missing.  Design is the GShard/Switch dense
dispatch formulated for XLA:

- routing, capacity assignment, and combine are all static-shaped einsums
  over one-hot dispatch tensors — no ragged shapes, no data-dependent
  control flow, so the whole layer jits and shards;
- expert weights are stacked [E, ...] and carry the ``expert`` logical
  axis; grouped activations inside the expert computation carry
  ``expert_batch`` on their batch dim (the ``expert`` mesh axis is spent
  on the expert dim there).  Tokens are batch-sharded over the ``expert``
  axis OUTSIDE the layer (GShard convention: EP groups share DP), so
  GSPMD lowers the dispatch/return reshardings to real all-to-all
  collectives (asserted in tests by inspecting the compiled HLO);
- capacity-factor token dropping bounds the per-expert group size (the
  ragged_all_to_all upgrade path can land later without changing the
  routing contract);
- the Switch load-balancing auxiliary loss is sown into the
  ``intermediates`` collection under ``moe_aux_loss``.

With top-k probabilities renormalized (default) and identical expert
weights, the layer is exactly the dense MLP — the equivalence the unit
tests pin down.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


@jax.custom_vjp
def _permute(x, order, inv):
    """``x[order]`` whose BACKWARD is ``g[inv]`` — a gather, not the
    scatter-add autodiff derives for gather's transpose.  For a bijective
    permutation the two are identical math, but the gather keeps the
    backward pass on the same fast path as the forward (the r4 PERF.md
    "reuse the fwd sort order in bwd" lever: the permutation is
    value-independent given routing, so bwd re-derives nothing)."""
    return jnp.take(x, order, axis=0)


def _permute_fwd(x, order, inv):
    return jnp.take(x, order, axis=0), (order, inv)


def _permute_bwd(res, g):
    order, inv = res
    ft0 = jax.dtypes.float0
    return (jnp.take(g, inv, axis=0),
            jnp.zeros(order.shape, ft0), jnp.zeros(inv.shape, ft0))


_permute.defvjp(_permute_fwd, _permute_bwd)


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gather_tokens(xf, order, inv, k):
    """``xf[order // k]`` (each token row fans out to its k expert
    copies, permuted to expert order) whose BACKWARD is gather-by-inverse
    + a k-way reshape-sum — no scatter-add.  ``inv`` is the caller's
    already-computed ``argsort(order)`` (reused, not re-derived)."""
    return jnp.take(xf, order // k, axis=0)


def _gather_tokens_fwd(xf, order, inv, k):
    return jnp.take(xf, order // k, axis=0), (inv, xf.shape[0])


def _gather_tokens_bwd(k, res, g):
    inv, n = res
    # unsort to (token-major, k) layout, then sum each token's k copies
    g_tok = jnp.take(g, inv, axis=0).reshape(n, k, *g.shape[1:])
    ft0 = jax.dtypes.float0
    return (g_tok.sum(axis=1),
            jnp.zeros(inv.shape, ft0), jnp.zeros(inv.shape, ft0))


_gather_tokens.defvjp(_gather_tokens_fwd, _gather_tokens_bwd)

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .llama import LlamaConfig


class MoeMlp(nn.Module):
    """Drop-in MoE replacement for the Llama gated MLP."""

    cfg: "LlamaConfig"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        e = cfg.moe_experts
        k = cfg.moe_top_k
        b, s, h = x.shape
        m = cfg.intermediate_size

        router = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "expert_dim")),
            (h, e), jnp.float32,
        )
        init = nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal",
                                                in_axis=(1,), out_axis=(2,))
        w_gate = self.param(
            "w_gate",
            nn.with_logical_partitioning(init, ("expert", "embed", "mlp")),
            (e, h, m), cfg.param_dtype,
        )
        w_up = self.param(
            "w_up",
            nn.with_logical_partitioning(init, ("expert", "embed", "mlp")),
            (e, h, m), cfg.param_dtype,
        )
        init_down = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=(1,), out_axis=(2,))
        w_down = self.param(
            "w_down",
            nn.with_logical_partitioning(init_down, ("expert", "mlp", "embed")),
            (e, m, h), cfg.param_dtype,
        )

        # -- routing (f32 for a stable softmax) ---------------------------
        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32), router)
        probs = jax.nn.softmax(logits, axis=-1)                  # [b, s, e]
        gate_vals, idx = jax.lax.top_k(probs, k)                 # [b, s, k]
        if cfg.moe_normalize_topk:
            gate_vals = gate_vals / (
                gate_vals.sum(axis=-1, keepdims=True) + 1e-9)

        # -- load-balance aux loss (Switch) -------------------------------
        # fraction of ASSIGNMENTS per expert, pre-capacity (expert_mask,
        # not dispatch_k): counting only kept tokens would make dropping
        # lower the loss — the optimizer then prefers collapse-with-drops
        # over balance.  Normalized by s*k so fractions sum to 1; uniform
        # routing gives aux = 1, full collapse ~ e.  (In ragged mode
        # nothing drops, but balance still shapes the transport/compute
        # load, so the loss is identical.)
        expert_mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [b, s, k, e]
        frac_tokens = expert_mask.sum(axis=(1, 2)).mean(axis=0) / (s * k)
        mean_prob = probs.mean(axis=(0, 1))                         # [e]
        aux = e * jnp.sum(frac_tokens * mean_prob)
        self.sow("intermediates", "moe_aux_loss", aux)

        if cfg.moe_dispatch == "ragged":
            return _ragged_moe(
                x, idx, gate_vals, w_gate, w_up, w_down, dtype=cfg.dtype,
                compute=cfg.moe_ragged_compute)

        # -- capacity assignment (sequence-major priority) ----------------
        capacity = max(1, int(cfg.moe_capacity_factor * k * s / e))
        flat = expert_mask.transpose(0, 2, 1, 3).reshape(b, k * s, e)
        pos_flat = jnp.cumsum(flat, axis=1) - flat               # queue index
        pos = pos_flat.reshape(b, k, s, e).transpose(0, 2, 1, 3)  # [b, s, k, e]
        keep = (pos < capacity).astype(jnp.float32)
        dispatch_k = expert_mask * keep                          # [b, s, k, e]
        cpos = (pos * dispatch_k).sum(-1).astype(jnp.int32)      # [b, s, k]
        cap_onehot = jax.nn.one_hot(cpos, capacity, dtype=jnp.float32)
        # [b, s, e, c]
        dispatch = jnp.einsum("bske,bskc->bsec", dispatch_k, cap_onehot)
        combine = jnp.einsum(
            "bske,bskc,bsk->bsec", dispatch_k, cap_onehot, gate_vals)

        # -- expert computation (all-to-all inserted by GSPMD here) -------
        xin = jnp.einsum(
            "bsec,bsh->ebch", dispatch.astype(cfg.dtype), x)     # [e, b, c, h]
        xin = nn.with_logical_constraint(
            xin, ("expert", "expert_batch", None, "act_embed"))
        gate = jnp.einsum("ebch,ehm->ebcm", xin, w_gate.astype(cfg.dtype))
        up = jnp.einsum("ebch,ehm->ebcm", xin, w_up.astype(cfg.dtype))
        hidden = nn.silu(gate) * up
        hidden = nn.with_logical_constraint(
            hidden, ("expert", "expert_batch", None, "act_mlp"))
        out_e = jnp.einsum("ebcm,emh->ebch", hidden, w_down.astype(cfg.dtype))
        out_e = nn.with_logical_constraint(
            out_e, ("expert", "expert_batch", None, "act_embed"))
        out = jnp.einsum("bsec,ebch->bsh", combine.astype(cfg.dtype), out_e)
        return nn.with_logical_constraint(out, ("batch", "act_seq", "act_embed"))


def _ragged_moe(x, idx, gates, w_gate, w_up, w_down, *, dtype,
                compute: str = "auto"):
    """Dropless MoE dispatch: sort-by-expert + ``ragged_all_to_all``.

    Every (token, expert) assignment is honored — no capacity factor, no
    drops (SURVEY §2.5 EP row names ragged_all_to_all as the upgrade path
    over capacity dispatch).  Layout per expert-shard device:

    1. repeat each local token per its top-k choices and SORT by
       destination expert — the ragged triple (data, offsets, sizes)
       groups contiguously by destination device;
    2. exchange counts (all_gather of the send-size matrix), then move
       only REAL tokens with ``ragged_all_to_all`` — the dense dispatch
       ships e x capacity slots regardless of load;
    3. run the local experts over the receive buffer — either the Pallas
       grouped-GEMM kernel (ops/grouped_matmul.py: rows re-grouped by
       local expert, block-sparse matmuls touch each row tile once) or
       the masked-scan fallback (per-expert masked matmuls over the full
       buffer: E_local x the useful FLOPs, free only at one expert per
       device), per ``compute`` ("auto" picks the kernel on TPU with
       MXU-tileable shapes);
    4. reverse the transport with the offset matrices transposed, unsort,
       and combine with the gate weights at the source.

    The receive buffer is statically sized at the true worst case (every
    global assignment landing on one device): dropless needs the bound,
    and XLA needs the static shape.  Falls back to a single-device
    sort/compute/unsort (same math, no collectives) when the mesh has no
    ``expert`` axis.
    """
    from jax import lax

    from ..parallel import collectives
    from ..parallel.mesh import current_mesh

    b, s, h = x.shape
    k = idx.shape[-1]
    e = w_gate.shape[0]

    mesh = current_mesh()
    d = (
        mesh.shape["expert"]
        if mesh is not None and "expert" in mesh.axis_names
        else 1
    )
    if e % max(d, 1):
        raise ValueError(f"{e} experts not divisible by expert axis {d}")
    if d > 1 and b % d:
        # tokens batch-shard over the expert axis (GShard convention), so
        # the shard_map transport needs b % d == 0.  Serving admission
        # runs batch-1 prefill rows on EP meshes — fall back to the
        # single-program path there: GSPMD gathers the (tiny-row) expert
        # weights instead, and the math is identical.
        d = 1

    m_dim = w_up.shape[-1]
    e_local_static = e // max(d, 1)
    if compute == "auto":
        # measured on v5e (scripts/moe_bench.py --sweep, PERF.md): with
        # the r4 tile sizes (512,1024,1024) the grouped GEMM runs the
        # E=8 top-2 layer at 13.3 ms vs masked's 43.6 — the r3 "masked
        # until >12 experts/device" threshold was an artifact of the old
        # 128^3 tiling (69.4 ms).  Masked's E_local x full-buffer FLOP
        # overhead loses as soon as there is more than one local expert;
        # at e_local == 1 there is nothing to group.
        use_grouped = (
            jax.default_backend() == "tpu"
            and e_local_static > 1
            and h % 128 == 0 and m_dim % 128 == 0)
    else:
        use_grouped = compute == "grouped"

    def local_compute(recv, lid, valid, wg, wu, wd):
        """Masked per-expert MLP over the receive buffer.

        recv: [B, h]; lid: [B] local expert ids; valid: [B].
        wg/wu/wd: [e_local, ...] this shard's experts.
        """
        def one_expert(acc, inputs):
            w_g, w_u, w_d, le = inputs
            sel = jnp.logical_and(lid == le, valid)
            xin = jnp.where(sel[:, None], recv, 0).astype(dtype)
            hidden = nn.silu(xin @ w_g.astype(dtype)) * (xin @ w_u.astype(dtype))
            out = hidden @ w_d.astype(dtype)
            return acc + jnp.where(sel[:, None], out, 0).astype(acc.dtype), None

        acc0 = jnp.zeros((recv.shape[0], wd.shape[-1]), dtype)
        acc, _ = jax.lax.scan(
            one_expert, acc0,
            (wg, wu, wd, jnp.arange(wg.shape[0], dtype=jnp.int32)))
        return acc

    def grouped_compute(recv, lid, valid, wg, wu, wd, presorted=False):
        """Grouped-GEMM expert MLP: re-group rows by local expert, run the
        block-sparse kernel over contiguous expert ranges, un-group.

        ``presorted``: the d == 1 path hands rows ALREADY globally
        expert-sorted with padding at the end — the second sort and its
        two permutes (fwd gather + unsort gather, and their backward
        twins) are pure tax there and are skipped (PERF.md MoE table,
        the r4 "2 sorts + 2 gathers" lever)."""
        from ..ops.grouped_matmul import grouped_matmul

        e_local = wg.shape[0]
        key = jnp.where(valid, lid, e_local)  # invalid rows sort last
        counts = jax.ops.segment_sum(
            jnp.where(valid, 1, 0), jnp.clip(key, 0, e_local),
            num_segments=e_local + 1)[:e_local]
        offsets = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
        if presorted:
            xs2 = recv
        else:
            order2 = jnp.argsort(key, stable=True)
            inv2 = jnp.argsort(order2)
            xs2 = _permute(recv, order2, inv2)
        g = grouped_matmul(xs2, wg.astype(dtype), offsets)
        u = grouped_matmul(xs2, wu.astype(dtype), offsets)
        hidden = nn.silu(g) * u
        y2 = grouped_matmul(hidden, wd.astype(dtype), offsets)
        if presorted:
            return y2
        return _permute(y2, inv2, order2)

    expert_mlp = grouped_compute if use_grouped else local_compute

    def _pad_rows(arrs, rows):
        """Pad leading dim up to an MXU-tileable multiple (extra rows fall
        outside every group / are invalid, so they produce zeros)."""
        if rows % 128 == 0 or not use_grouped:
            return arrs, rows
        padded = ((rows + 127) // 128) * 128
        return [
            jnp.concatenate(
                [a, jnp.zeros((padded - rows, *a.shape[1:]), a.dtype)])
            for a in arrs
        ], padded

    def shard_body(x_blk, idx_blk, gates_blk, wg, wu, wd):
        """Runs per expert-shard: x_blk [b/d, s, h], wg [e/d, h, m]."""
        bl = x_blk.shape[0]
        n = bl * s
        e_local = wg.shape[0]
        xf = x_blk.reshape(n, h)
        flat_expert = idx_blk.reshape(n * k)
        order = jnp.argsort(flat_expert, stable=True)
        inv = jnp.argsort(order)
        sorted_expert = flat_expert[order]
        # fan-out + permute whose BACKWARD is gathers (no scatter-add)
        xs = _gather_tokens(xf, order, inv, k).astype(dtype)  # [n*k, h]

        if d == 1:
            (xs_p, ids_p), rows = _pad_rows(
                [xs, sorted_expert], n * k)
            valid_p = jnp.arange(rows) < n * k
            ids_m = jnp.where(valid_p, ids_p, e_local)
            if use_grouped:
                # rows are already globally expert-sorted: skip the
                # kernel-side re-sort entirely
                y_buf = grouped_compute(
                    xs_p, ids_m, valid_p, wg, wu, wd, presorted=True)
            else:
                y_buf = expert_mlp(xs_p, ids_m, valid_p, wg, wu, wd)
            y_sorted = y_buf[: n * k]
        else:
            me = lax.axis_index("expert")
            dest_dev = sorted_expert // e_local
            send_sizes = jax.ops.segment_sum(
                jnp.ones_like(dest_dev), dest_dev, num_segments=d
            ).astype(jnp.int32)                            # [D]
            m_mat = lax.all_gather(send_sizes, "expert")   # [D src, D dst]
            # exclusive cumsums: mc over sources (receiver-side layout),
            # mr over destinations (sender-side layout)
            mc = jnp.cumsum(m_mat, axis=0) - m_mat
            mr = jnp.cumsum(m_mat, axis=1) - m_mat
            input_offsets = mr[me]                         # [D]
            output_offsets = mc[me]                        # [D]
            recv_sizes = m_mat[:, me]                      # [D]
            recv_starts = mc[:, me]                        # [D]

            cap = n * k * d  # true worst case: all assignments on one shard
            if use_grouped:
                cap = ((cap + 127) // 128) * 128  # MXU-tileable row count
            buf = jnp.zeros((cap, h), dtype)
            recv = collectives.ragged_all_to_all(
                xs, buf, input_offsets, send_sizes, output_offsets,
                recv_sizes, axis_name="expert")
            ids_buf = jnp.full((cap,), -1, jnp.int32)
            ids = collectives.ragged_all_to_all(
                sorted_expert.astype(jnp.int32), ids_buf, input_offsets,
                send_sizes, output_offsets, recv_sizes, axis_name="expert")

            rows = jnp.arange(cap)
            valid = jnp.logical_and(
                rows[:, None] >= recv_starts[None, :],
                rows[:, None] < (recv_starts + recv_sizes)[None, :],
            ).any(axis=1)
            lid = ids - me * e_local
            y_buf = expert_mlp(recv, lid, valid, wg, wu, wd)

            # reverse transport: each received chunk returns to its source
            # at the source's original sorted position
            back = jnp.zeros((n * k, h), dtype)
            y_sorted = collectives.ragged_all_to_all(
                y_buf, back, recv_starts, recv_sizes, mr[:, me], send_sizes,
                axis_name="expert")

        y_flat = _permute(y_sorted, inv, order).reshape(n, k, h)
        y = (y_flat * gates_blk.reshape(n, k)[..., None].astype(dtype)).sum(1)
        return y.reshape(bl, s, h)

    if d == 1:
        return shard_body(x, idx, gates, w_gate, w_up, w_down)

    from jax.sharding import PartitionSpec as P

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P("expert"), P("expert"), P("expert"), P("expert"),
                  P("expert"), P("expert")),
        out_specs=P("expert"),
        axis_names={"expert"},
        check_vma=False,
    )(x, idx, gates, w_gate, w_up, w_down)
