"""Llama-family decoder transformer, TPU-first.

Capability target: the models the reference's baseline configs train/serve —
"MPIJob Llama-7B multi-host pretrain -> JAXJob on v5e-16 pod slice" and the
SDK ``train()`` LLM fine-tune path [local: BASELINE.json configs 5, SURVEY.md
§3.5].  The reference ships no model code (its Llama runs live in user
containers, Megatron/transformers over NCCL); this is the in-container
runtime layer the TPU rebuild must own (SURVEY.md §1, closing paragraph).

TPU-first choices:

- bfloat16 activations, float32 params/accumulators; RMSNorm + softmax in
  float32 (MXU-friendly matmuls, stable reductions).
- ``nn.scan`` over the layer stack: one traced block, O(1) compile time in
  depth; ``nn.remat`` with the ``dots_with_no_batch_dims_saveable`` policy
  trades HBM for recompute exactly where the scaling playbook says to.
- every parameter and residual activation carries *logical* axis names
  (kubeflow_tpu.parallel.sharding) so the same code runs DP / FSDP / TP / SP
  by mesh choice alone; attention heads are grouped (GQA) and head/mlp dims
  shard on the ``model`` axis, embed dim on ``fsdp``, sequence on ``seq``.
- static shapes everywhere; causal masking via lax primitives, no Python
  control flow under jit.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel import ring_attention as ringlib

Dtype = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    remat: bool = True
    #: what the rematerializer keeps across the backward pass:
    #: "dots" saves every matmul output (fastest recompute; ~2.7 GB/chip of
    #: saved ffn activations at 7B/seq-4096 — fine when HBM is ample);
    #: "nothing" saves only the per-layer carry (full recompute, the
    #: standard large-model setting — what lets 7B fit v5e's 16 GiB).
    remat_policy: str = "dots"
    scan_layers: bool = True
    #: "dense" = full causal attention (XLA-fused; fastest <= ~2k seq);
    #: "flash" = our Pallas flash kernel (wins at long seq: measured 1.4x
    #: over dense and 1.8x over jax's reference flash kernel at seq 4096
    #: on v5e); "ring" = blockwise ring attention over the mesh's ``seq``
    #: axis for sequence parallelism (SURVEY §5).
    attention_impl: str = "dense"
    tie_embeddings: bool = False
    #: Mixture-of-Experts MLP (models/moe.py): 0 = dense MLP; >0 = number
    #: of experts with top-k routing and expert-axis dispatch (SURVEY §2.5
    #: EP row).  With normalize_topk and identical expert weights the MoE
    #: layer equals the dense MLP exactly (tested).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_normalize_topk: bool = True
    #: "dense" = GShard capacity-factor dispatch (static one-hot einsums;
    #: tokens past capacity are DROPPED); "ragged" = dropless dispatch —
    #: tokens sort by destination expert and only real tokens cross the
    #: wire via ragged_all_to_all (SURVEY §2.5 EP row), zero drops at any
    #: load skew.
    moe_dispatch: str = "dense"
    #: local expert compute under ragged dispatch: "masked" = per-expert
    #: masked matmuls over the whole buffer (E_local x the useful FLOPs —
    #: free only at one expert/device); "grouped" = the Pallas grouped-GEMM
    #: kernel (ops/grouped_matmul.py, block-sparse over expert row ranges);
    #: "auto" = grouped on TPU when shapes are MXU-tileable, else masked.
    moe_ragged_compute: str = "auto"
    #: token-embedding lookup: False = gather from an explicitly
    #: replicated table (default; one ICI all-gather per step); True =
    #: one-hot matmul, no table gather (prefer under heavy vocab/TP
    #: sharding where replicating the table is the bottleneck)
    embed_one_hot: bool = False
    #: SERVING-ONLY int8 quantization (quantize_for_serving).  Decode is
    #: HBM-bound — every token streams the weights (and the attended KV)
    #: from HBM — so int8 storage halves the decode roofline's byte bill
    #: and doubles KV slots per GiB on v5e (SURVEY §2.2, the
    #: vLLM/Triton quantization family; r4 verdict missing #3).
    #: quant_weights: projection kernels + unembedding stored int8 with
    #: per-output-channel scales, applied to the matmul OUTPUT so the
    #: kernel feeds the dot as int8 bytes (no dequantized copy lives in
    #: HBM as a parameter).  quant_kv: KV cache stored int8 with
    #: per-(position, kv_head) scales, dequantized into the f32 attend
    #: math the decode path already does.
    quant_weights: bool = False
    quant_kv: bool = False
    #: LoRA adapters (SURVEY §3.5 — the reference's train() packages
    #: transformers/peft fine-tuning).  rank > 0 adds low-rank deltas
    #: ``y += (x @ A) @ B * (alpha/rank)`` to the ``lora_targets``
    #: projections; the trainer freezes everything else (optax
    #: multi_transform), so a 7B fine-tune trains <1% of the params and
    #: publishes MB-scale adapter snapshots (save_adapter) instead of
    #: full-size ones.  B initializes to zeros: step 0 is exactly the
    #: base model.
    lora_rank: int = 0
    lora_alpha: float = 0.0  # 0 -> alpha = rank (scale 1.0)
    lora_targets: tuple[str, ...] = ("wq", "wv")

    @property
    def lora_scale(self) -> float:
        return (self.lora_alpha or float(self.lora_rank)) / max(
            self.lora_rank, 1)

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    def __post_init__(self):
        if self.num_heads % self.num_kv_heads:
            raise ValueError("num_heads must be a multiple of num_kv_heads")
        if self.attention_impl not in ("dense", "flash", "ring"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.remat_policy not in ("dots", "nothing"):
            raise ValueError(f"unknown remat_policy {self.remat_policy!r}")
        if self.moe_dispatch not in ("dense", "ragged"):
            raise ValueError(f"unknown moe_dispatch {self.moe_dispatch!r}")
        if self.moe_ragged_compute not in ("auto", "masked", "grouped"):
            raise ValueError(
                f"unknown moe_ragged_compute {self.moe_ragged_compute!r}")


# -- presets ----------------------------------------------------------------

def _preset(defaults: dict, overrides: dict) -> LlamaConfig:
    return LlamaConfig(**{**defaults, **overrides})


def tiny(**kw) -> LlamaConfig:
    """Test/smoke config: runs on one CPU device in <1s."""
    return _preset(
        dict(
            vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
            num_heads=4, num_kv_heads=2, head_dim=16, max_seq_len=128,
            dtype=jnp.float32, remat=False,
        ),
        kw,
    )


def llama2_7b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_1b(**kw) -> LlamaConfig:
    """~1.19B — the largest config that trains on ONE 16 GiB v5e chip with
    an f32-param Adafactor setup (full-recompute remat + flash attention +
    gradient accumulation; see PERF.md).  Shape follows the 7B recipe at
    half width: 21L / 2048h / 16 heads / 5504 ffn."""
    return _preset(
        dict(hidden_size=2048, intermediate_size=5504, num_layers=21,
             num_heads=16, num_kv_heads=16, max_seq_len=2048,
             remat_policy="nothing", attention_impl="flash"),
        kw,
    )


def llama2_13b(**kw) -> LlamaConfig:
    return _preset(
        dict(hidden_size=5120, intermediate_size=13824, num_layers=40,
             num_heads=40, num_kv_heads=40),
        kw,
    )


def llama2_70b(**kw) -> LlamaConfig:
    return _preset(
        dict(hidden_size=8192, intermediate_size=28672, num_layers=80,
             num_heads=64, num_kv_heads=8),
        kw,
    )


def llama3_8b(**kw) -> LlamaConfig:
    return _preset(
        dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
             num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
             rope_theta=500000.0),
        kw,
    )


PRESETS = {
    "tiny": tiny,
    "llama2-7b": llama2_7b,
    "llama2-13b": llama2_13b,
    "llama2-70b": llama2_70b,
    "llama3-8b": llama3_8b,
}


# -- building blocks --------------------------------------------------------


class RMSNorm(nn.Module):
    eps: float
    dtype: Dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param(
            "scale", nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (x.shape[-1],), jnp.float32,
        )
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.eps)
        return (y * scale).astype(self.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding; x: [..., seq, heads, head_dim]."""
    head_dim = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, head_dim // 2, dtype=jnp.float32) / (head_dim // 2))
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., :, None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class Einsum(nn.Module):
    """Einsum layer with an explicitly-shaped, logically-named kernel.

    flax's DenseGeneral flattens its kernel to 2D at creation, which breaks
    >2-axis logical metadata the moment a mesh context makes boxing apply
    real constraints — so parameter shapes are owned here, not by flax.
    """

    subscript: str
    shape: tuple[int, ...]
    logical_axes: tuple[str, ...]
    dtype: Dtype
    param_dtype: Dtype
    in_axes: tuple[int, ...] = (0,)   # kernel dims contracted with the input
    #: int8 weight-only quantization (serving): the kernel is stored int8
    #: and a per-OUTPUT-channel scale multiplies the matmul result —
    #: y = (x @ w_q) * s factors exactly because scales vary only over
    #: non-contracted dims (which every subscript here keeps trailing in
    #: the output).  The dot reads int8 bytes from HBM; no bf16 weight
    #: copy exists as a parameter.
    quant: bool = False
    #: LoRA: rank > 0 adds ``lora_a`` [in..., r] / ``lora_b`` [r, out...]
    #: and y += ((x @ a) @ b) * lora_scale.  Kept as two rank-r matmuls —
    #: never materialized into the kernel during training (that would
    #: erase the memory/FLOP economy adapters exist for).
    lora_rank: int = 0
    lora_scale: float = 1.0

    def _lora_delta(self, x: jax.Array, dtype) -> jax.Array:
        shape = self.shape
        out_axes = tuple(
            i for i in range(len(shape)) if i not in self.in_axes)
        x_sub, rest = self.subscript.split(",")
        k_sub, out_sub = rest.split("->")
        used = set(self.subscript) - {",", "-", ">"}
        r_ch = next(c for c in "zyxwvutq" if c not in used)
        in_letters = "".join(k_sub[i] for i in self.in_axes)
        out_letters = "".join(k_sub[i] for i in out_axes)
        batch_letters = "".join(c for c in out_sub if c not in out_letters)
        a = self.param(
            "lora_a",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                tuple(self.logical_axes[i] for i in self.in_axes) + ("lora",)),
            tuple(shape[i] for i in self.in_axes) + (self.lora_rank,),
            jnp.float32,
        )
        b = self.param(
            "lora_b",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(),
                ("lora",) + tuple(self.logical_axes[i] for i in out_axes)),
            (self.lora_rank,) + tuple(shape[i] for i in out_axes),
            jnp.float32,
        )
        mid = jnp.einsum(
            f"{x_sub},{in_letters}{r_ch}->{batch_letters}{r_ch}",
            x, a.astype(dtype))
        return jnp.einsum(
            f"{batch_letters}{r_ch},{r_ch}{out_letters}->{out_sub}",
            mid, b.astype(dtype)) * jnp.asarray(self.lora_scale, dtype)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        out_axes = tuple(i for i in range(len(self.shape)) if i not in self.in_axes)
        if self.quant:
            kernel = self.param(
                "kernel",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), self.logical_axes),
                self.shape, jnp.int8,
            )
            scale = self.param(
                "scale",
                nn.with_logical_partitioning(
                    nn.initializers.ones_init(),
                    tuple(self.logical_axes[i] for i in out_axes)),
                tuple(self.shape[i] for i in out_axes), jnp.float32,
            )
            y = jnp.einsum(self.subscript, x, kernel.astype(self.dtype))
            y = y * scale.astype(self.dtype)
        else:
            init = nn.initializers.variance_scaling(
                1.0, "fan_in", "truncated_normal",
                in_axis=self.in_axes, out_axis=out_axes)
            kernel = self.param(
                "kernel",
                nn.with_logical_partitioning(init, self.logical_axes),
                self.shape, self.param_dtype,
            )
            y = jnp.einsum(self.subscript, x, kernel.astype(self.dtype))
        if self.lora_rank > 0:
            y = y + self._lora_delta(x, self.dtype)
        return y


class Attention(nn.Module):
    cfg: LlamaConfig
    decode: bool = False
    #: decode-time attention window: attend only over cache slots
    #: [0, decode_attend_len) instead of all max_seq_len — the KV read is
    #: the decode step's HBM bill, and short live fronts shouldn't pay
    #: for the whole buffer.  Callers guarantee every live position is
    #: below it; writes still target the full cache.
    decode_attend_len: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 prefix=None, cache_positions=None) -> jax.Array:
        cfg = self.cfg

        def proj(*args, name: str, **kw):
            return Einsum(
                *args, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                quant=cfg.quant_weights,
                lora_rank=(cfg.lora_rank if name in cfg.lora_targets else 0),
                lora_scale=cfg.lora_scale, name=name, **kw)

        h_dim = x.shape[-1]
        q = proj(
            "bse,ehd->bshd", (h_dim, cfg.num_heads, cfg.head_dim),
            ("embed", "heads", "head_dim"), name="wq")(x)
        k = proj(
            "bse,ekd->bskd", (h_dim, cfg.num_kv_heads, cfg.head_dim),
            ("embed", "kv_heads", "head_dim"), name="wk")(x)
        v = proj(
            "bse,ekd->bskd", (h_dim, cfg.num_kv_heads, cfg.head_dim),
            ("embed", "kv_heads", "head_dim"), name="wv")(x)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        q = nn.with_logical_constraint(q, ("batch", "act_seq", "act_heads", "head_dim"))
        k = nn.with_logical_constraint(k, ("batch", "act_seq", "act_kv_heads", "head_dim"))
        v = nn.with_logical_constraint(v, ("batch", "act_seq", "act_kv_heads", "head_dim"))

        if self.decode:
            out = self._decode_attend(q, k, v, positions,
                                      prefix=prefix,
                                      cache_positions=cache_positions)
        elif cfg.attention_impl == "ring":
            out = ringlib.ring_attention(
                q, k, v, axis_name="seq", q_per_kv=cfg.q_per_kv
            )
        elif cfg.attention_impl == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, q_per_kv=cfg.q_per_kv)
        else:
            out = _causal_attention(q, k, v, cfg.q_per_kv)
        out = nn.with_logical_constraint(out, ("batch", "act_seq", "act_heads", "head_dim"))
        return proj(
            "bshd,hde->bse", (cfg.num_heads, cfg.head_dim, h_dim),
            ("heads", "head_dim", "embed"), in_axes=(0, 1), name="wo")(out)

    def _decode_attend(self, q, k, v, positions, prefix=None,
                       cache_positions=None):
        """Decode against a mutable KV cache with PER-ROW positions.

        Flax 'cache' collection: cached_key/value are [batch, max_seq, kv,
        hd].  ``positions`` [batch, sc] gives each incoming token's global
        position per row, so slot index == global position: writes scatter
        per row (touching only the written slots) and a query at row
        position p attends exactly slots <= p.  This is what
        makes RAGGED batches sound: rows pad to a shared bucket, pad-slot
        junk sits at positions greater than the row's live front, where the
        mask hides it until a real decode write overwrites it.

        SHARED-PREFIX mode (serving/prefix_sharing.py): ``prefix`` =
        (pk, pv, plen) — per-row KV of an IMMUTABLE shared segment
        holding global positions [0, plen), already roped at those
        positions.  The row's own cache then stores only its suffix at
        SLOT-LOCAL index ``cache_positions = positions - plen`` (rope and
        causal order stay global).  Attention is ONE softmax over
        [segment ; private] — logits concatenate along the key axis, so
        the math is exactly full-sequence attention, not an approximate
        merge.
        """
        cfg = self.cfg
        batch, sc = q.shape[0], q.shape[1]
        if cache_positions is None:
            cache_positions = positions
        cache_positions = jnp.broadcast_to(cache_positions, (batch, sc))
        kv_dtype = jnp.int8 if cfg.quant_kv else cfg.dtype
        cached_k = self.variable(
            "cache", "cached_key",
            jnp.zeros, (batch, cfg.max_seq_len, cfg.num_kv_heads, cfg.head_dim), kv_dtype)
        cached_v = self.variable(
            "cache", "cached_value",
            jnp.zeros, (batch, cfg.max_seq_len, cfg.num_kv_heads, cfg.head_dim), kv_dtype)
        idx = self.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
        positions = jnp.broadcast_to(positions, (batch, sc))
        # per-row scatter write: touches only the written slots (a one-hot
        # matmul alternative rewrites the entire cache every step — O(S)
        # HBM traffic per decoded token)
        rows = jnp.arange(batch, dtype=jnp.int32)[:, None]
        if cfg.quant_kv:
            # int8 KV: per-(position, kv_head) absmax scales in parallel
            # buffers — the attended read streams half the bytes, which
            # IS the decode step's HBM bill (quant_kv docstring).
            # LAYOUT [batch, kv_heads, seq], seq MINOR: with seq trailing
            # the 128-lane tile rides the long dim; the "natural"
            # [batch, seq, kv_heads] puts a tiny kv dim (2 at 7B/TP=16)
            # in the lanes and XLA pads the f32 buffer up to 64x (4 GB of
            # padding per pool, measured in the AOT sweep).  Bonus: the
            # kv dim lands at ndim-2, the SAME slot the cache tensors
            # shard on (serving/sharded.py keeps one uniform rule).
            k_scale = self.variable(
                "cache", "cached_key_scale",
                jnp.zeros, (batch, cfg.num_kv_heads, cfg.max_seq_len),
                jnp.float32)
            v_scale = self.variable(
                "cache", "cached_value_scale",
                jnp.zeros, (batch, cfg.num_kv_heads, cfg.max_seq_len),
                jnp.float32)

            def quantize(x):
                s = jnp.maximum(
                    jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1), 1e-8
                ) / 127.0
                q8 = jnp.clip(
                    jnp.round(x.astype(jnp.float32) / s[..., None]),
                    -127, 127).astype(jnp.int8)
                return q8, s  # s: [batch, sc, kv_heads]

            kq, ks = quantize(k)
            vq, vs = quantize(v)
            cached_k.value = cached_k.value.at[rows, cache_positions].set(
                kq, mode="drop")
            cached_v.value = cached_v.value.at[rows, cache_positions].set(
                vq, mode="drop")
            heads_ix = jnp.arange(cfg.num_kv_heads, dtype=jnp.int32)[
                None, None, :]
            k_scale.value = k_scale.value.at[
                rows[:, :, None], heads_ix,
                cache_positions[:, :, None]].set(ks, mode="drop")
            v_scale.value = v_scale.value.at[
                rows[:, :, None], heads_ix,
                cache_positions[:, :, None]].set(vs, mode="drop")
        else:
            cached_k.value = cached_k.value.at[rows, cache_positions].set(
                k.astype(cfg.dtype), mode="drop")
            cached_v.value = cached_v.value.at[rows, cache_positions].set(
                v.astype(cfg.dtype), mode="drop")
        idx.value = idx.value + sc  # legacy cursor, informational only
        # static slice to the live front: the decode step streams the
        # whole attended cache from HBM every token, so a 192-token
        # conversation must not read a 4096-slot buffer
        attend = self.decode_attend_len or cfg.max_seq_len
        if cfg.quant_kv:
            kf = (cached_k.value[:, :attend].astype(jnp.float32)
                  * k_scale.value[:, :, :attend].transpose(0, 2, 1)[
                      ..., None])
            vf = (cached_v.value[:, :attend].astype(jnp.float32)
                  * v_scale.value[:, :, :attend].transpose(0, 2, 1)[
                      ..., None])
        else:
            kf = cached_k.value[:, :attend]
            vf = cached_v.value[:, :attend]
        qh = q.reshape(batch, sc, cfg.num_kv_heads, cfg.q_per_kv, cfg.head_dim)
        qf = qh.astype(jnp.float32)
        logits = jnp.einsum("bqkgh,bskh->bkgqs", qf, kf.astype(jnp.float32))
        # per-row per-query causal mask over the PRIVATE cache: slot-local
        # index i holds global position plen + i, so i <= local_pos is
        # exactly global causality
        valid = (jnp.arange(attend)[None, None, :]
                 <= cache_positions[:, :, None])  # [b, q, s]
        logits = jnp.where(valid[:, None, None, :, :], logits, -1e30)
        if prefix is not None:
            pk, pv, plen = prefix
            pkf = pk.astype(jnp.float32)
            pvf = pv.astype(jnp.float32)
            plogits = jnp.einsum("bqkgh,bskh->bkgqs", qf, pkf)
            # the whole live prefix precedes every query position
            pvalid = (jnp.arange(pk.shape[1])[None, :]
                      < plen[:, None])  # [b, sp]
            plogits = jnp.where(
                pvalid[:, None, None, None, :], plogits, -1e30)
            # ONE softmax over [segment ; private] — exact full-sequence
            # attention, keys merely live in two buffers
            cat = jnp.concatenate([plogits, logits], axis=-1)
            cat = cat / jnp.sqrt(cfg.head_dim).astype(jnp.float32)
            probs = jax.nn.softmax(cat, axis=-1)
            sp = pk.shape[1]
            out = (jnp.einsum("bkgqs,bskh->bqkgh", probs[..., :sp], pvf)
                   + jnp.einsum("bkgqs,bskh->bqkgh", probs[..., sp:],
                                vf.astype(jnp.float32)))
            return out.reshape(
                batch, sc, cfg.num_heads, cfg.head_dim).astype(cfg.dtype)
        logits = logits / jnp.sqrt(cfg.head_dim).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bkgqs,bskh->bqkgh", probs, vf.astype(jnp.float32))
        return out.reshape(batch, sc, cfg.num_heads, cfg.head_dim).astype(cfg.dtype)


def _causal_attention(q, k, v, q_per_kv: int) -> jax.Array:
    """Dense causal GQA attention; XLA fuses mask+softmax into the matmuls.

    q: [b, s, h, d]; k,v: [b, s, kv, d] with h = kv * q_per_kv.
    """
    b, s, h, d = q.shape
    kv = k.shape[2]
    qh = q.reshape(b, s, kv, q_per_kv, d).astype(jnp.float32)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qh, k.astype(jnp.float32))
    logits = logits / jnp.sqrt(d).astype(jnp.float32)
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    logits = jnp.where(causal[None, None, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, s, h, d).astype(q.dtype)


class Mlp(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg

        def proj(*args, name: str, **kw):
            return Einsum(
                *args, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                quant=cfg.quant_weights,
                lora_rank=(cfg.lora_rank if name in cfg.lora_targets else 0),
                lora_scale=cfg.lora_scale, name=name, **kw)

        h_dim = x.shape[-1]
        gate = proj(
            "bse,em->bsm", (h_dim, cfg.intermediate_size),
            ("embed", "mlp"), name="w_gate")(x)
        up = proj(
            "bse,em->bsm", (h_dim, cfg.intermediate_size),
            ("embed", "mlp"), name="w_up")(x)
        h = nn.silu(gate) * up
        h = nn.with_logical_constraint(h, ("batch", "act_seq", "act_mlp"))
        return proj(
            "bsm,me->bse", (cfg.intermediate_size, h_dim),
            ("mlp", "embed"), name="w_down")(h)


def remat_policy(cfg: LlamaConfig):
    """Checkpoint policy object for ``cfg.remat_policy`` (None = save
    nothing: jax.checkpoint's default full-recompute behavior)."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "nothing":
        return None
    raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")


class Block(nn.Module):
    cfg: LlamaConfig
    decode: bool = False
    decode_attend_len: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 prefix=None, cache_positions=None):
        cfg = self.cfg
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="attn_norm")(x)
        x = x + Attention(cfg, self.decode, self.decode_attend_len,
                          name="attn")(h, positions, prefix,
                                       cache_positions)
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="mlp_norm")(x)
        if cfg.moe_experts > 0:
            from .moe import MoeMlp

            x = x + MoeMlp(cfg, name="mlp")(h)
        else:
            x = x + Mlp(cfg, name="mlp")(h)
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))
        return x


class _ScanBlock(nn.Module):
    """Block wrapped for nn.scan: carry = activations, no per-layer output."""

    cfg: LlamaConfig
    decode: bool = False
    decode_attend_len: Optional[int] = None

    @nn.compact
    def __call__(self, x, positions):
        return Block(self.cfg, self.decode, self.decode_attend_len,
                     name="block")(x, positions), None


class _ScanBlockPrefix(nn.Module):
    """_ScanBlock variant with shared-prefix args: pk/pv scan over their
    leading LAYER axis (each block attends its own layer's segment KV);
    plen/cache_positions broadcast.  Same "block" module name, so the
    param tree is identical to _ScanBlock's — one set of weights serves
    both call shapes."""

    cfg: LlamaConfig
    decode: bool = False
    decode_attend_len: Optional[int] = None

    @nn.compact
    def __call__(self, x, positions, pk, pv, plen, cache_positions):
        return Block(self.cfg, self.decode, self.decode_attend_len,
                     name="block")(
            x, positions, (pk, pv, plen), cache_positions), None


class Embedder(nn.Module):
    """Token embedding lookup — standalone so the pipeline executor can run
    it outside the staged block stack (parallel/pipeline.py).  setup-style
    so both ``__call__`` and ``table`` (tie_embeddings) can touch the param.
    """

    cfg: LlamaConfig

    def setup(self):
        self.embedding = self.param(
            "embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")),
            (self.cfg.vocab_size, self.cfg.hidden_size), self.cfg.param_dtype,
        )

    def __call__(self, tokens: jax.Array) -> jax.Array:
        table = self.embedding.astype(self.cfg.dtype)
        # deliberate, mode-independent OOB semantics: clamp like the
        # pre-r3 `table[tokens]` gather did (jnp.take would NaN-fill,
        # one-hot would zero-fill — two silent divergences otherwise)
        tokens = jnp.clip(tokens, 0, self.cfg.vocab_size - 1)
        if self.cfg.embed_one_hot:
            # one-hot matmul: contraction over the sharded vocab dim turns
            # into a clean psum — no table gather at all.  Costs b*s*v*e
            # MACs on the MXU; right when vocab-sharding is heavy (big TP)
            oh = jax.nn.one_hot(tokens, self.cfg.vocab_size, dtype=self.cfg.dtype)
            x = jnp.einsum("bsv,ve->bse", oh, table)
        else:
            # explicitly replicate the table before the lookup: SPMD would
            # otherwise do the same replication "involuntarily" per its
            # last-resort warning, but through an inefficient reshard of
            # the gather result.  64MB bf16 at 32k vocab — an ICI
            # all-gather, amortized across the whole batch's lookups.
            table = nn.with_logical_constraint(table, (None, None))
            x = jnp.take(table, tokens, axis=0, mode="clip")
        return nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))

    def table(self) -> jax.Array:
        """The raw embedding table (for tie_embeddings heads)."""
        return self.embedding


class Head(nn.Module):
    """Final norm + unembedding.  ``embed_table`` feeds tie_embeddings."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, embed_table: Optional[jax.Array] = None
    ) -> jax.Array:
        cfg = self.cfg
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="final_norm")(x)
        # The unembedding matmul runs in the activation dtype (bf16 on TPU:
        # full MXU rate, half the HBM of f32 logits); the loss fn upcasts
        # logits to f32 for the softmax/cross-entropy reduction.
        if cfg.tie_embeddings:
            if embed_table is None:
                raise ValueError("tie_embeddings Head needs the embed table")
            logits = jnp.einsum("bse,ve->bsv", x, embed_table.astype(cfg.dtype))
        elif cfg.quant_weights:
            unembed = self.param(
                "unembedding",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("embed", "vocab")),
                (cfg.hidden_size, cfg.vocab_size), jnp.int8,
            )
            uscale = self.param(
                "unembedding_scale",
                nn.with_logical_partitioning(
                    nn.initializers.ones_init(), ("vocab",)),
                (cfg.vocab_size,), jnp.float32,
            )
            logits = jnp.einsum(
                "bse,ev->bsv", x, unembed.astype(cfg.dtype)
            ) * uscale.astype(cfg.dtype)
        else:
            unembed = self.param(
                "unembedding",
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02), ("embed", "vocab")),
                (cfg.hidden_size, cfg.vocab_size), cfg.param_dtype,
            )
            logits = jnp.einsum("bse,ev->bsv", x, unembed.astype(cfg.dtype))
        return nn.with_logical_constraint(logits, ("batch", "act_seq", "act_vocab"))


class Llama(nn.Module):
    cfg: LlamaConfig
    #: decode-time attention window (see Attention.decode_attend_len);
    #: serving runtimes compile one program per window bucket so short
    #: conversations read KV proportional to their live front
    decode_attend_len: Optional[int] = None

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        positions: Optional[jax.Array] = None,
        *,
        decode: bool = False,
        prefix=None,
        cache_positions=None,
    ) -> jax.Array:
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(tokens.shape[-1])[None, :]
        embedder = Embedder(cfg, name="embedder")
        x = embedder(tokens)

        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(
                Block, policy=remat_policy(cfg), prevent_cse=False)
        if cfg.scan_layers and prefix is not None:
            # shared-prefix decode: pk/pv carry a leading layer axis and
            # scan WITH the blocks; everything else broadcasts
            pk, pv, plen = prefix
            if cache_positions is None:
                cache_positions = positions
            x, _ = nn.scan(
                _ScanBlockPrefix,
                variable_axes={"params": 0, "cache": 0, "intermediates": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0, 0, nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, decode, self.decode_attend_len, name="layers")(
                x, positions, pk, pv, plen, cache_positions)
        elif cfg.scan_layers:
            scan_cls = _ScanBlock
            if cfg.remat:
                scan_cls = nn.remat(
                    _ScanBlock, policy=remat_policy(cfg), prevent_cse=False)
            x, _ = nn.scan(
                scan_cls,
                # intermediates: per-layer sown values (e.g. moe_aux_loss)
                # stack along a leading layer axis
                variable_axes={"params": 0, "cache": 0, "intermediates": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, decode, self.decode_attend_len, name="layers")(x, positions)
        else:
            for i in range(cfg.num_layers):
                lp = None
                if prefix is not None:
                    pk, pv, plen = prefix
                    lp = (pk[i], pv[i], plen)
                x = block_cls(cfg, decode, self.decode_attend_len,
                              name=f"layer_{i}")(x, positions, lp,
                                                 cache_positions)

        table = embedder.table() if cfg.tie_embeddings else None
        return Head(cfg, name="head")(x, table)


def block_apply_with_aux(cfg: LlamaConfig, positions):
    """``block_apply(lp, h) -> (h, aux)`` for the pipeline executors:
    one Block forward that also surfaces the layer's sown ``moe_aux_loss``
    (0 for dense layers) — how the Switch balancing loss flows through
    GPipe/1F1B, where the single-mesh ``mutable=["intermediates"]``
    collection cannot reach inside the schedule."""

    def apply(layer_params, h):
        y, mut = Block(cfg).apply(
            {"params": layer_params}, h, positions,
            mutable=["intermediates"])
        leaves = [
            jnp.sum(v.astype(jnp.float32))
            for path, v in _flatten(mut.get("intermediates", {}))
            if "moe_aux_loss" in path
        ]
        aux = sum(leaves, jnp.zeros((), jnp.float32))
        return y, aux

    def _flatten(tree, prefix=()):
        if isinstance(tree, dict):
            for k, v in tree.items():
                yield from _flatten(v, prefix + (k,))
        elif isinstance(tree, (list, tuple)):
            for v in tree:
                yield from _flatten(v, prefix)
        else:
            yield prefix, tree

    return apply


def pipelined_apply(
    cfg: LlamaConfig,
    params: Any,
    tokens: jax.Array,
    *,
    mesh=None,
    num_microbatches: Optional[int] = None,
    with_aux: bool = False,
):
    """Forward pass with the block stack run as a GPipe microbatch pipeline.

    Embedding and head run data-parallel on every device (they are cheap
    and replicated over the ``pipeline`` axis); the scanned layer stack —
    whose params are already stage-sharded by the ``("layers", "pipeline")``
    rule — executes through ``parallel.pipeline.gpipe``.  Numerically
    identical to ``Llama.__call__`` (same blocks, same order), so loss
    trajectories match the single-mesh run.

    ``with_aux=True`` returns ``(logits, aux_mean)`` where ``aux_mean`` is
    the per-layer-mean MoE load-balancing loss (matching the trainer's
    single-mesh ``_sum_aux_losses`` normalization: sum over layers and
    microbatches / (num_layers * num_microbatches)).
    """
    from ..parallel import pipeline as pipelib

    if not cfg.scan_layers:
        raise ValueError("pipelined_apply requires scan_layers=True "
                         "(stage-stacked params)")
    positions = jnp.arange(tokens.shape[-1])[None, :]
    x = Embedder(cfg).apply({"params": params["embedder"]}, tokens)

    if with_aux:
        block_apply = block_apply_with_aux(cfg, positions)
    else:
        def block_apply(layer_params, h):
            return Block(cfg).apply({"params": layer_params}, h, positions)

    out = pipelib.gpipe(
        block_apply, params["layers"]["block"], x,
        mesh=mesh, num_microbatches=num_microbatches, remat=cfg.remat,
        with_aux=with_aux,
    )
    table = params["embedder"]["embedding"] if cfg.tie_embeddings else None
    if with_aux:
        x, aux_sum = out
        # normalization must match how many passes actually contributed:
        # the degree-1 fallback runs ONE pass regardless of the requested
        # microbatch count (dividing by it would silently under-weight
        # the balancing loss)
        deg = pipelib.pipeline_degree(mesh or pipelib.current_mesh())
        m = (num_microbatches or deg) if deg > 1 else 1
        aux = aux_sum / (cfg.num_layers * m)
        return Head(cfg).apply({"params": params["head"]}, x, table), aux
    return Head(cfg).apply({"params": params["head"]}, out, table)


def save_pretrained(path: str, cfg: LlamaConfig, params: Any) -> None:
    """Write an HF-layout snapshot: ``config.json`` + ``weights.msgpack``
    (flax serialization) — the same layout ``models/bert.py`` uses and
    what ``hf://`` snapshots under $KFT_HF_HOME contain.  This is the
    publish side of the north-star fine-tune UX [upstream:
    training-operator -> sdk train() v1.9 LLM path, SURVEY.md §3.5]:
    ``load_pretrained`` (or ``KFT_INIT_FROM``) reads it back."""
    import json
    import os

    from flax import serialization
    from flax import linen as fnn

    os.makedirs(path, exist_ok=True)
    d = dataclasses.asdict(cfg)
    d["dtype"] = jnp.dtype(cfg.dtype).name
    d["param_dtype"] = jnp.dtype(cfg.param_dtype).name
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(d, f, indent=1)
    with open(os.path.join(path, "weights.msgpack"), "wb") as f:
        f.write(serialization.msgpack_serialize(
            jax.tree.map(jax.device_get, fnn.meta.unbox(params))))


def load_pretrained_config(path: str) -> LlamaConfig:
    """The snapshot's architecture, without touching the weights (cheap on
    every process; weight loading happens once per host at init).

    Auto-detects the layout: this repo's ``save_pretrained`` dataclass
    config, OR a stock transformers snapshot (``model_type: llama`` +
    safetensors — models/hf_checkpoint.py), so every call site
    (KFT_INIT_FROM, storage_path serving, TrainingClient.train(model=...))
    accepts published Llama checkpoints unchanged."""
    import json
    import os

    from . import hf_checkpoint

    if hf_checkpoint.is_hf_snapshot(path):
        return hf_checkpoint.config_from_hf(path)
    with open(os.path.join(path, "config.json")) as f:
        d = json.load(f)
    d["dtype"] = jnp.dtype(d["dtype"])
    d["param_dtype"] = jnp.dtype(d["param_dtype"])
    if "lora_targets" in d:
        d["lora_targets"] = tuple(d["lora_targets"])  # json round-trip
    return LlamaConfig(**d)


def load_pretrained(path: str) -> tuple[LlamaConfig, Any]:
    """Read a snapshot written by ``save_pretrained`` — or a stock
    transformers-layout safetensors snapshot (auto-detected) — into
    (config, params): plain host arrays, ready for ``jax.device_put``
    onto any mesh's shardings."""
    import os

    from flax import serialization

    from . import hf_checkpoint

    if hf_checkpoint.is_hf_snapshot(path):
        return hf_checkpoint.load_hf_llama(path)
    cfg = load_pretrained_config(path)
    with open(os.path.join(path, "weights.msgpack"), "rb") as f:
        params = serialization.msgpack_restore(f.read())
    return cfg, params


def is_lora_path(path: tuple) -> bool:
    """True for adapter leaves (flattened-dict path tuples)."""
    return any(p in ("lora_a", "lora_b") for p in path)


def split_lora(params: Any) -> tuple[Any, Any]:
    """(base, adapters) as flattened-path dicts reassembled into trees —
    the partition the trainer's freeze mask, adapter-only checkpoints and
    ``save_adapter`` all share."""
    from flax import traverse_util

    flat = traverse_util.flatten_dict(params)
    base = {k: v for k, v in flat.items() if not is_lora_path(k)}
    lora = {k: v for k, v in flat.items() if is_lora_path(k)}
    return (traverse_util.unflatten_dict(base),
            traverse_util.unflatten_dict(lora))


def save_adapter(path: str, cfg: LlamaConfig, params: Any) -> None:
    """Publish ONLY the adapter weights (plus the full config, lora
    fields included) — the MB-scale artifact that makes LoRA fine-tuning
    economical: a 7B rank-8 q/v adapter is ~8 MB vs a 13 GiB snapshot."""
    import json
    import os

    from flax import serialization
    from flax import linen as fnn

    _, lora = split_lora(fnn.meta.unbox(params))
    if not jax.tree.leaves(lora):
        raise ValueError("save_adapter: params contain no lora_a/lora_b "
                         "leaves (model has lora_rank == 0?)")
    os.makedirs(path, exist_ok=True)
    d = dataclasses.asdict(cfg)
    d["dtype"] = jnp.dtype(cfg.dtype).name
    d["param_dtype"] = jnp.dtype(cfg.param_dtype).name
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(d, f, indent=1)
    with open(os.path.join(path, "adapter.msgpack"), "wb") as f:
        f.write(serialization.msgpack_serialize(
            jax.tree.map(jax.device_get, lora)))


def load_adapter(path: str) -> tuple[LlamaConfig, Any]:
    """(config-with-lora-fields, adapter tree) from ``save_adapter``."""
    import os

    from flax import serialization

    cfg = load_pretrained_config(path)
    with open(os.path.join(path, "adapter.msgpack"), "rb") as f:
        lora = serialization.msgpack_restore(f.read())
    return cfg, lora


def merge_adapter(cfg: LlamaConfig, base_params: Any,
                  adapters: Any) -> tuple[LlamaConfig, Any]:
    """Fold adapters into the base weights for serving:
    ``kernel += reshape(A @ B) * scale`` per adapted projection — after
    the merge the model is a PLAIN Llama (lora_rank 0) and every serving
    path (engines, int8 quantization, TP sharding) applies unchanged.

    Relies on the Einsum convention that kernel dims order is
    [*in_axes, *out_axes] (true for every projection in this file), so
    the rank-r product reshapes straight onto the kernel.
    """
    import numpy as np

    from flax import traverse_util

    if cfg.quant_weights:
        raise ValueError(
            "merge_adapter needs an UNQUANTIZED base: adding a "
            "model-space delta to int8 codes corrupts them — merge "
            "first, then quantize_for_serving")
    scale = cfg.lora_scale
    flat = dict(traverse_util.flatten_dict(base_params))
    aflat = dict(traverse_util.flatten_dict(adapters))
    for path, a in aflat.items():
        if path[-1] != "lora_a":
            continue
        mod = path[:-1]
        b = aflat[mod + ("lora_b",)]
        kpath = mod + ("kernel",)
        kernel = np.asarray(jax.device_get(flat[kpath]))
        if kernel.dtype == np.int8:
            raise ValueError(
                f"merge_adapter: base kernel {'/'.join(mod)} is int8 — "
                "merge before quantizing")
        a_np = np.asarray(jax.device_get(a), np.float32)
        b_np = np.asarray(jax.device_get(b), np.float32)
        r = a_np.shape[-1]
        if kernel.ndim == a_np.ndim - 1 + b_np.ndim - 1:
            # unstacked (non-scan) kernel
            delta = (a_np.reshape(-1, r) @ b_np.reshape(r, -1)).reshape(
                kernel.shape)
        else:
            # scan-stacked: leading layer axis on kernel, a and b alike
            L = kernel.shape[0]
            delta = np.einsum(
                "lir,lro->lio",
                a_np.reshape(L, -1, r), b_np.reshape(L, r, -1)
            ).reshape(kernel.shape)
        flat[kpath] = (kernel + scale * delta).astype(kernel.dtype)
    merged_cfg = dataclasses.replace(cfg, lora_rank=0, lora_alpha=0.0)
    return merged_cfg, traverse_util.unflatten_dict(flat)


def quantize_for_serving(
    cfg: LlamaConfig, params: Any, *, weights: bool = True, kv: bool = True
) -> tuple[LlamaConfig, Any]:
    """bf16/f32 snapshot -> int8 serving artifacts (SURVEY §2.2, the
    vLLM/Triton weight+KV quantization family).

    Per-OUTPUT-channel symmetric absmax quantization of every projection
    kernel and the unembedding: scales vary only over non-contracted
    dims, so ``y = (x @ w_q) * s`` is exact algebra and the dot's HBM
    read is int8.  Embedding table and norm scales stay full precision
    (a few % of the bytes; the embedding feeds a gather, not a dot).
    Returns the serving config (quant flags set) + the matching param
    tree — feed both anywhere a (cfg, params) pair goes (engines,
    generators, the AOT artifact path).
    """
    import numpy as np

    from flax import linen as fnn

    if cfg.moe_experts > 0 and weights:
        raise ValueError(
            "int8 weight quantization does not cover MoE expert trees yet "
            "(MoeMlp owns raw stacked params, not Einsum kernels); serve "
            "MoE bf16 or pass weights=False for int8 KV only")
    params = fnn.meta.unbox(params)
    qcfg = dataclasses.replace(
        cfg, quant_weights=bool(weights), quant_kv=bool(kv))
    if not weights:
        return qcfg, params

    def quant(kernel, in_axes, stacked: bool) -> dict:
        arr = np.asarray(jax.device_get(kernel), np.float32)
        axes = tuple(a + 1 for a in in_axes) if stacked else tuple(in_axes)
        s = np.maximum(np.max(np.abs(arr), axis=axes), 1e-8) / 127.0
        shape = [1 if i in axes else n for i, n in enumerate(arr.shape)]
        q8 = np.clip(np.round(arr / s.reshape(shape)), -127, 127).astype(
            np.int8)
        return q8, s.astype(np.float32)

    out = jax.tree.map(lambda x: x, params)  # shallow-copy the dicts
    stacked = cfg.scan_layers

    def replace_kernel(mod: dict, in_axes) -> None:
        q8, s = quant(mod["kernel"], in_axes, stacked)
        mod["kernel"], mod["scale"] = q8, s

    block = out["layers"]["block"] if stacked else None
    blocks = [block] if stacked else [
        out[f"layer_{i}"] for i in range(cfg.num_layers)]
    for b in blocks:
        replace_kernel(b["attn"]["wq"], (0,))
        replace_kernel(b["attn"]["wk"], (0,))
        replace_kernel(b["attn"]["wv"], (0,))
        replace_kernel(b["attn"]["wo"], (0, 1))
        replace_kernel(b["mlp"]["w_gate"], (0,))
        replace_kernel(b["mlp"]["w_up"], (0,))
        replace_kernel(b["mlp"]["w_down"], (0,))
    if not cfg.tie_embeddings:
        arr = np.asarray(
            jax.device_get(out["head"]["unembedding"]), np.float32)
        s = np.maximum(np.max(np.abs(arr), axis=0), 1e-8) / 127.0
        out["head"]["unembedding"] = np.clip(
            np.round(arr / s[None, :]), -127, 127).astype(np.int8)
        out["head"]["unembedding_scale"] = s.astype(np.float32)
    return qcfg, out


def num_params(cfg: LlamaConfig) -> int:
    """Closed-form parameter count (for tokens/sec -> MFU conversion)."""
    h, v, m = cfg.hidden_size, cfg.vocab_size, cfg.intermediate_size
    attn = h * cfg.num_heads * cfg.head_dim * 2 + h * cfg.num_kv_heads * cfg.head_dim * 2
    mlp = 3 * h * m
    per_layer = attn + mlp + 2 * h
    out = v * h if cfg.tie_embeddings else 2 * v * h
    return per_layer * cfg.num_layers + out + h


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approx train FLOPs/token: 6*N + causal attention quadratic term.

    The quadratic term counts only the lower triangle actually computed by
    causal attention (QK^T + PV, fwd+bwd = 12*L*h*d*s/2 = 6*L*h*d*s) —
    counting the full square would overstate MFU ~2x at long seq.
    """
    n = num_params(cfg)
    attn_flops = 6 * cfg.num_layers * cfg.num_heads * cfg.head_dim * seq_len
    return 6.0 * n + attn_flops
