"""``python -m kubeflow_tpu`` — the kft CLI entry point."""

from .cli import main

raise SystemExit(main())
