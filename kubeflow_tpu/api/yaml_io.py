"""YAML (de)serialization for API objects — the ``kubectl apply -f`` surface.

The reference's CRD YAMLs are camelCase with ``apiVersion``/``kind``/
``metadata``/``spec``; this module accepts both camelCase and snake_case keys
(converted at load time) so manifests read like the reference's while the
python API stays pythonic.  Kind dispatch mirrors scheme registration in the
operator manager [upstream: kubeflow/training-operator ->
cmd/training-operator.v1/main.go].
"""

from __future__ import annotations

import re
from typing import Any, Type

import yaml

from .common import TypedObject
from .experiment import Experiment, Suggestion, Trial
from .inference import InferenceGraph, InferenceService, ServingRuntime
from .jaxjob import JaxJob
from .platform import Notebook, PodDefault, Profile

#: kind -> class; cluster-substrate kinds (Pod/Node/Service/PodGroup/Event)
#: self-register from controlplane.objects at import time — the api layer
#: must not import upward into controlplane.
KIND_REGISTRY: dict[str, Type[TypedObject]] = {
    "JaxJob": JaxJob,
    "Experiment": Experiment,
    "Trial": Trial,
    "Suggestion": Suggestion,
    "InferenceService": InferenceService,
    "ServingRuntime": ServingRuntime,
    "InferenceGraph": InferenceGraph,
    "Profile": Profile,
    "Notebook": Notebook,
    "PodDefault": PodDefault,
}

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")
#: Only lowerCamelCase identifiers are schema keys worth converting; keys
#: with underscores or leading caps (env var names, label keys) pass through.
_SCHEMA_KEY_RE = re.compile(r"^[a-z][a-zA-Z0-9]*$")
#: Fields whose dict values are user data, not schema — never recursed into
#: (env var names, labels, mesh axes, algorithm settings, raw manifests…).
_DATA_MAP_FIELDS = frozenset(
    {
        "env",
        "labels",
        "annotations",
        "mesh",
        "settings",
        "config",
        "capacity",
        "trial_parameters",
        "job_manifest",
        "metrics",
    }
)


def _snake(key: str) -> str:
    if not _SCHEMA_KEY_RE.match(key):
        return key
    return _CAMEL_RE.sub("_", key).lower()


def _snake_keys(obj: Any) -> Any:
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            nk = _snake(k) if isinstance(k, str) else k
            out[nk] = v if nk in _DATA_MAP_FIELDS else _snake_keys(v)
        return out
    if isinstance(obj, list):
        return [_snake_keys(v) for v in obj]
    return obj


def from_dict(manifest: dict[str, Any]) -> TypedObject:
    """Build a typed object from a (possibly camelCase) manifest dict."""
    kind = manifest.get("kind")
    if not kind:
        raise ValueError("manifest has no 'kind'")
    cls = KIND_REGISTRY.get(str(kind))
    if cls is None:
        raise ValueError(f"unknown kind {kind!r}; known: {sorted(KIND_REGISTRY)}")
    body = _snake_keys({k: v for k, v in manifest.items() if k not in ("apiVersion",)})
    body.pop("api_version", None)
    return cls.model_validate(body)


def to_dict(obj: TypedObject) -> dict[str, Any]:
    d = obj.model_dump(mode="json", exclude_none=True, by_alias=True)
    d["apiVersion"] = obj.api_version
    d.pop("api_version", None)
    return d


def load_yaml(text: str) -> list[TypedObject]:
    """Load one or more objects from a (multi-document) YAML string."""
    out: list[TypedObject] = []
    for doc in yaml.safe_load_all(text):
        if doc is None:
            continue
        out.append(from_dict(doc))
    return out


def load_yaml_file(path: str) -> list[TypedObject]:
    with open(path) as f:
        return load_yaml(f.read())


def dump_yaml(objs: list[TypedObject] | TypedObject) -> str:
    if isinstance(objs, TypedObject):
        objs = [objs]
    return yaml.safe_dump_all([to_dict(o) for o in objs], sort_keys=False)
