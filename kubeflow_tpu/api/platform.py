"""Platform-UX API objects: Profile, Notebook, PodDefault.

The kubeflow/kubeflow shell tier (SURVEY.md §2.4) [upstream:
kubeflow/kubeflow -> components/profile-controller (Profile CRD: namespace-
per-user multi-tenancy + ResourceQuota), components/notebook-controller
(Notebook CRD: a stateful per-user workbench pod with stable URL + idle
culling), components/admission-webhook (PodDefault: label-selected env/
volume injection)].  TPU-first divergences: quotas are enforced by the gang
scheduler at admission (so a whole gang either fits the profile's quota or
stays Pending — quota overcommit can't strand half a TPU slice), and
notebooks are plain entrypoint pods on the same kubelet contract as jobs.
"""

from __future__ import annotations

from typing import Optional

from pydantic import Field

from .common import Container, TypedObject, _Model

KIND_PROFILE = "Profile"
KIND_NOTEBOOK = "Notebook"
KIND_PODDEFAULT = "PodDefault"

#: annotation a culler (or user) stamps on a Notebook to stop its pod; the
#: kubeflow analog is ``kubeflow-resource-stopped``
STOPPED_ANNOTATION = "kft-stopped"


class ProfileSpec(_Model):
    #: owning user (email in upstream kubeflow; an opaque id here)
    owner: str = ""
    contributors: list[str] = Field(default_factory=list)
    #: hard caps for the profile's namespace, enforced gang-atomically by
    #: the scheduler: {"cpu": ..., "memory_gb": ..., "tpu": ...}
    resource_quota: dict[str, float] = Field(default_factory=dict)
    #: bearer token authenticating AS this profile on the REST API —
    #: mutations scope to the profile's namespace (apiserver authz;
    #: the reference's Profile RBAC binding analog)
    api_token: Optional[str] = None
    #: request-plane QoS for this tenant (ISSUE 9; serving/traffic.py
    #: ``validate_qos`` shape): ``{"rate": req/s, "burst": n,
    #: "priority": "high"|"normal"|"low", "max_concurrent": n,
    #: "queue_depth": n}``.  The ISvc controller merges every Profile's
    #: qos into each front door's traffic plane (tenant id = profile
    #: name); resource_quota stays the gang scheduler's concern —
    #: this is the REQUEST-RATE half the platform lacked.  Validated
    #: by the Profile controller (a bad spec is one Failed status),
    #: kept a plain dict so the api layer stays serving-agnostic.
    qos: Optional[dict] = None


class ProfileStatus(_Model):
    phase: str = "Pending"  # Pending | Ready | Failed
    #: live resource usage of non-terminal pods in the namespace
    usage: dict[str, float] = Field(default_factory=dict)
    message: str = ""


class Profile(TypedObject):
    """A Profile's name IS the tenant namespace (upstream convention)."""

    kind: str = KIND_PROFILE
    spec: ProfileSpec = Field(default_factory=ProfileSpec)
    status: ProfileStatus = Field(default_factory=ProfileStatus)


class NotebookSpec(_Model):
    #: the workbench process (``module:function(ctx)`` entrypoint or command)
    template: Container = Field(default_factory=Container)
    #: stop the pod after this long without activity; 0 disables culling
    idle_cull_seconds: float = 0.0


class NotebookStatus(_Model):
    phase: str = "Pending"  # Pending | Running | Stopped | Failed
    url: Optional[str] = None
    #: wall-clock of the last observed activity (pod start or heartbeat)
    last_activity: Optional[float] = None
    message: str = ""


class Notebook(TypedObject):
    kind: str = KIND_NOTEBOOK
    spec: NotebookSpec = Field(default_factory=NotebookSpec)
    status: NotebookStatus = Field(default_factory=NotebookStatus)


class PodDefaultSpec(_Model):
    #: pods whose labels include every (k, v) here get the injection;
    #: empty selector matches nothing (upstream matchLabels semantics)
    selector: dict[str, str] = Field(default_factory=dict)
    env: dict[str, str] = Field(default_factory=dict)
    annotations: dict[str, str] = Field(default_factory=dict)


class PodDefault(TypedObject):
    """Namespace-scoped injection defaults [upstream: kubeflow/kubeflow ->
    components/admission-webhook PodDefault CRD]."""

    kind: str = KIND_PODDEFAULT
    spec: PodDefaultSpec = Field(default_factory=PodDefaultSpec)
