"""Experiment / Suggestion / Trial — the HPO plane's API objects.

Capability parity with Katib's CRD triple [upstream: kubeflow/katib ->
pkg/apis/controller/{experiments,suggestions,trials}/v1beta1/]: an objective
(metric + goal + direction), a typed search space, an algorithm name,
parallelism budgets, and a trial template that is a real ``JaxJob`` with
``${trialParameters.x}`` placeholders substituted per trial — so the HPO
outer loop composes with the training control plane exactly the way Katib
composes with the training-operator (SURVEY.md §3.4).
"""

from __future__ import annotations

import enum
import re
from typing import Any, Optional, Union

from pydantic import Field, model_validator

from .common import TypedObject, _Model

KIND_EXPERIMENT = "Experiment"
KIND_TRIAL = "Trial"
KIND_SUGGESTION = "Suggestion"


class ObjectiveType(str, enum.Enum):
    MINIMIZE = "minimize"
    MAXIMIZE = "maximize"


class ObjectiveSpec(_Model):
    type: ObjectiveType = ObjectiveType.MAXIMIZE
    objective_metric_name: str = "accuracy"
    additional_metric_names: list[str] = Field(default_factory=list)
    goal: Optional[float] = None


class ParameterType(str, enum.Enum):
    DOUBLE = "double"
    INT = "int"
    CATEGORICAL = "categorical"
    DISCRETE = "discrete"


class FeasibleSpace(_Model):
    min: Optional[float] = None
    max: Optional[float] = None
    list_: list[Union[str, float]] = Field(default_factory=list, alias="list")
    step: Optional[float] = None
    log_scale: bool = False

    model_config = {"populate_by_name": True, "extra": "forbid"}


class ParameterSpec(_Model):
    name: str
    parameter_type: ParameterType
    feasible_space: FeasibleSpace

    @model_validator(mode="after")
    def _space_ok(self) -> "ParameterSpec":
        fs = self.feasible_space
        if self.parameter_type in (ParameterType.DOUBLE, ParameterType.INT):
            if fs.min is None or fs.max is None or fs.min > fs.max:
                raise ValueError(f"parameter {self.name}: need min <= max")
        else:
            if not fs.list_:
                raise ValueError(f"parameter {self.name}: need a non-empty list")
        return self


class AlgorithmSpec(_Model):
    algorithm_name: str = "random"
    # string KV settings passed through to the suggestion service, exactly the
    # reference's AlgorithmSetting shape [upstream: katib api.proto].
    settings: dict[str, str] = Field(default_factory=dict)


class TrialTemplate(_Model):
    """A JaxJob manifest (as a plain dict) containing
    ``${trialParameters.<name>}`` placeholders."""

    job_manifest: dict[str, Any]
    # maps placeholder name -> parameter name (identity by default)
    trial_parameters: dict[str, str] = Field(default_factory=dict)


_PLACEHOLDER_RE = re.compile(r"\$\{trialParameters\.([A-Za-z0-9_]+)\}")


def substitute_parameters(obj: Any, assignments: dict[str, Any]) -> Any:
    """Deep-substitute ``${trialParameters.x}`` in a manifest tree.

    A string that is exactly one placeholder becomes the typed value; strings
    with embedded placeholders get string substitution — matching Katib's
    trial-template mutation semantics [upstream: katib ->
    pkg/controller.v1beta1/trial/].
    """
    if isinstance(obj, dict):
        return {k: substitute_parameters(v, assignments) for k, v in obj.items()}
    if isinstance(obj, list):
        return [substitute_parameters(v, assignments) for v in obj]
    if isinstance(obj, str):
        m = _PLACEHOLDER_RE.fullmatch(obj)
        if m:
            name = m.group(1)
            if name not in assignments:
                raise KeyError(f"unresolved trial parameter {name!r}")
            return assignments[name]
        return _PLACEHOLDER_RE.sub(
            lambda mm: str(assignments[mm.group(1)]), obj
        )
    return obj


class EarlyStoppingSpec(_Model):
    """Early-stopping policy [upstream: Katib EarlyStopping CRD field;
    algorithms in pkg/earlystopping/].  ``asha`` implemented natively
    (hpo/early_stopping.py); settings are string KV like AlgorithmSpec."""

    algorithm_name: str = "asha"
    settings: dict[str, str] = Field(default_factory=dict)


class ExperimentSpec(_Model):
    objective: ObjectiveSpec = Field(default_factory=ObjectiveSpec)
    algorithm: AlgorithmSpec = Field(default_factory=AlgorithmSpec)
    parameters: list[ParameterSpec] = Field(default_factory=list)
    parallel_trial_count: int = 1
    max_trial_count: int = 1
    max_failed_trial_count: int = 0
    trial_template: Optional[TrialTemplate] = None
    early_stopping: Optional[EarlyStoppingSpec] = None


class TrialAssignment(_Model):
    name: str
    value: Union[str, float, int]


class ExperimentStatus(_Model):
    conditions: list = Field(default_factory=list)
    trials_created: int = 0
    trials_succeeded: int = 0
    trials_failed: int = 0
    trials_early_stopped: int = 0
    trials_running: int = 0
    current_optimal_trial: Optional[str] = None
    current_optimal_value: Optional[float] = None
    current_optimal_assignments: list[TrialAssignment] = Field(default_factory=list)
    completed: bool = False
    #: set once observations from a previous control-plane incarnation have
    #: been replayed from the durable store (hpo/db.py)
    replayed: bool = False


class Experiment(TypedObject):
    kind: str = KIND_EXPERIMENT
    spec: ExperimentSpec = Field(default_factory=ExperimentSpec)
    status: ExperimentStatus = Field(default_factory=ExperimentStatus)


class SuggestionSpec(_Model):
    """Request for parameter assignments [upstream: katib ->
    pkg/apis/controller/suggestions/v1beta1]: the experiment controller bumps
    ``requests``; the suggestion controller (running the algorithm service)
    appends to ``status.assignments`` until it catches up."""

    experiment_name: str = ""
    algorithm: AlgorithmSpec = Field(default_factory=AlgorithmSpec)
    requests: int = 0


class SuggestionStatus(_Model):
    assignments: list[dict[str, Any]] = Field(default_factory=list)
    service_address: Optional[str] = None
    exhausted: bool = False  # algorithm cannot produce more (grid walked out)


class Suggestion(TypedObject):
    kind: str = KIND_SUGGESTION
    spec: SuggestionSpec = Field(default_factory=SuggestionSpec)
    status: SuggestionStatus = Field(default_factory=SuggestionStatus)


class TrialSpec(_Model):
    experiment_name: str = ""
    assignments: list[TrialAssignment] = Field(default_factory=list)
    job_manifest: dict[str, Any] = Field(default_factory=dict)
    objective_metric_name: str = ""


class TrialStatus(_Model):
    conditions: list = Field(default_factory=list)
    observation: Optional[float] = None  # final objective metric value
    metrics: dict[str, float] = Field(default_factory=dict)
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed | EarlyStopped
    #: ASHA rung -> objective value recorded when the trial crossed that
    #: resource milestone (str keys: the status round-trips through JSON)
    rung_values: dict[str, float] = Field(default_factory=dict)


class Trial(TypedObject):
    kind: str = KIND_TRIAL
    spec: TrialSpec = Field(default_factory=TrialSpec)
    status: TrialStatus = Field(default_factory=TrialStatus)
