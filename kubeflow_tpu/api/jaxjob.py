"""JaxJob — the flagship training-job kind.

Capability target: the union of the reference's PyTorchJob / TFJob / MPIJob /
JAXJob CRDs [upstream: kubeflow/training-operator ->
pkg/apis/kubeflow.org/v1/{pytorch,tensorflow,mpi,jax}job_types.go], collapsed
into the one shape TPU training actually needs:

- a single logical ``worker`` replica role (rank 0 doubles as the
  ``jax.distributed`` coordinator — the JAXJob-controller precedent), with
  optional extra roles for heterogenous jobs (e.g. a ``dataset`` role);
- gang semantics by construction (``SchedulingPolicy.min_available`` defaults
  to the full worker count, the Volcano PodGroup ``minMember`` analog);
- the rendezvous contract is the ``jax.distributed.initialize`` triple, not
  MASTER_ADDR/RANK/WORLD_SIZE or an ssh hostfile;
- an ``ElasticPolicy`` analog that means what elasticity *can* mean on TPU
  slices: checkpoint-restart reshape between allowed world sizes (Tenplex
  pattern, PAPERS.md), not in-place c10d rejoin.
"""

from __future__ import annotations

from typing import Optional

from pydantic import Field, field_validator, model_validator

from .common import (
    JobCondition,
    ReplicaSpec,
    ReplicaStatus,
    RunPolicy,
    TypedObject,
    _Model,
)

WORKER = "worker"
KIND_JAXJOB = "JaxJob"


class ElasticPolicy(_Model):
    """Checkpoint-restart elasticity [reference analog: PyTorchJob
    ElasticPolicy, upstream: pkg/controller.v1/pytorch/].  TPU slices cannot
    grow in place, so elasticity = save, re-admit at a new world size in
    [min_replicas, max_replicas], reshape-restore (orbax)."""

    min_replicas: int = 1
    max_replicas: int = 1
    # restart budget consumed by reshape events (distinct from failure backoff)
    max_restarts: int = 3

    @model_validator(mode="after")
    def _ordered(self) -> "ElasticPolicy":
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        return self


class JaxJobSpec(_Model):
    run_policy: RunPolicy = Field(default_factory=RunPolicy)
    replica_specs: dict[str, ReplicaSpec] = Field(default_factory=dict)
    # 0 = let the controller allocate a port at gang-bind time (the safe
    # default: submit-time allocation races with other gangs on the host,
    # r1 verdict weak #6); a fixed value pins it (real slices, known VIPs).
    coordinator_port: int = 0
    elastic_policy: Optional[ElasticPolicy] = None
    # Mesh axis sizes requested for the job, e.g. {"data": 4, "model": 2};
    # validated against the chip count by kubeflow_tpu.parallel.mesh.
    mesh: dict[str, int] = Field(default_factory=dict)

    @field_validator("replica_specs")
    @classmethod
    def _roles(cls, v: dict[str, ReplicaSpec]) -> dict[str, ReplicaSpec]:
        for role in v:
            if role != role.lower():
                raise ValueError(f"replica role {role!r} must be lowercase")
        return v

    @property
    def worker_count(self) -> int:
        spec = self.replica_specs.get(WORKER)
        return spec.replicas if spec else 0

    @property
    def total_replicas(self) -> int:
        return sum(s.replicas for s in self.replica_specs.values())


class JaxJobStatus(_Model):
    conditions: list[JobCondition] = Field(default_factory=list)
    replica_statuses: dict[str, ReplicaStatus] = Field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    restart_count: int = 0
    # Recovery probes (scripts/recovery_bench.py): when the last gang
    # restart was decided, and how long that restart took to bring every
    # worker back to Running (restart decision -> gang re-running).
    last_restart_time: Optional[float] = None
    last_recovery_seconds: Optional[float] = None
    # Gang-startup probe: wall-clock seconds from job creation to every
    # process past its first collective barrier (a headline BASELINE metric).
    gang_startup_seconds: Optional[float] = None
    # Coordinator port the controller resolved for this job (when
    # spec.coordinator_port == 0); stable across gang restarts.
    coordinator_port: Optional[int] = None


class JaxJob(TypedObject):
    kind: str = KIND_JAXJOB
    spec: JaxJobSpec = Field(default_factory=JaxJobSpec)
    status: JaxJobStatus = Field(default_factory=JaxJobStatus)
