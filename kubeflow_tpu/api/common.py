"""Common API types shared by every job kind in the control plane.

Capability parity with the reference's shared CRD vocabulary
[upstream: kubeflow/training-operator -> pkg/apis/kubeflow.org/v1/common_types.go]:
``RunPolicy``, ``ReplicaSpec``, ``ReplicaStatus``, ``JobCondition``,
``SchedulingPolicy``.  The reference expresses these as Kubernetes CRD Go
structs validated by OpenAPI schemas and admission webhooks; here they are
typed pydantic models validated at construction time, with defaulting exposed
as explicit pure functions (``kubeflow_tpu.api.validation``) so tests can
exercise the webhook-equivalent logic directly.

TPU-first divergences from the reference:

- Resources speak ``google.com/tpu`` + an explicit ``TpuTopology`` (e.g. a
  ``2x4`` v5e slice) instead of ``nvidia.com/gpu`` counts.
- Rendezvous config is the ``jax.distributed.initialize`` triple
  (coordinator address / num processes / process id) instead of
  ``MASTER_ADDR``/``RANK``/``WORLD_SIZE`` — see
  ``kubeflow_tpu.runtime.bootstrap``.
"""

from __future__ import annotations

import enum
import re
import time
from typing import Any, Optional

from pydantic import BaseModel, ConfigDict, Field, field_validator

API_GROUP = "kubeflow-tpu.dev"
API_VERSION = "v1"


class _Model(BaseModel):
    """Base config: reject unknown fields (the OpenAPI-schema equivalent)."""

    model_config = ConfigDict(extra="forbid", validate_assignment=True)


# ---------------------------------------------------------------------------
# Object metadata (the k8s ObjectMeta analog, trimmed to what the plane uses)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


class ObjectMeta(_Model):
    name: str
    namespace: str = "default"
    labels: dict[str, str] = Field(default_factory=dict)
    annotations: dict[str, str] = Field(default_factory=dict)
    uid: Optional[str] = None
    resource_version: int = 0
    creation_timestamp: Optional[float] = None
    deletion_timestamp: Optional[float] = None
    owner_references: list["OwnerReference"] = Field(default_factory=list)

    @field_validator("name")
    @classmethod
    def _dns1123(cls, v: str) -> str:
        if len(v) > 253 or not _NAME_RE.match(v):
            raise ValueError(
                f"name {v!r} must be a DNS-1123 label "
                "(lowercase alphanumerics and '-', start/end alphanumeric)"
            )
        return v


class OwnerReference(_Model):
    kind: str
    name: str
    uid: Optional[str] = None
    controller: bool = True


# ---------------------------------------------------------------------------
# Conditions and status vocabulary
# ---------------------------------------------------------------------------


class JobConditionType(str, enum.Enum):
    """Lifecycle conditions, same vocabulary as the reference's JobCondition
    [upstream: kubeflow/training-operator -> pkg/apis/kubeflow.org/v1]."""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    SUSPENDED = "Suspended"


class JobCondition(_Model):
    type: JobConditionType
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition_time: float = Field(default_factory=time.time)


def set_condition(conditions: list[JobCondition], cond: JobCondition) -> list[JobCondition]:
    """Upsert ``cond``; terminal conditions flip the other terminals off.

    Mirrors the reference's status-aggregation helpers
    [upstream: training-operator -> pkg/controller.v1/common/status.go]:
    at most one condition per type, Running is set False when a terminal
    condition lands, timestamps only bump on actual transitions.
    """
    out: list[JobCondition] = []
    replaced = False
    for existing in conditions:
        if existing.type == cond.type:
            if existing.status == cond.status and existing.reason == cond.reason:
                cond = existing  # no transition -> keep original timestamp
            out.append(cond)
            replaced = True
        elif cond.type in (JobConditionType.SUCCEEDED, JobConditionType.FAILED) and existing.type in (
            JobConditionType.RUNNING,
            JobConditionType.RESTARTING,
        ):
            if existing.status:
                out.append(
                    JobCondition(
                        type=existing.type,
                        status=False,
                        reason=cond.reason,
                        message=cond.message,
                    )
                )
            else:
                out.append(existing)
        else:
            out.append(existing)
    if not replaced:
        out.append(cond)
    return out


def get_condition(
    conditions: list[JobCondition], ctype: JobConditionType
) -> Optional[JobCondition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def has_condition(conditions: list[JobCondition], ctype: JobConditionType) -> bool:
    c = get_condition(conditions, ctype)
    return c is not None and c.status


class ReplicaStatus(_Model):
    """Pod-phase rollup per replica type [upstream: common_types.go ReplicaStatus]."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0


# ---------------------------------------------------------------------------
# Policies
# ---------------------------------------------------------------------------


class CleanPodPolicy(str, enum.Enum):
    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class RestartPolicy(str, enum.Enum):
    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    # Retry only on retryable exit codes (128+ = killed by signal, plus an
    # allowlist) — the reference's ExitCode policy.
    EXIT_CODE = "ExitCode"


#: Exit codes treated as retryable under RestartPolicy.EXIT_CODE.  The
#: reference treats 1-127 as permanent and 128+ (signal deaths) as retryable;
#: we add 42 (conventional "retry me" in kubeflow examples).
RETRYABLE_EXIT_CODES = frozenset({42}) | frozenset(range(128, 256))


def is_retryable_exit(code: int) -> bool:
    return code in RETRYABLE_EXIT_CODES


class SchedulingPolicy(_Model):
    """Gang-scheduling knobs [upstream: common_types.go SchedulingPolicy]."""

    min_available: Optional[int] = None
    queue: str = "default"
    priority_class: Optional[str] = None
    # Seconds a gang may sit Pending before the job is marked Failed
    # (the Volcano `pod-group.scheduling.sigs.k8s.io` timeout analog).
    schedule_timeout_seconds: Optional[float] = None


class RunPolicy(_Model):
    """Job-level execution policy [upstream: common_types.go RunPolicy]."""

    clean_pod_policy: CleanPodPolicy = CleanPodPolicy.NONE
    ttl_seconds_after_finished: Optional[float] = None
    active_deadline_seconds: Optional[float] = None
    backoff_limit: int = 0
    scheduling_policy: Optional[SchedulingPolicy] = None
    suspend: bool = False
    # Gang-restart pacing: the delay before restart #n is
    # ``min(restart_backoff_seconds * 2**(n-1), restart_backoff_max_seconds)``
    # with +-50% deterministic jitter, so a flapping node cannot drive a
    # fixed-interval restart storm (ISSUE 1: the 0.05 s requeue was the
    # storm).
    restart_backoff_seconds: float = 0.1
    restart_backoff_max_seconds: float = 5.0
    # Restart-budget window: after this many seconds of stable running,
    # ``status.restart_count`` resets to 0 — a long-lived job is judged by
    # its recent behavior, not by backoff_limit accumulated over weeks.
    # None = the classic lifetime budget.
    restart_window_seconds: Optional[float] = None


# ---------------------------------------------------------------------------
# Replica / pod template
# ---------------------------------------------------------------------------


class TpuTopology(_Model):
    """A TPU slice topology request, e.g. ``2x4`` (v5e-8) or ``4x4`` (v5e-16).

    Replaces the reference's opaque ``nvidia.com/gpu: N`` quantity with the
    thing the TPU scheduler actually places: a slice shape whose chip count is
    the product of its dims.
    """

    shape: str = "1x1"

    @field_validator("shape")
    @classmethod
    def _shape_ok(cls, v: str) -> str:
        if not re.match(r"^\d+(x\d+){0,2}$", v):
            raise ValueError(f"topology shape {v!r} must look like '2x4' or '4x4x4'")
        return v

    @property
    def dims(self) -> tuple[int, ...]:
        return tuple(int(d) for d in self.shape.split("x"))

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n


class Resources(_Model):
    cpu: float = 1.0
    memory_gb: float = 1.0
    tpu: int = 0  # google.com/tpu chip count per pod
    tpu_topology: Optional[TpuTopology] = None


class Container(_Model):
    """What runs inside a replica.  The reference carries a full k8s
    PodTemplateSpec; this plane runs local processes, so the template is a
    command + env + resources.  ``entrypoint`` may name a registered python
    callable (``module:function``) instead of an argv, which is how the
    runtime launches trainers without docker images.
    """

    command: list[str] = Field(default_factory=list)
    entrypoint: Optional[str] = None  # "pkg.module:func" python target
    args: list[str] = Field(default_factory=list)
    env: dict[str, str] = Field(default_factory=dict)
    resources: Resources = Field(default_factory=Resources)
    working_dir: Optional[str] = None

    @field_validator("env", mode="before")
    @classmethod
    def _stringify_env(cls, v):
        # env vars are strings by nature; numbers arrive here via typed
        # trial-parameter substitution (${trialParameters.x} in a template)
        if isinstance(v, dict):
            return {k: str(val) for k, val in v.items()}
        return v


class ReplicaSpec(_Model):
    """[upstream: common_types.go ReplicaSpec] — replicas of one role."""

    replicas: int = 1
    restart_policy: RestartPolicy = RestartPolicy.NEVER
    template: Container = Field(default_factory=Container)

    @field_validator("replicas")
    @classmethod
    def _pos(cls, v: int) -> int:
        if v < 0:
            raise ValueError("replicas must be >= 0")
        return v


def object_key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}"


def replica_pod_name(job_name: str, replica_type: str, index: int) -> str:
    """Stable pod naming ``<job>-<type>-<index>`` — the DNS contract every
    rendezvous scheme relies on [upstream: training-operator headless
    Services, pkg/controller.v1/common/service.go]."""
    return f"{job_name}-{replica_type.lower()}-{index}"


def replica_service_dns(job_name: str, replica_type: str, index: int, namespace: str) -> str:
    return f"{replica_pod_name(job_name, replica_type, index)}.{namespace}.svc"


class TypedObject(_Model):
    """Base for every API object stored in the control plane."""

    api_version: str = f"{API_GROUP}/{API_VERSION}"
    kind: str = ""
    metadata: ObjectMeta

    @property
    def key(self) -> str:
        return object_key(self.metadata.namespace, self.metadata.name)
