"""Typed API objects (the CRD layer) for the TPU-native control plane."""

from .common import (
    API_GROUP,
    API_VERSION,
    CleanPodPolicy,
    Container,
    JobCondition,
    JobConditionType,
    ObjectMeta,
    OwnerReference,
    ReplicaSpec,
    ReplicaStatus,
    Resources,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    TpuTopology,
    TypedObject,
    get_condition,
    has_condition,
    is_retryable_exit,
    object_key,
    replica_pod_name,
    replica_service_dns,
    set_condition,
)
from .experiment import (
    AlgorithmSpec,
    Experiment,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    Suggestion,
    SuggestionSpec,
    SuggestionStatus,
    Trial,
    TrialAssignment,
    TrialSpec,
    TrialTemplate,
    substitute_parameters,
)
from .inference import (
    ComponentSpec,
    InferenceService,
    InferenceServicePhase,
    InferenceServiceSpec,
    ModelFormat,
    ServingRuntime,
    ServingRuntimeSpec,
    SupportedModelFormat,
    select_runtime,
)
from .jaxjob import WORKER, ElasticPolicy, JaxJob, JaxJobSpec, JaxJobStatus
from .validation import (
    AdmissionError,
    default_experiment,
    default_inference_service,
    default_jaxjob,
    validate_experiment,
    validate_inference_service,
    validate_jaxjob,
)
from .yaml_io import dump_yaml, from_dict, load_yaml, load_yaml_file, to_dict

__all__ = [k for k in dir() if not k.startswith("_")]
