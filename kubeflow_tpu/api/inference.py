"""InferenceService / ServingRuntime — the serving plane's API objects.

Capability parity with KServe [upstream: kserve/kserve ->
pkg/apis/serving/v1beta1/inference_service.go and
pkg/apis/serving/v1alpha1/servingruntime_types.go]: an InferenceService with
predictor / transformer / explainer components, model-format -> runtime
auto-selection against a registry of ServingRuntimes, a storage URI resolved
by a storage initializer, and autoscaling targets.  The TPU-first divergence:
runtimes name an in-process JAX predictor class (an XLA AOT-compiled
callable) rather than a Triton/GPU container image — the north star's ``tpu``
ServingRuntime [local: BASELINE.json].
"""

from __future__ import annotations

import enum
from typing import Any, Optional

from pydantic import Field, model_validator

from .common import Resources, TypedObject, _Model

KIND_INFERENCE_SERVICE = "InferenceService"
KIND_SERVING_RUNTIME = "ServingRuntime"


class ModelFormat(_Model):
    name: str  # e.g. "jax", "flax-msgpack", "sklearn-json", "bert"
    version: Optional[str] = None


class GangSpec(_Model):
    """Multi-HOST predictor placement (serving/gang.py).

    A TPU pod slice is hosts x chips — a predictor whose tensor-parallel
    mesh exceeds one host's chips must run as a gang of cooperating
    processes (the multi-host jit contract), placed and restarted like a
    JaxJob.  ``mesh_axes`` is the GLOBAL serving mesh (its product must
    equal hosts * chips_per_host); ``chips_per_host`` doubles as the
    virtual-device count for the local CPU stand-in runtime.
    """

    hosts: int = Field(default=2, ge=1)
    mesh_axes: dict[str, int] = Field(default_factory=dict)
    chips_per_host: int = Field(default=4, ge=1)
    #: gang-restart budget (JaxJob run_policy.backoff_limit)
    backoff_limit: int = 16

    @model_validator(mode="after")
    def _mesh_covers_gang(self) -> "GangSpec":
        # reject at admission, not after backoff_limit whole-gang crash
        # loops: every member builds this exact global mesh
        import math

        if not self.mesh_axes:
            raise ValueError("gang.mesh_axes must name the serving mesh")
        n = math.prod(self.mesh_axes.values())
        if n != self.hosts * self.chips_per_host:
            raise ValueError(
                f"gang mesh {self.mesh_axes} covers {n} chips but "
                f"{self.hosts} hosts x {self.chips_per_host} chips/host "
                f"= {self.hosts * self.chips_per_host}")
        return self


class LoggerSpec(_Model):
    """Inference payload logging [upstream: kserve -> pkg/agent/logger,
    the ISvc ``logger`` field]: every request/response POSTs to ``url``
    with CloudEvents binary-mode headers, asynchronously (a dead sink
    drops events, never backpressures predicts)."""

    url: str
    #: "all" | "request" | "response"
    mode: str = "all"

    @model_validator(mode="after")
    def _mode_ok(self) -> "LoggerSpec":
        # reject at admission, not deep inside reconcile (or a gang pod)
        if self.mode not in ("all", "request", "response"):
            raise ValueError(
                f"logger mode {self.mode!r}: all|request|response")
        return self


class ComponentSpec(_Model):
    """One serving component (predictor/transformer/explainer)."""

    model_format: Optional[ModelFormat] = None
    storage_uri: Optional[str] = None  # file:// | mem:// | gs:// (stubbed)
    runtime: Optional[str] = None  # explicit ServingRuntime name override
    # "module:Class" for custom python components (transformer/explainer)
    handler: Optional[str] = None
    min_replicas: int = 1
    max_replicas: int = 1
    # target concurrent requests per replica before scaling out (knative
    # KPA concurrency-target analog)
    scale_target_concurrency: float = 4.0
    resources: Resources = Field(default_factory=Resources)
    batch_max_size: int = 8
    batch_timeout_ms: float = 2.0
    config: dict[str, Any] = Field(default_factory=dict)
    #: place the predictor as a multi-host gang instead of in-process
    #: replicas (predictor only; see GangSpec)
    gang: Optional[GangSpec] = None
    #: payload logging to a collector sink (see LoggerSpec)
    logger: Optional[LoggerSpec] = None


class InferenceServiceSpec(_Model):
    predictor: ComponentSpec = Field(default_factory=ComponentSpec)
    transformer: Optional[ComponentSpec] = None
    explainer: Optional[ComponentSpec] = None
    #: KServe canary rollout [upstream: kserve ->
    #: pkg/apis/serving/v1beta1/inference_service.go CanaryTrafficPercent]:
    #: when set and the spec changes, the previous revision keeps serving
    #: (100 - p)% of traffic while the new revision gets p%.  100 (or
    #: None) rolls the change out fully; reverting the spec rolls back.
    canary_traffic_percent: Optional[int] = Field(default=None, ge=0, le=100)


class InferenceServicePhase(str, enum.Enum):
    PENDING = "Pending"
    LOADING = "Loading"
    READY = "Ready"
    #: serving, but below strength: some replica (e.g. a gang re-forming
    #: after a member loss) is not taking traffic; healthy replicas are
    DEGRADED = "Degraded"
    FAILED = "Failed"


class InferenceServiceStatus(_Model):
    phase: InferenceServicePhase = InferenceServicePhase.PENDING
    url: Optional[str] = None
    active_replicas: int = 0
    message: str = ""
    #: revision bookkeeping (KServe's latestRolledOutRevision /
    #: latestCreatedRevision analog): monotonically increasing ints
    stable_revision: int = 0
    canary_revision: Optional[int] = None
    #: live traffic share of the canary revision (0 when no canary)
    canary_traffic: int = 0
    #: the stable revision's spec (minus traffic split) — what the SDK's
    #: ``rollback`` verb restores
    stable_spec: Optional[dict] = None


class InferenceService(TypedObject):
    kind: str = KIND_INFERENCE_SERVICE
    spec: InferenceServiceSpec = Field(default_factory=InferenceServiceSpec)
    status: InferenceServiceStatus = Field(default_factory=InferenceServiceStatus)


KIND_INFERENCE_GRAPH = "InferenceGraph"


class GraphStep(_Model):
    """One step of a graph node [upstream: kserve ->
    pkg/apis/serving/v1alpha1/inference_graph_types.go InferenceStep]."""

    #: target InferenceService (exactly one of service_name/node_name)
    service_name: Optional[str] = None
    #: target nested node in the same graph
    node_name: Optional[str] = None
    #: Switch: simple predicate on the request JSON — ``key == value``,
    #: ``key != value``, ``key > value``, ``key < value`` (kserve uses
    #: gjson expressions; this is the same capability, simpler syntax)
    condition: Optional[str] = None
    #: Sequence: what the step receives — "$response" (previous step's
    #: output, default) or "$request" (the original graph input)
    data: str = "$response"
    #: Ensemble: key for this step's output in the merged response
    #: (defaults to the service/node name); Splitter: ignored
    name: Optional[str] = None
    #: Splitter: relative traffic weight (defaults to 1)
    weight: Optional[int] = None


class GraphNode(_Model):
    #: "Sequence" (steps chained in order), "Switch" (first step whose
    #: condition matches handles it), "Ensemble" (all steps run in
    #: parallel on the same input; outputs merged under step names), or
    #: "Splitter" (one step picked by traffic weight)
    router_type: str = "Sequence"
    steps: list[GraphStep] = Field(default_factory=list)


class InferenceGraphSpec(_Model):
    #: node name -> node; "root" is the entrypoint
    nodes: dict[str, GraphNode] = Field(default_factory=dict)


class InferenceGraphStatus(_Model):
    phase: InferenceServicePhase = InferenceServicePhase.PENDING
    url: Optional[str] = None
    message: str = ""


class InferenceGraph(TypedObject):
    kind: str = KIND_INFERENCE_GRAPH
    spec: InferenceGraphSpec = Field(default_factory=InferenceGraphSpec)
    status: InferenceGraphStatus = Field(default_factory=InferenceGraphStatus)


class SupportedModelFormat(_Model):
    name: str
    version: Optional[str] = None
    auto_select: bool = True
    priority: int = 1


class ServingRuntimeSpec(_Model):
    supported_model_formats: list[SupportedModelFormat] = Field(default_factory=list)
    # python target "module:Class" implementing kubeflow_tpu.serving.model.Model
    server_class: str = ""
    # runtime-level defaults merged under component config
    config: dict[str, Any] = Field(default_factory=dict)


class ServingRuntime(TypedObject):
    kind: str = KIND_SERVING_RUNTIME
    spec: ServingRuntimeSpec = Field(default_factory=ServingRuntimeSpec)


def select_runtime(
    fmt: ModelFormat, runtimes: list[ServingRuntime]
) -> Optional[ServingRuntime]:
    """Model-format -> runtime auto-selection [upstream: kserve ->
    pkg/apis/serving/v1beta1/predictor_model.go GetSupportingRuntimes]:
    highest-priority runtime whose supported formats include the requested
    name (and version when both specify one), auto_select only."""
    best: tuple[int, Optional[ServingRuntime]] = (-1, None)
    for rt in runtimes:
        for sf in rt.spec.supported_model_formats:
            if not sf.auto_select or sf.name != fmt.name:
                continue
            if fmt.version and sf.version and fmt.version != sf.version:
                continue
            if sf.priority > best[0]:
                best = (sf.priority, rt)
    return best[1]
