"""Defaulting + validation as pure functions — the admission-webhook layer.

The reference splits this across OpenAPI schema validation, defaulting
webhooks, and validating webhooks [upstream: kubeflow/training-operator ->
pkg/webhooks/, kserve -> pkg/apis/serving/v1beta1/*_validation.go].  pydantic
covers the schema tier at construction; these functions are the mutating
(default_*) and validating (validate_*) webhook equivalents, called by the
control plane on admission so tests can exercise them directly.
"""

from __future__ import annotations

from .common import ReplicaSpec, SchedulingPolicy
from .experiment import Experiment
from .inference import InferenceService
from .jaxjob import WORKER, JaxJob


class AdmissionError(ValueError):
    """Rejection from the validating-webhook equivalent."""


# ---------------------------------------------------------------------------
# JaxJob
# ---------------------------------------------------------------------------


def default_jaxjob(job: JaxJob) -> JaxJob:
    """Mutating defaults: ensure a worker role exists, gang min_available
    covers the full gang, and the mesh (if any) defaults to pure DP."""
    spec = job.spec
    if WORKER not in spec.replica_specs:
        spec.replica_specs[WORKER] = ReplicaSpec()
    rp = spec.run_policy
    if rp.scheduling_policy is None:
        rp.scheduling_policy = SchedulingPolicy()
    if rp.scheduling_policy.min_available is None:
        # all-or-nothing by default: the whole gang (Volcano minMember analog)
        rp.scheduling_policy.min_available = spec.total_replicas
    elif rp.scheduling_policy.min_available > spec.total_replicas:
        # elastic resize shrinks the gang: a min_available stamped for the
        # old world size would make the spec permanently inadmissible, so
        # defaulting re-clamps it (mutating webhooks run on UPDATE too)
        rp.scheduling_policy.min_available = spec.total_replicas
    workers = spec.replica_specs[WORKER]
    chips_per_host = max(1, workers.template.resources.tpu or 1)
    total_chips = workers.replicas * chips_per_host
    if (
        job.metadata.creation_timestamp is not None
        and set(spec.mesh) == {"data"}
        and spec.mesh["data"] != total_chips
    ):
        # UPDATE of a live job whose pure-DP default mesh was stamped for an
        # old world size (elastic resize): re-derive.  On CREATE (no
        # creation_timestamp yet) a mismatched mesh is the user's own input
        # and must fail validation, not be silently rewritten; custom
        # (non-"data") meshes are always left to validation.
        spec.mesh = {}
    if not spec.mesh:
        spec.mesh = {"data": total_chips}
    return job


def validate_jaxjob(job: JaxJob) -> None:
    spec = job.spec
    workers = spec.replica_specs.get(WORKER)
    if workers is None or workers.replicas < 1:
        raise AdmissionError("JaxJob needs a 'worker' replica spec with replicas >= 1")
    sp = spec.run_policy.scheduling_policy
    if sp and sp.min_available is not None and sp.min_available > spec.total_replicas:
        raise AdmissionError(
            f"min_available {sp.min_available} exceeds total replicas {spec.total_replicas}"
        )
    if spec.run_policy.backoff_limit < 0:
        raise AdmissionError("backoff_limit must be >= 0")
    if not (0 <= spec.coordinator_port < 65536):  # 0 = controller-allocated
        raise AdmissionError(f"coordinator_port {spec.coordinator_port} out of range")
    if spec.elastic_policy and spec.elastic_policy.max_replicas < workers.replicas:
        raise AdmissionError("elastic_policy.max_replicas < worker replicas")
    if spec.mesh:
        mesh_devices = 1
        for ax, size in spec.mesh.items():
            if size < 1:
                raise AdmissionError(f"mesh axis {ax!r} has non-positive size {size}")
            mesh_devices *= size
        chips_per_host = max(1, workers.template.resources.tpu or 1)
        total_devices = workers.replicas * chips_per_host
        if mesh_devices != total_devices:
            raise AdmissionError(
                f"mesh {spec.mesh} covers {mesh_devices} devices but the job "
                f"provides {total_devices} ({workers.replicas} workers x "
                f"{chips_per_host} chips)"
            )


# ---------------------------------------------------------------------------
# Experiment
# ---------------------------------------------------------------------------


def default_experiment(exp: Experiment) -> Experiment:
    s = exp.spec
    if s.parallel_trial_count < 1:
        s.parallel_trial_count = 1
    if s.max_trial_count < s.parallel_trial_count:
        s.max_trial_count = s.parallel_trial_count
    if s.trial_template and not s.trial_template.trial_parameters:
        s.trial_template.trial_parameters = {p.name: p.name for p in s.parameters}
    return exp


def validate_experiment(exp: Experiment) -> None:
    s = exp.spec
    if not s.parameters:
        raise AdmissionError("Experiment needs at least one parameter")
    if s.trial_template is None:
        raise AdmissionError("Experiment needs a trial_template")
    if s.trial_template.job_manifest.get("kind") not in ("JaxJob",):
        raise AdmissionError("trial_template.job_manifest must be a JaxJob manifest")
    names = [p.name for p in s.parameters]
    if len(names) != len(set(names)):
        raise AdmissionError("duplicate parameter names")
    if not s.objective.objective_metric_name:
        raise AdmissionError("objective_metric_name is required")


# ---------------------------------------------------------------------------
# InferenceService
# ---------------------------------------------------------------------------


def default_inference_service(isvc: InferenceService) -> InferenceService:
    p = isvc.spec.predictor
    if p.min_replicas < 0:
        p.min_replicas = 0  # 0 = scale-to-zero allowed (knative KPA analog)
    if p.max_replicas < max(p.min_replicas, 1):
        p.max_replicas = max(p.min_replicas, 1)
    return isvc


def validate_inference_service(isvc: InferenceService) -> None:
    p = isvc.spec.predictor
    if p.model_format is None and p.handler is None and p.runtime is None:
        raise AdmissionError(
            "predictor needs a model_format (for runtime auto-selection), "
            "an explicit runtime, or a custom handler"
        )
    if p.storage_uri is not None:
        scheme = p.storage_uri.split("://", 1)[0] if "://" in p.storage_uri else ""
        if scheme not in ("file", "mem", "gs", "s3", "hf", "pvc"):
            raise AdmissionError(f"unsupported storage_uri scheme {scheme!r}")
