"""Profile controller: namespace-per-user multi-tenancy with live usage.

[upstream: kubeflow/kubeflow -> components/profile-controller]: a Profile
creates the user's namespace, RBAC, and a ResourceQuota.  Here the Profile's
name *is* the tenant namespace; the controller keeps ``status.usage``
current (non-terminal pod consumption in that namespace) and the gang
scheduler enforces ``spec.resource_quota`` atomically at admission — a gang
that would exceed the profile's quota stays Pending whole, so quota pressure
can never strand a partial TPU slice (the upstream ResourceQuota admission
rejects pod-by-pod, which would).
"""

from __future__ import annotations

from typing import Optional

from ..api.platform import KIND_PROFILE, Profile
from ..controlplane.controller import Controller, Result
from ..controlplane.objects import KIND_POD, Pod, pod_resources
from ..controlplane.store import NotFound, Store
from ..api.common import TypedObject

#: profiles live in this namespace; their *name* is the tenant namespace
PROFILE_NS = "default"


def namespace_usage(store: Store, namespace: str) -> dict[str, float]:
    usage: dict[str, float] = {}
    for pod in store.list(KIND_POD, namespace):
        assert isinstance(pod, Pod)
        if pod.terminal or not pod.spec.node_name:
            continue
        for k, v in pod_resources(pod).items():
            usage[k] = usage.get(k, 0.0) + v
    return {k: round(v, 9) for k, v in usage.items() if v}


class ProfileController(Controller):
    kind = KIND_PROFILE
    owned_kinds = (KIND_POD,)

    def owner_key_for(self, obj: TypedObject) -> Optional[str]:
        # every pod event in a tenant namespace dirties that namespace's
        # profile (pods carry no owner-ref to profiles, upstream-style)
        if obj.kind == KIND_POD:
            return f"{PROFILE_NS}/{obj.metadata.namespace}"
        return None

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        prof = self.store.try_get(KIND_PROFILE, name, namespace)
        if prof is None:
            return None
        assert isinstance(prof, Profile)
        usage = namespace_usage(self.store, name)
        qos_err = ""
        if prof.spec.qos is not None:
            # validate the tenant's QoS contract HERE (one Failed
            # status with the field named — the conf-freeze convention)
            # instead of letting every ISvc front door silently skip a
            # malformed class; lazy import keeps the control plane free
            # of the serving stack until a profile actually uses qos
            from ..serving.traffic import validate_qos

            try:
                validate_qos({name: prof.spec.qos})
            except (TypeError, ValueError) as e:
                # validate_qos promises ValueError, but a Failed status
                # beats a crash-looping reconcile if that ever slips
                qos_err = str(e)

        def mut(o):
            assert isinstance(o, Profile)
            o.status.usage = usage
            o.status.phase = "Failed" if qos_err else "Ready"
            o.status.message = qos_err

        try:
            self.store.update_with_retry(KIND_PROFILE, name, namespace, mut)
        except NotFound:
            pass
        return None
