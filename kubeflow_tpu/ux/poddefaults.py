"""PodDefault injection: the admission-webhook tier of the platform shell.

[upstream: kubeflow/kubeflow -> components/admission-webhook]: PodDefault
objects in a namespace declare env/annotation injections for pods matching
a label selector; a mutating webhook applies them at pod admission.  Here
the same hook is a store mutator registered for the Pod kind — it runs on
every pod CREATE, exactly where the upstream webhook sits.
"""

from __future__ import annotations

from ..api.platform import KIND_PODDEFAULT, PodDefault
from ..controlplane.objects import Pod
from ..controlplane.store import Store


def _matches(selector: dict[str, str], labels: dict[str, str]) -> bool:
    return bool(selector) and all(labels.get(k) == v for k, v in selector.items())


def pod_default_mutator(store: Store):
    """Returns the mutating hook; bound to the store it reads defaults from."""

    def mutate(pod: Pod) -> Pod:
        for pd in store.list(KIND_PODDEFAULT, pod.metadata.namespace):
            if not isinstance(pd, PodDefault):
                continue
            if not _matches(pd.spec.selector, pod.metadata.labels):
                continue
            # pod's own values win over injected defaults (upstream merges
            # without overwriting existing keys)
            for k, v in pd.spec.env.items():
                pod.spec.container.env.setdefault(k, v)
            for k, v in pd.spec.annotations.items():
                pod.metadata.annotations.setdefault(k, v)
        return pod

    return mutate
