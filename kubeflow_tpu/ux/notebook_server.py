"""In-pod notebook server: the workbench process a Notebook pod runs.

[upstream: kubeflow/kubeflow notebook images run Jupyter; the controller
only cares that *some* HTTP server sits behind the Service].  This is the
minimal native workbench: a persistent-namespace code executor over HTTP —
``POST /execute {"code": ...}`` evaluates in a kernel namespace that
survives across requests (the kernel semantics notebooks need), ``GET /``
reports liveness.  Each request stamps an activity heartbeat into the
pod's status dir, which is the culling signal's source of truth.

Security note: /execute runs arbitrary code *by design* — a notebook IS a
user-code execution service, isolated at the pod boundary exactly like a
Jupyter kernel is upstream.
"""

from __future__ import annotations

import io
import json
import os
import time
from contextlib import redirect_stdout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

ENV_NOTEBOOK_PORT = "KFT_NOTEBOOK_PORT"
ACTIVITY_FILE = "activity"


def main(ctx) -> None:
    port = int(os.environ.get(ENV_NOTEBOOK_PORT, "0"))
    kernel_ns: dict = {"__name__": "__kft_notebook__"}
    status_dir = getattr(ctx, "status_dir", None) or os.environ.get(
        "KFT_STATUS_DIR")

    def touch_activity() -> None:
        if status_dir:
            try:
                with open(os.path.join(status_dir, ACTIVITY_FILE), "w") as f:
                    f.write(str(time.time()))
            except OSError:
                pass

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            touch_activity()
            self._send(200, {"notebook": getattr(ctx, "job_name", "notebook"),
                             "alive": True})

        def do_POST(self):
            touch_activity()
            if self.path != "/execute":
                self._send(404, {"error": "unknown path"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                code = json.loads(self.rfile.read(length))["code"]
                out = io.StringIO()
                with redirect_stdout(out):
                    try:
                        result = eval(  # noqa: S307 — the product IS a kernel
                            compile(code, "<cell>", "eval"), kernel_ns)
                    except SyntaxError:
                        exec(compile(code, "<cell>", "exec"), kernel_ns)  # noqa: S102
                        result = None
                self._send(200, {"result": repr(result) if result is not None else None,
                                 "stdout": out.getvalue()})
            except Exception as e:  # noqa: BLE001 — surfaced as 400
                self._send(400, {"error": f"{type(e).__name__}: {e}"})

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    # publish the bound port so tests/operators can find a 0-port server
    if status_dir:
        try:
            with open(os.path.join(status_dir, "notebook_port"), "w") as f:
                f.write(str(httpd.server_address[1]))
        except OSError:
            pass
    touch_activity()
    httpd.serve_forever()
