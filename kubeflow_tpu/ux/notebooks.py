"""Notebook controller: per-user workbench pods with stable URLs + culling.

[upstream: kubeflow/kubeflow -> components/notebook-controller]: a Notebook
CRD reconciles to a StatefulSet (one pod) + Service, exposes a stable URL
behind the dashboard, and an idle culler stops notebooks by stamping the
``kubeflow-resource-stopped`` annotation.  Same shape here: Notebook ->
one pod (``<name>-notebook-0``) on the ordinary kubelet contract + headless
Service; ``spec.idle_cull_seconds`` of inactivity stamps the
``kft-stopped`` annotation and deletes the pod (state lives outside the
pod, like upstream's PVC); removing the annotation resumes it.
"""

from __future__ import annotations

import time
from typing import Optional

from ..api.common import ObjectMeta, OwnerReference, replica_service_dns
from ..api.platform import (
    KIND_NOTEBOOK,
    Notebook,
    STOPPED_ANNOTATION,
)
from ..controlplane.controller import Controller, Result
from ..controlplane.objects import (
    KIND_POD,
    KIND_SERVICE,
    Pod,
    PodPhase,
    PodSpec,
    Service,
    ServiceSpec,
)
from ..controlplane.store import AlreadyExists, NotFound, Store


def notebook_pod_name(name: str) -> str:
    return f"{name}-notebook-0"


class NotebookController(Controller):
    kind = KIND_NOTEBOOK
    owned_kinds = (KIND_POD, KIND_SERVICE)

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        nb = self.store.try_get(KIND_NOTEBOOK, name, namespace)
        pod_name = notebook_pod_name(name)
        if nb is None:
            self.store.try_delete(KIND_POD, pod_name, namespace)
            self.store.try_delete(KIND_SERVICE, pod_name, namespace)
            return None
        assert isinstance(nb, Notebook)

        stopped = STOPPED_ANNOTATION in nb.metadata.annotations
        pod = self.store.try_get(KIND_POD, pod_name, namespace)

        if stopped:
            if pod is not None:
                self.store.try_delete(KIND_POD, pod_name, namespace)
                self.emit_event(nb, "NotebookStopped",
                                nb.metadata.annotations.get(STOPPED_ANNOTATION, ""))
            self._set_status(nb, phase="Stopped", url=None)
            return None

        if pod is None:
            pod = Pod(
                metadata=ObjectMeta(
                    name=pod_name,
                    namespace=namespace,
                    labels={"kft-notebook": name},
                    owner_references=[OwnerReference(
                        kind=KIND_NOTEBOOK, name=name, uid=nb.metadata.uid)],
                ),
                spec=PodSpec(
                    container=nb.spec.template.model_copy(deep=True),
                    scheduler_name="default",  # notebooks are not gangs
                ),
            )
            try:
                self.store.create(pod)
                self.emit_event(nb, "PodCreated", pod_name)
            except AlreadyExists:
                pass
            self._ensure_service(nb, pod_name, namespace)
            self._set_status(nb, phase="Pending")
            return Result(requeue_after=0.05)

        assert isinstance(pod, Pod)
        url = f"http://{replica_service_dns(name, 'notebook', 0, namespace)}"
        if pod.status.phase == PodPhase.RUNNING:
            # activity = the pod's own heartbeat (notebook_server stamps it
            # per request, surfaced by the kubelet), falling back to start
            last = (pod.status.last_activity
                    or pod.status.start_time or time.time())
            cull = nb.spec.idle_cull_seconds
            if cull > 0 and time.time() - last > cull:
                # the culler half of the controller: stamp + stop
                def stamp(o):
                    assert isinstance(o, Notebook)
                    o.metadata.annotations[STOPPED_ANNOTATION] = "idle-culled"

                try:
                    self.store.update_with_retry(
                        KIND_NOTEBOOK, name, namespace, stamp)
                except NotFound:
                    return None
                return Result(requeue_after=0.0)
            self._set_status(nb, phase="Running", url=url, last_activity=last)
            return Result(requeue_after=0.25 if cull > 0 else None)
        if pod.status.phase == PodPhase.FAILED:
            self._set_status(nb, phase="Failed",
                             message=pod.status.message or "notebook pod failed")
            return None
        self._set_status(nb, phase="Pending")
        return Result(requeue_after=0.1)

    def _ensure_service(self, nb: Notebook, pod_name: str, namespace: str) -> None:
        try:
            self.store.create(Service(
                metadata=ObjectMeta(
                    name=pod_name, namespace=namespace,
                    owner_references=[OwnerReference(
                        kind=KIND_NOTEBOOK, name=nb.metadata.name,
                        uid=nb.metadata.uid)],
                ),
                spec=ServiceSpec(selector={"kft-notebook": nb.metadata.name}),
            ))
        except AlreadyExists:
            pass

    def _set_status(self, nb: Notebook, phase: str, url=None,
                    last_activity=None, message: str = "") -> None:
        def mut(o):
            assert isinstance(o, Notebook)
            o.status.phase = phase
            o.status.url = url
            if last_activity is not None:
                o.status.last_activity = last_activity
            o.status.message = message

        try:
            self.store.update_with_retry(
                KIND_NOTEBOOK, nb.metadata.name, nb.metadata.namespace, mut)
        except NotFound:
            pass
