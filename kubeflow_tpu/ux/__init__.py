"""Platform-UX tier (SURVEY.md §2.4): profiles, notebooks, pod defaults,
central dashboard — the kubeflow/kubeflow shell rebuilt on this cluster."""

from .dashboard import Dashboard
from .notebooks import NotebookController
from .poddefaults import pod_default_mutator
from .profiles import ProfileController

__all__ = [
    "Dashboard",
    "NotebookController",
    "ProfileController",
    "pod_default_mutator",
]
