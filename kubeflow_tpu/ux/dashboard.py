"""Central dashboard: one URL aggregating every plane's status.

[upstream: kubeflow/kubeflow -> components/centraldashboard (TS web app)]:
the landing surface listing jobs, experiments, inference services,
notebooks, and profiles across the platform.  Here a single HTTP server
over the store: JSON APIs per kind (what the upstream web apps fetch from
their backends) plus a minimal server-rendered HTML index — enough for a
human to see the whole cluster at a glance, with zero JS build tooling.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..api.experiment import KIND_EXPERIMENT
from ..api.inference import KIND_INFERENCE_GRAPH, KIND_INFERENCE_SERVICE
from ..api.jaxjob import KIND_JAXJOB
from ..api.platform import KIND_NOTEBOOK, KIND_PROFILE
from ..controlplane.objects import KIND_EVENT, KIND_NODE, KIND_POD
from ..controlplane.store import Store
from ..utils.net import allocate_port

#: API path segment -> store kind
_SECTIONS = {
    "jaxjobs": KIND_JAXJOB,
    "experiments": KIND_EXPERIMENT,
    "inferenceservices": KIND_INFERENCE_SERVICE,
    "inferencegraphs": KIND_INFERENCE_GRAPH,
    "notebooks": KIND_NOTEBOOK,
    "profiles": KIND_PROFILE,
    "nodes": KIND_NODE,
    "pods": KIND_POD,
    "events": KIND_EVENT,
}


def _summarize(obj) -> dict:
    out = {
        "name": obj.metadata.name,
        "namespace": obj.metadata.namespace,
        "kind": obj.kind,
    }
    status = getattr(obj, "status", None)
    if status is not None:
        out["status"] = status.model_dump(mode="json")
    for attr in ("reason", "message", "type", "involved_kind", "involved_name"):
        v = getattr(obj, attr, None)
        if isinstance(v, str) and v:
            out[attr] = v
    return out


def _phase_of(summary: dict) -> str:
    st = summary.get("status", {})
    if "phase" in st and st["phase"]:
        return str(st["phase"])
    conds = st.get("conditions") or []
    return str(conds[-1]["type"]) if conds else ""


class Dashboard:
    """Serve ``/`` (HTML index), ``/api/overview`` and ``/api/<section>``."""

    def __init__(self, store: Store, port: Optional[int] = None):
        self.store = store
        self.port = port or allocate_port()
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path == "/":
                        self._send(200, dash.index_html().encode(), "text/html")
                    elif self.path == "/api/overview":
                        self._send(200, json.dumps(dash.overview()).encode(),
                                   "application/json")
                    elif self.path.startswith("/api/"):
                        section = self.path[len("/api/"):].strip("/")
                        if section not in _SECTIONS:
                            self._send(404, b'{"error": "unknown section"}',
                                       "application/json")
                            return
                        self._send(200, json.dumps(dash.section(section)).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found", "text/plain")
                except Exception as e:  # noqa: BLE001
                    self._send(500, json.dumps({"error": str(e)}).encode(),
                               "application/json")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dashboard", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

    # -- data -------------------------------------------------------------

    def section(self, name: str) -> list[dict]:
        return [_summarize(o) for o in self.store.list(_SECTIONS[name])]

    def overview(self) -> dict:
        return {name: len(self.store.list(kind))
                for name, kind in _SECTIONS.items()}

    def index_html(self) -> str:
        parts = ["<html><head><title>kubeflow-tpu</title></head><body>",
                 "<h1>kubeflow-tpu dashboard</h1>"]
        for name in _SECTIONS:
            if name in ("events", "pods"):
                continue  # noisy sections stay API-only, like upstream
            rows = self.section(name)
            parts.append(f"<h2>{name} ({len(rows)})</h2><ul>")
            for r in rows:
                label = html.escape(f"{r['namespace']}/{r['name']}")
                phase = html.escape(_phase_of(r))
                parts.append(f"<li>{label} — {phase}</li>")
            parts.append("</ul>")
        parts.append("</body></html>")
        return "".join(parts)
