"""Central dashboard: one URL aggregating every plane's status.

[upstream: kubeflow/kubeflow -> components/centraldashboard (TS web app)]:
the landing surface listing jobs, experiments, inference services,
notebooks, and profiles across the platform.  Here a single HTTP server
over the store: JSON APIs per kind (what the upstream web apps fetch from
their backends) plus a minimal server-rendered HTML index — enough for a
human to see the whole cluster at a glance, with zero JS build tooling.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..api.experiment import KIND_EXPERIMENT
from ..api.inference import KIND_INFERENCE_GRAPH, KIND_INFERENCE_SERVICE
from ..api.jaxjob import KIND_JAXJOB
from ..api.platform import KIND_NOTEBOOK, KIND_PROFILE
from ..controlplane.objects import KIND_EVENT, KIND_NODE, KIND_POD
from ..controlplane.store import Store
from ..utils.net import allocate_port

#: API path segment -> store kind
_SECTIONS = {
    "jaxjobs": KIND_JAXJOB,
    "experiments": KIND_EXPERIMENT,
    "inferenceservices": KIND_INFERENCE_SERVICE,
    "inferencegraphs": KIND_INFERENCE_GRAPH,
    "notebooks": KIND_NOTEBOOK,
    "profiles": KIND_PROFILE,
    "nodes": KIND_NODE,
    "pods": KIND_POD,
    "events": KIND_EVENT,
}


def _summarize(obj) -> dict:
    out = {
        "name": obj.metadata.name,
        "namespace": obj.metadata.namespace,
        "kind": obj.kind,
    }
    status = getattr(obj, "status", None)
    if status is not None:
        out["status"] = status.model_dump(mode="json")
    for attr in ("reason", "message", "type", "involved_kind", "involved_name"):
        v = getattr(obj, attr, None)
        if isinstance(v, str) and v:
            out[attr] = v
    return out


def _phase_of(summary: dict) -> str:
    st = summary.get("status", {})
    if "phase" in st and st["phase"]:
        return str(st["phase"])
    conds = st.get("conditions") or []
    return str(conds[-1]["type"]) if conds else ""


class Dashboard:
    """Serve ``/`` (HTML index), ``/api/overview``, ``/api/<section>``,
    per-object detail ``/api/<section>/<ns>/<name>`` (+ its events, + pod
    logs via ``log_path_for``), and experiment metric curves
    ``/api/experiments/<ns>/<name>/curves`` (the Katib UI's main job,
    read from the observation DB)."""

    def __init__(self, store: Store, port: Optional[int] = None,
                 db=None, log_path_for=None):
        self.store = store
        self.db = db  # hpo.db.DbManagerClient (experiment curves)
        self.log_path_for = log_path_for  # (namespace, pod) -> log path
        self.port = port or allocate_port()
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path == "/":
                        self._send(200, dash.index_html().encode(), "text/html")
                    elif self.path == "/api/overview":
                        self._send(200, json.dumps(dash.overview()).encode(),
                                   "application/json")
                    elif self.path.startswith("/api/"):
                        parts = self.path[len("/api/"):].strip("/").split("/")
                        if parts[0] not in _SECTIONS:
                            self._send(404, b'{"error": "unknown section"}',
                                       "application/json")
                            return
                        if len(parts) == 1:
                            payload = dash.section(parts[0])
                        elif len(parts) == 3:
                            payload = dash.detail(parts[0], parts[1], parts[2])
                        elif (len(parts) == 4 and parts[0] == "experiments"
                              and parts[3] == "curves"):
                            payload = dash.curves(parts[1], parts[2])
                        elif (len(parts) == 4 and parts[0] == "pods"
                              and parts[3] == "logs"):
                            self._send(
                                200, dash.pod_logs(parts[1], parts[2]).encode(),
                                "text/plain")
                            return
                        else:
                            self._send(404, b'{"error": "unknown path"}',
                                       "application/json")
                            return
                        if payload is None:
                            self._send(404, b'{"error": "not found"}',
                                       "application/json")
                            return
                        self._send(200, json.dumps(payload).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found", "text/plain")
                except Exception as e:  # noqa: BLE001 — surfaced as 500
                    self._send(500, json.dumps({"error": str(e)}).encode(),
                               "application/json")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dashboard", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

    # -- data -------------------------------------------------------------

    def section(self, name: str) -> list[dict]:
        return [_summarize(o) for o in self.store.list(_SECTIONS[name])]

    def detail(self, section: str, namespace: str, name: str) -> Optional[dict]:
        """Full object dump + its events (the kubectl-describe surface the
        upstream web apps render per object)."""
        from ..controlplane.controller import events_for

        obj = self.store.try_get(_SECTIONS[section], name, namespace)
        if obj is None:
            return None
        events = [
            {"reason": e.reason, "message": e.message, "type": e.type,
             "timestamp": e.timestamp}
            for e in events_for(self.store, _SECTIONS[section], name)
            if e.metadata.namespace == namespace
        ]
        out = {"object": obj.model_dump(mode="json"), "events": events}
        if section == "experiments" and self.db is not None:
            out["curves"] = self.curves(namespace, name)
        return out

    def curves(self, namespace: str, name: str) -> Optional[dict]:
        """Per-trial objective curves, step-ordered for plotting (the
        Katib UI experiment-curves view); needs the observation DB.
        Returns None (HTTP 404) when no DB is attached — the payload
        schema is trial-name -> points, so an inline error object would
        masquerade as a trial."""
        if self.db is None:
            return None
        rows = self.db.get_observation_log(name, namespace=namespace)
        curves: dict[str, list] = {}
        for r in rows:
            curves.setdefault(r.get("trial", "?"), []).append({
                k: r[k] for k in ("step", "value", "phase", "assignments")
                if k in r
            })
        return curves

    def pod_logs(self, namespace: str, name: str) -> str:
        """Captured stdout/stderr of a pod (the kubectl-logs surface)."""
        if self.log_path_for is None:
            return "(no log source attached)"
        try:
            with open(self.log_path_for(namespace, name)) as f:
                return f.read()
        except OSError as e:
            return f"(no logs: {e})"

    def overview(self) -> dict:
        return {name: len(self.store.list(kind))
                for name, kind in _SECTIONS.items()}

    def index_html(self) -> str:
        parts = ["<html><head><title>kubeflow-tpu</title></head><body>",
                 "<h1>kubeflow-tpu dashboard</h1>"]
        for name in _SECTIONS:
            if name in ("events", "pods"):
                continue  # noisy sections stay API-only, like upstream
            rows = self.section(name)
            parts.append(f"<h2>{name} ({len(rows)})</h2><ul>")
            for r in rows:
                label = html.escape(f"{r['namespace']}/{r['name']}")
                phase = html.escape(_phase_of(r))
                parts.append(f"<li>{label} — {phase}</li>")
            parts.append("</ul>")
        parts.append("</body></html>")
        return "".join(parts)
