"""Pure trace generation + scoring shared by the live bench and the twin.

Moved verbatim from ``scripts/autoscale_bench.py`` (ISSUE 20): the
fleet-scale digital twin replays the SAME seeded diurnal trace through
the SAME scorer as the live-engine bench, so a twin score and a bench
score are comparable row for row.  ``autoscale_bench`` re-exports these
names (``tests/test_autoscale.py`` imports them from there), and
``diurnal_policy`` is the one shared :class:`AutoscalePolicy`
constructor both sides use — the parity test pins that the twin's
decision sequence equals the live replay's because it IS the same
``decide()`` under the same policy.

Everything here is pure and seeded — no wall clock, no process rng —
the ``wall-clock-in-policy`` analyzer rule lints this package.
"""

from __future__ import annotations

import math

#: QoS classes: (engine priority tier, diurnal peak phase in day
#: fractions, share of total traffic, SLO in compressed wall seconds).
#: Distinct peak phases are what makes the trace MULTI-tenant: the
#: fleet-wide rate is the sum of three out-of-phase sinusoids, so
#: static provisioning cannot sit at any single tenant's peak.
CLASSES = {
    "gold": {"priority": 0, "phase": 0.35, "share": 0.25, "slo_s": 2.0},
    "silver": {"priority": 1, "phase": 0.55, "share": 0.35, "slo_s": 4.0},
    "bronze": {"priority": 2, "phase": 0.80, "share": 0.40, "slo_s": 8.0},
}


def diurnal_arrivals(seed: int, duration_s: float, day_s: float, *,
                     peak_rps: float = 14.0, trough_rps: float = 1.0,
                     bursts: int = 2, burst_mult: float = 4.0,
                     burst_len_s: float = 1.0,
                     classes=None) -> list:
    """Seeded non-homogeneous Poisson arrivals: per class, rate(t) =
    share * (trough + (peak-trough) * (1+sin(2pi(t/day - phase)))/2),
    plus ``bursts`` seeded spikes multiplying one random class's rate
    by ``burst_mult`` for ``burst_len_s``.  Returns a time-sorted list
    of ``(t, class_name)`` — deterministic for a given seed.
    """
    import numpy as np

    classes = classes or CLASSES
    rng = np.random.default_rng(seed)
    spikes = [(rng.uniform(0.1, 0.9) * duration_s,
               list(classes)[rng.integers(0, len(classes))])
              for _ in range(bursts)]
    out = []
    dt = 0.02
    steps = int(duration_s / dt)
    for cls, spec in classes.items():
        for k in range(steps):
            t = k * dt
            wave = (1.0 + math.sin(
                2 * math.pi * (t / day_s - spec["phase"]))) / 2.0
            rate = spec["share"] * (
                trough_rps + (peak_rps - trough_rps) * wave)
            for t0, scls in spikes:
                if scls == cls and t0 <= t < t0 + burst_len_s:
                    rate *= burst_mult
            for _ in range(rng.poisson(rate * dt)):
                out.append((t + rng.uniform(0, dt), cls))
    out.sort()
    return out


def chip_seconds(trace: list, end_s: float) -> float:
    """Integrate a step-function replica trace ``[(t, replicas), ...]``
    (time-sorted, first entry at t<=0) to chip-seconds over [0, end]."""
    total = 0.0
    for i, (t, n) in enumerate(trace):
        t_next = trace[i + 1][0] if i + 1 < len(trace) else end_s
        total += max(0.0, min(t_next, end_s) - max(t, 0.0)) * n
    return total


def static_replicas_for(chips: float, duration_s: float) -> int:
    """The equal-chip-seconds baseline: the constant fleet size that
    spends the same chip budget over the same window."""
    return max(1, round(chips / max(duration_s, 1e-9)))


def slo_attainment(latencies: dict, classes=None) -> dict:
    """Per-class fraction of requests with e2e latency <= the class
    SLO.  ``latencies`` maps class -> list of e2e seconds (a dropped
    request must be recorded as +inf by the caller — absence would
    inflate the score)."""
    classes = classes or CLASSES
    out = {}
    for cls, spec in classes.items():
        xs = latencies.get(cls, [])
        out[cls] = (sum(1 for x in xs if x <= spec["slo_s"]) / len(xs)
                    if xs else 1.0)
    return out


def diurnal_policy():
    """The ONE diurnal-bench :class:`AutoscalePolicy` — constructed
    here so ``autoscale_bench.py`` (live engines) and the twin's
    diurnal scenario provably run the identical policy: the parity
    test (same signals -> same ``decide()`` actions in the same order)
    is only meaningful because neither side can drift a band on its
    own.

    target_concurrency is deliberately fractional: the tiny CPU
    engines drain requests in tens of milliseconds, so "hot" for this
    fleet is half a live request per replica — the bands and the
    diurnal wave do the rest, exactly as they would at real scale.
    horizon_s ~ the measured cold start: the predictor must lead by
    at least the time a new replica takes to warm, or every scale-up
    lands after the wave it was meant to absorb.
    """
    from kubeflow_tpu.serving.autoscale import AutoscalePolicy

    return AutoscalePolicy(
        target_concurrency=0.5, window_s=3.0, horizon_s=3.0,
        high_band=1.1, low_band=0.35, loop_s=0.25,
        up_cooldown_s=0.5, down_cooldown_s=3.0)
