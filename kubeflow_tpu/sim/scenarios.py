"""The scenario catalog: seeded outage stories, scored as JSON rows.

Each scenario builds a :class:`~kubeflow_tpu.sim.core.Simulator`, a
:class:`~kubeflow_tpu.sim.fleet.SimFleet` around the real policy
objects, runs a seeded story, and returns one deterministic score
dict (SLO attainment through the shared
:func:`~kubeflow_tpu.sim.traces.slo_attainment` scorer, shed/failed
counts, retry amplification, exactly-once outage detection, leaked
state).  :func:`score_json` serializes a score byte-stably — same
scenario + same seed = the same bytes, which is the twin's regression
contract: a policy change that shifts a score shows up as a diff, not
a flake.

Catalog rows (``scripts/twin_bench.py`` runs them; tests mark the
fleet-scale ones ``slow``):

- ``smoke``        — door -> route -> decide -> actuate in one breath
- ``diurnal``      — the bench's multi-tenant day (4 .. 500 replicas)
- ``domain_outage``— zone loss + thundering-herd re-route at 100+
  replicas: PR 16's amplification <= 1.2 and exactly-once invariants
- ``cold_start_storm`` — scale-to-zero wake storms under the r21
  warm/cold EWMAs
- ``noisy_neighbor``   — one flooding tenant vs the QoS door
- ``chaos_fleet``  — a seeded :class:`FaultPlan` (domain outage +
  actuator failures) replayed as sim events
"""

from __future__ import annotations

import json
import random

from ..serving.autoscale import AutoscalePolicy
from ..utils.stats import round_floats
from .core import Simulator
from .fleet import PhaseCosts, SimFleet
from .traces import (
    CLASSES,
    chip_seconds,
    diurnal_arrivals,
    diurnal_policy,
    slo_attainment,
)

#: fleet-scale knobs: slower modeled replicas (so queueing dynamics
#: dominate, ~1.1 s mean service) and a policy that can actually ramp
#: hundreds of replicas inside a compressed window.
FLEET_COST_SCALE = 10.0


def fleet_policy(**over) -> AutoscalePolicy:
    kw = dict(target_concurrency=1.0, window_s=5.0, horizon_s=5.0,
              high_band=1.05, low_band=0.4, loop_s=0.25,
              up_cooldown_s=0.25, down_cooldown_s=2.0,
              emergency_surge=10)
    kw.update(over)
    return AutoscalePolicy(**kw)


def _burst_arrivals(seed: int, windows, rate: float,
                    classes=None) -> list:
    """Seeded Poisson arrivals confined to ``windows`` ([(t0, t1)...])
    — the wake-storm trace: silence, then a wall of demand."""
    rng = random.Random(seed)
    names = list(classes or CLASSES)
    out = []
    for (t0, t1) in windows:
        t = t0
        while True:
            t += rng.expovariate(rate)
            if t >= t1:
                break
            out.append((t, names[rng.randrange(len(names))]))
    out.sort()
    return out


def _run(sim: Simulator, fleet: SimFleet, arrivals, auto, *,
         duration_s: float, session_pool: int = 0,
         record_decisions=None) -> None:
    """Schedule the trace + the autoscaler tick cadence, run to
    ``duration_s``, then drain in-flight work to terminal states (the
    grace window is the client deadline — anything still live after
    it is a leak the score reports)."""
    for i, (t, cls) in enumerate(arrivals):
        session = f"s{i % session_pool}" if session_pool else ""
        sim.at(t, lambda cls=cls, session=session:
               fleet.submit(cls, session=session))
    if auto is not None:
        def tick():
            dec = auto.tick()
            if record_decisions is not None:
                record_decisions.append(
                    (round(sim.now, 6), dec.action, dec.reason))
        sim.every(auto.policy.loop_s, tick, until=duration_s)
    sim.run(until=duration_s)
    sim.run(until=duration_s + fleet.request_timeout_s + 1.0)


def _score(name: str, seed: int, sim: Simulator, fleet: SimFleet,
           auto=None, extra: dict | None = None) -> dict:
    sc = {
        "scenario": name,
        "seed": seed,
        "duration_s": sim.now,
        "events": sim.events_run,
        "replicas_peak": max(n for _, n in fleet.replica_trace),
        "chip_seconds": chip_seconds(fleet.replica_trace, sim.now),
        "requests_total": len(fleet.requests),
        "admitted": fleet.admitted,
        "completed": fleet.completed,
        "shed": dict(sorted(fleet.shed.items())),
        "failed": dict(sorted(fleet.failed.items())),
        "slo_attainment": slo_attainment(fleet.latencies),
        "retry_amplification": fleet.forwards / max(fleet.admitted, 1),
        "retries_granted": fleet.retries_granted,
        "domain_outages_total": fleet.router.domain_outages_total,
        "leaked": fleet.leaked(),
    }
    if auto is not None:
        sc["decisions"] = {a: n for a, n
                           in sorted(auto.decisions_total.items()) if n}
        sc["actuator_failures_total"] = auto.actuator_failures_total
        sc["emergency_bypass_total"] = auto.emergency_bypass_total
    if extra:
        sc.update(extra)
    return round_floats(sc)


def score_json(score: dict) -> str:
    """The byte-stable serialization of one score row — sorted keys,
    rounded floats, no incidental whitespace.  Two runs of the same
    (scenario, seed, knobs) must produce identical bytes."""
    return json.dumps(round_floats(score), sort_keys=True,
                      separators=(",", ":"))


# -- catalog rows ---------------------------------------------------------

def scenario_smoke(seed: int = 0, replicas: int = 2, **kw) -> dict:
    """The tier-1 breath: a short burst through the REAL door
    (bounded concurrency forces queueing), REAL routing, and a REAL
    autoscaler that fires at least one actuation — door -> route ->
    decide -> actuate end to end in well under a second of wall."""
    sim = Simulator(seed)
    qos = {"gold": {"priority": 0, "max_concurrent": 3,
                    "queue_depth": 16}}
    fleet = SimFleet(sim, max_replicas=max(replicas, 2),
                     qos=qos, tenants={"gold": "gold"})
    fleet.add_replica()
    sim.run(until=2.0)
    policy = diurnal_policy()
    decisions: list = []
    auto = fleet.make_autoscaler(policy)
    arrivals = _burst_arrivals(seed + 1, [(0.2, 2.2)], 30.0,
                               classes=("gold",))
    _run(sim, fleet, arrivals, auto, duration_s=4.0,
         record_decisions=decisions)
    return _score("smoke", seed, sim, fleet, auto, extra={
        "scaled_up": int(auto.decisions_total.get("scale_up", 0) > 0),
    })


def scenario_diurnal(seed: int = 0, replicas: int = 4,
                     duration_s: float | None = None,
                     day_s: float | None = None,
                     record_signals=None, record_decisions=None,
                     **kw) -> dict:
    """The bench's multi-tenant diurnal day.  At <= 8 replicas this is
    the PARITY configuration: the exact ``diurnal_policy()`` and trace
    shape ``autoscale_bench.py`` replays on live engines, so the
    recorded (signal, decision) stream is directly comparable.  Above
    that it is the fleet-scale row — slower modeled replicas, a policy
    that ramps hundreds of replicas, arrival rate proportional to the
    fleet."""
    sim = Simulator(seed)
    small = replicas <= 8
    duration = duration_s or (20.0 if small else 90.0)
    day = day_s or duration
    if small:
        policy = diurnal_policy()
        fleet = SimFleet(sim, max_replicas=replicas)
        arrivals = diurnal_arrivals(seed, duration, day)
        fleet.add_replica()
    else:
        policy = fleet_policy()
        fleet = SimFleet(sim, max_replicas=replicas, domains=8,
                         costs=PhaseCosts(scale=FLEET_COST_SCALE))
        arrivals = diurnal_arrivals(seed, duration, day,
                                    peak_rps=replicas * 0.8,
                                    trough_rps=replicas * 0.02)
        fleet.warm_cache_seeded = True
        for _ in range(max(replicas // 4, 1)):
            fleet.add_replica()
    sim.run(until=3.0)
    auto = fleet.make_autoscaler(policy, record=record_signals)
    _run(sim, fleet, arrivals, auto, duration_s=3.0 + duration,
         record_decisions=record_decisions)
    return _score("diurnal", seed, sim, fleet, auto, extra={
        "replicas_cap": replicas,
        "arrivals": len(arrivals),
    })


def scenario_domain_outage(seed: int = 0, replicas: int = 100,
                           domains: int = 4,
                           duration_s: float = 20.0,
                           outage_at: float = 6.0, **kw) -> dict:
    """Zone loss at fleet scale: one failure domain (replicas/domains
    backends) dies whole mid-storm.  The real circuits must detect it,
    the real mass-forget must fire EXACTLY once, the herd of re-routes
    must stay inside the real retry budget's amplification bound
    (PR 16's invariants at 100x the live harness's replica count) and
    no request may hang or point at a corpse afterwards."""
    sim = Simulator(seed)
    fleet = SimFleet(sim, max_replicas=int(replicas * 1.2) + 1,
                     domains=domains,
                     costs=PhaseCosts(scale=FLEET_COST_SCALE))
    fleet.warm_cache_seeded = True
    for _ in range(replicas):
        fleet.add_replica()
    sim.run(until=2.0)
    auto = fleet.make_autoscaler(fleet_policy())
    rate = replicas * 1.5
    arrivals = _burst_arrivals(seed + 1, [(0.0, duration_s)], rate)
    victim = fleet.domain_names[0]
    sim.at(outage_at, lambda: fleet.kill_domain(victim))
    _run(sim, fleet, arrivals, auto, duration_s=2.0 + duration_s,
         session_pool=replicas * 3)
    return _score("domain_outage", seed, sim, fleet, auto, extra={
        "replicas": replicas,
        "domains": domains,
        "outage_domain": victim,
        "outage_at_s": outage_at,
    })


def scenario_cold_start_storm(seed: int = 0, replicas: int = 8,
                              **kw) -> dict:
    """Scale-to-zero wake storms: demand arrives in walls separated by
    idle gaps longer than ``idle_zero_s``, so the fleet hibernates
    between them and every wall pays a wake.  The first boot ever is
    AOT-cache-cold; the wakes ride the warm path — the REAL r21
    warm/cold EWMA split budgets the zero gate, and the door queue
    absorbs (or sheds) the wall while the replica warms."""
    sim = Simulator(seed)
    policy = AutoscalePolicy(
        target_concurrency=0.5, window_s=2.0, horizon_s=2.0,
        high_band=1.1, low_band=0.35, loop_s=0.1,
        up_cooldown_s=0.2, down_cooldown_s=0.5,
        scale_to_zero=True, idle_zero_s=1.5,
        cold_start_budget_s=5.0, zero_cooldown_s=1.0)
    fleet = SimFleet(sim, max_replicas=replicas, min_replicas=0,
                     queue_timeout_s=5.0)
    fleet.add_replica()
    sim.run(until=2.0)
    auto = fleet.make_autoscaler(policy)
    windows = [(5.0, 9.0), (16.0, 20.0), (27.0, 31.0)]
    arrivals = _burst_arrivals(seed + 1, windows, 8.0)
    _run(sim, fleet, arrivals, auto, duration_s=2.0 + 34.0)
    return _score("cold_start_storm", seed, sim, fleet, auto, extra={
        "wakes": fleet.wakes,
        "zero_decisions": auto.decisions_total.get("scale_to_zero", 0),
        "cold_starts": len(fleet.cold_samples),
        "cold_starts_warm": sum(1 for _, w in fleet.cold_samples if w),
        "cold_start_ewma_s": auto.cold_start_s,
        "cold_start_warm_ewma_s": auto.cold_start_warm_s,
    })


def scenario_noisy_neighbor(seed: int = 0, replicas: int = 6,
                            duration_s: float = 15.0, **kw) -> dict:
    """One tenant floods at 10x its share; the REAL QoS door (token
    buckets + bounded per-class queues + priority tiers) must shed the
    flood at the rate limit while gold's SLO attainment holds — the
    isolation story the door exists to tell."""
    sim = Simulator(seed)
    qos = {
        "gold": {"priority": 0},
        "silver": {"priority": 1, "rate": 40.0, "burst": 40.0},
        "bronze": {"priority": 2, "rate": 12.0, "burst": 12.0,
                   "max_concurrent": 10, "queue_depth": 8},
    }
    fleet = SimFleet(sim, max_replicas=replicas, qos=qos,
                     tenants={"noisy": "bronze"})
    fleet.warm_cache_seeded = True
    for _ in range(replicas):
        fleet.add_replica()
    sim.run(until=1.0)
    auto = fleet.make_autoscaler(diurnal_policy())
    arrivals = diurnal_arrivals(seed, duration_s, duration_s,
                                peak_rps=10.0)
    noisy = [(t, "noisy") for (t, _c) in _burst_arrivals(
        seed + 2, [(2.0, duration_s)], 120.0, classes=("noisy",))]
    trace = sorted(arrivals + noisy)
    _run(sim, fleet, trace, auto, duration_s=1.0 + duration_s)
    plane_stats = fleet.plane.stats()["classes"]
    return _score("noisy_neighbor", seed, sim, fleet, auto, extra={
        "noisy_arrivals": len(noisy),
        "noisy_shed": fleet.shed.get("rate_limited", 0)
        + fleet.shed.get("queue_full", 0)
        + fleet.shed.get("queue_timeout", 0),
        "door_classes": {
            name: {k: v for k, v in sorted(st.items())
                   if k != "qos_live"}
            for name, st in sorted(plane_stats.items())},
    })


def scenario_chaos_fleet(seed: int = 0, replicas: int = 50,
                         domains: int = 5,
                         duration_s: float = 25.0, **kw) -> dict:
    """Chaos at fleet scope: a seeded :class:`FaultPlan` — the same
    plan object the live chaos harness drives — replayed as sim
    events.  A seeded domain dies for a window and comes back; seeded
    autoscale actuator failures hit the real bounded-retry/park
    machinery via the plan's failpoint.  The fleet must survive: no
    hung requests, bounded amplification, every injected fault
    consumed."""
    from ..chaos.plan import FaultPlan

    sim = Simulator(seed)
    fleet = SimFleet(sim, max_replicas=int(replicas * 1.3) + 1,
                     domains=domains,
                     costs=PhaseCosts(scale=FLEET_COST_SCALE))
    fleet.warm_cache_seeded = True
    for _ in range(replicas):
        fleet.add_replica()
    sim.run(until=2.0)

    outage_window = 9.0
    plan = (FaultPlan(seed)
            .domain_outage(fleet.domain_names, min_at=4.0, max_at=10.0,
                           duration=outage_window)
            .autoscale_actuator_fail("replica_up", times=2))
    plan.activate(now=sim.now)
    auto = fleet.make_autoscaler(fleet_policy(),
                                 failpoint=plan.autoscale_failpoint())
    fired: list = []

    def poll_faults():
        for d in plan.due_domain_outages(now=sim.now):
            fired.append((round(sim.now, 6), d))
            fleet.kill_domain(d)
            sim.after(outage_window, lambda d=d: fleet.revive_domain(d))
    sim.every(0.1, poll_faults, until=2.0 + duration_s)

    rate = replicas * 1.6
    arrivals = _burst_arrivals(seed + 1, [(0.0, duration_s)], rate)
    _run(sim, fleet, arrivals, auto, duration_s=2.0 + duration_s,
         session_pool=replicas * 2)
    return _score("chaos_fleet", seed, sim, fleet, auto, extra={
        "replicas": replicas,
        "domains": domains,
        "faults_fired": fired,
        "autoscale_faults_pending": len(plan.due_autoscale_fails()),
    })


SCENARIOS = {
    "smoke": scenario_smoke,
    "diurnal": scenario_diurnal,
    "domain_outage": scenario_domain_outage,
    "cold_start_storm": scenario_cold_start_storm,
    "noisy_neighbor": scenario_noisy_neighbor,
    "chaos_fleet": scenario_chaos_fleet,
}


def run_scenario(name: str, seed: int = 0,
                 replicas: int | None = None, **kw) -> dict:
    """Run one catalog row; ``replicas`` overrides the scenario's
    default scale.  Returns the deterministic score dict (pass it to
    :func:`score_json` for the byte-stable row)."""
    fn = SCENARIOS.get(name)
    if fn is None:
        raise KeyError(
            f"unknown scenario {name!r} (one of {sorted(SCENARIOS)})")
    if replicas is not None:
        kw["replicas"] = replicas
    return fn(seed=seed, **kw)
