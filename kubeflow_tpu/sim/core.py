"""Virtual clock + seeded discrete-event loop — the twin's heartbeat.

The whole point of the twin (ISSUE 20) is that the *decisions* come
from the real production objects and only the *physics* (time, network,
engine service) is modeled.  That works because every policy surface
grew a ``clock=``/``rng=`` seam this PR: a :class:`VirtualClock` is a
zero-arg callable, so ``TokenBucket(..., clock=sim.clock)`` or
``Router(..., clock=sim.clock, rng=sim.rng)`` makes the real circuit
breakers, retry budgets, coalescing windows and cooldowns tick in
simulated seconds.  A 24h diurnal cycle replays in wall milliseconds,
and two runs with the same seed are byte-identical.

No wall clock, no process rng anywhere in this package — the
``wall-clock-in-policy`` analyzer rule fails the build if one sneaks
in.
"""

from __future__ import annotations

import heapq
import random
from typing import Callable, Optional


class VirtualClock:
    """A monotonically advancing simulated clock.  Instances are
    zero-arg callables returning seconds-as-float, drop-in for the
    ``clock=time.monotonic`` seams across serving/."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    __call__ = now

    def advance_to(self, t: float) -> None:
        """Jump forward (never backward — simulated time is monotonic
        by construction, which is what lets the real cooldown/circuit
        arithmetic run unmodified)."""
        if t > self._now:
            self._now = t


class Simulator:
    """Seeded discrete-event loop over a :class:`VirtualClock`.

    Events are ``(time, seq, fn)`` on a heap; ``seq`` is a monotonic
    tiebreaker so same-instant events run in scheduling order —
    determinism does not hinge on heap internals or callable identity.
    ``fn`` takes no arguments and may schedule further events.

    One ``random.Random(seed)`` instance is threaded through every
    modeled cost AND every real policy object's ``rng=`` seam, so the
    full interleaving — arrival jitter, service noise, probe jitter,
    retry spread — replays exactly from the seed.
    """

    def __init__(self, seed: int = 0, start: float = 0.0):
        self.clock = VirtualClock(start)
        self.rng = random.Random(seed)
        self.seed = seed
        self.events_run = 0
        self._heap: list = []
        self._seq = 0

    @property
    def now(self) -> float:
        return self.clock.now()

    def at(self, t: float, fn: Callable[[], None]) -> None:
        """Schedule ``fn`` at simulated time ``t`` (clamped to now —
        the past is not schedulable)."""
        heapq.heappush(self._heap, (max(t, self.clock.now()),
                                    self._seq, fn))
        self._seq += 1

    def after(self, dt: float, fn: Callable[[], None]) -> None:
        self.at(self.clock.now() + max(dt, 0.0), fn)

    def every(self, period: float, fn: Callable[[], None], *,
              until: float) -> None:
        """Schedule ``fn`` at ``now+period, now+2*period, ...`` up to
        ``until`` — the autoscaler tick cadence, made explicit events
        instead of a thread loop."""
        def tick():
            fn()
            if self.clock.now() + period <= until:
                self.after(period, tick)
        self.after(period, tick)

    def run(self, until: Optional[float] = None,
            max_events: int = 20_000_000) -> int:
        """Drain events in time order up to ``until`` (inclusive);
        returns the number of events run.  ``max_events`` is a runaway
        backstop — a scenario that hits it is a bug, not a workload."""
        n = 0
        while self._heap and n < max_events:
            t = self._heap[0][0]
            if until is not None and t > until:
                break
            _, _, fn = heapq.heappop(self._heap)
            self.clock.advance_to(t)
            fn()
            n += 1
        if until is not None:
            self.clock.advance_to(until)
        self.events_run += n
        return n
