"""The modeled half of the twin: replicas, network, cold starts.

Everything that *decides* here is a real production object — the
:class:`~kubeflow_tpu.serving.controller.Router` (smooth-WRR pools,
health circuits, retry budget, domain mass-forget, prefix/session
affinity), the :class:`~kubeflow_tpu.serving.traffic.TrafficPlane`
door (:func:`door_decision` via ``offer``/``promote``/``abandon``),
and the :class:`~kubeflow_tpu.serving.autoscale.ClusterAutoscaler`
(``decide`` + cooldowns + emergency surge), all constructed on the
simulator's virtual clock and seeded rng.  Everything that *costs*
is modeled: request service times come from per-phase distributions
(queue/prefill/decode/handoff — the r17 phase-histogram tiles that
sum to e2e), cold starts from a warm/cold pair of distributions (the
r21 AOT split), and re-route hops from the handler's jitter window.

The fleet mirrors the live wiring faithfully enough that its failure
behavior is the production behavior: a killed replica's in-flight
requests take the handler's retry path (``_backend_down`` -> budget
``try_retry`` -> re-pick with ``exclude``/``avoid_domains``), so PR
16's amplification bound and exactly-once outage detection are
exercised on the REAL circuit/budget/mass-forget code at 100x the
replica count the live harness can afford.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

from ..serving.autoscale import AutoscalePolicy, ClusterAutoscaler
from ..serving.controller import Router
from ..serving.traffic import TrafficPlane, jittered_retry_after
from .core import Simulator


class PhaseCosts:
    """Per-phase service-time model, the r17 histogram tiles as
    distributions: ``handoff`` (queue->slot + detokenize/transfer,
    per request), ``prefill`` (per prompt token), ``decode`` (per
    generated token).  A sample is the tile sum times a lognormal
    noise factor — seeded rng in, deterministic sample out.  The
    defaults approximate the tiny-engine CPU stand-in the serving
    benches run; ``scale`` stretches all tiles together (fleet-scale
    scenarios use slower "replicas" so queueing dynamics dominate)."""

    def __init__(self, handoff_s: float = 0.004,
                 prefill_tok_s: float = 0.0015,
                 decode_tok_s: float = 0.006,
                 sigma: float = 0.25, scale: float = 1.0):
        self.handoff_s = handoff_s * scale
        self.prefill_tok_s = prefill_tok_s * scale
        self.decode_tok_s = decode_tok_s * scale
        self.sigma = sigma

    def sample(self, rng, prompt_tokens: int, new_tokens: int) -> float:
        base = (self.handoff_s + self.prefill_tok_s * prompt_tokens
                + self.decode_tok_s * new_tokens)
        return base * math.exp(rng.gauss(0.0, self.sigma))

    @classmethod
    def from_phase_totals(cls, totals: dict, *, prompt_tokens: int = 8,
                          new_tokens: int = 16,
                          sigma: float = 0.25) -> "PhaseCosts":
        """Calibrate the tiles from a live run's r17 phase totals
        (``phase -> (count, total_seconds)``, the TraceSink histogram
        aggregate): mean queue+handoff per request, prefill/decode
        normalized per token of the workload they were measured on —
        so the twin's e2e tile sum matches the measured histograms."""
        def mean(ph: str) -> float:
            n, s = totals.get(ph, (0, 0.0))
            return s / n if n else 0.0
        return cls(
            handoff_s=mean("handoff") + mean("queue"),
            prefill_tok_s=(mean("prefill") / max(prompt_tokens, 1))
            or 0.0015,
            decode_tok_s=(mean("decode") / max(new_tokens, 1)) or 0.006,
            sigma=sigma)


class SimRequest:
    """One modeled request moving through the REAL door/route policy.
    ``state`` walks pending -> (queued ->) active -> done, or ends in
    shed/failed; anything non-terminal when the run drains is a LEAK
    (a hung request — the invariant PR 16 pins at live scale)."""

    __slots__ = ("rid", "cls", "tenant", "session", "keys", "t_arrive",
                 "t_done", "state", "attempts", "backend", "ticket",
                 "reason", "prompt_tokens", "new_tokens")

    def __init__(self, rid: int, cls: str, tenant: str, t: float, *,
                 session: str = "", keys=None,
                 prompt_tokens: int = 8, new_tokens: int = 16):
        self.rid = rid
        self.cls = cls
        self.tenant = tenant
        self.session = session
        self.keys = keys or []
        self.t_arrive = t
        self.t_done: Optional[float] = None
        self.state = "pending"
        self.attempts = 0
        self.backend: Optional[str] = None
        self.ticket = None
        self.reason = ""
        self.prompt_tokens = prompt_tokens
        self.new_tokens = new_tokens

    @property
    def terminal(self) -> bool:
        return self.state in ("done", "shed", "failed")


class SimReplica:
    """A modeled engine: ``slots`` concurrent requests, FIFO overflow
    queue (the engine-side queue tile), an epoch counter that
    invalidates scheduled completions when the replica dies — the sim
    analog of a connection reset mid-stream."""

    __slots__ = ("url", "domain", "slots", "state", "epoch",
                 "active", "queue")

    def __init__(self, url: str, domain: str, slots: int):
        self.url = url
        self.domain = domain
        self.slots = slots
        self.state = "warming"   # warming -> up -> draining | down
        self.epoch = 0
        self.active: list[SimRequest] = []
        self.queue: deque = deque()

    @property
    def load(self) -> int:
        return len(self.active) + len(self.queue)


class SimFleet:
    """Replica lifecycle + request transport around the real policy
    objects.  The router is a ``serve=False``
    :class:`~kubeflow_tpu.serving.controller.Router` — the production
    pick/circuit/budget/mass-forget object with no HTTP server — and
    the plane is a real :class:`TrafficPlane`; both tick on the
    simulator's clock and draw jitter from its seeded rng."""

    def __init__(self, sim: Simulator, *, max_replicas: int,
                 min_replicas: int = 1, slots_per_replica: int = 4,
                 domains: int = 0, costs: Optional[PhaseCosts] = None,
                 qos: Optional[dict] = None,
                 tenants: Optional[dict] = None,
                 cold_start_s: float = 1.6, warm_start_s: float = 0.3,
                 queue_timeout_s: float = 2.0,
                 request_timeout_s: float = 10.0,
                 reroute_min_s: float = 0.01,
                 reroute_max_s: float = 0.05):
        self.sim = sim
        self.max_replicas = int(max_replicas)
        self.min_replicas = int(min_replicas)
        self.slots = int(slots_per_replica)
        self.costs = costs or PhaseCosts()
        self.domain_names = [f"zone-{i}" for i in range(int(domains))]
        self.cold_start_s = cold_start_s
        self.warm_start_s = warm_start_s
        self.queue_timeout_s = queue_timeout_s
        self.request_timeout_s = request_timeout_s
        self.reroute_min_s = reroute_min_s
        self.reroute_max_s = reroute_max_s

        self.router = Router(lambda: None, clock=sim.clock,
                             rng=sim.rng, serve=False)
        self.plane = TrafficPlane(qos=qos or {}, tenants=tenants,
                                  clock=sim.clock, rng=sim.rng)
        self.router.set_traffic(self.plane)

        self.replicas: dict[str, SimReplica] = {}
        self._made = 0
        self.pending = 0            # replicas warming (capacity-to-be)
        self.warm_cache_seeded = False   # r21: first boot is cache-cold
        self.requests: list[SimRequest] = []
        self._door_waiting: list[SimRequest] = []
        self._unrouted: list[SimRequest] = []
        self.replica_trace: list[tuple] = [(0.0, 0)]
        self.latencies: dict[str, list] = {}
        self.completed = 0
        self.admitted = 0
        self.forwards = 0           # connect attempts (amplification)
        self.retries_granted = 0
        self.failed: dict[str, int] = {}
        self.shed: dict[str, int] = {}
        self.cold_samples: list[tuple] = []   # (seconds, warm)
        self.wakes = 0
        self._last_arrival = 0.0

    # -- replica lifecycle -------------------------------------------------

    def n_up(self) -> int:
        return sum(1 for r in self.replicas.values()
                   if r.state == "up")

    def n_billed(self) -> int:
        return self.n_up() + self.pending

    def _wire(self) -> None:
        """Keep the real router's membership in lockstep with the UP
        fleet — the controller's ``_wire`` analog.  Dead replicas stay
        wired (matching a controller that has not reconciled yet):
        DETECTING them is the circuits' job, which is the behavior
        under test."""
        urls = [u for u, r in self.replicas.items()
                if r.state in ("up", "down")]
        self.router.set_backends(urls)
        if self.domain_names:
            self.router.set_domains(
                {u: self.replicas[u].domain for u in urls})

    def _trace_point(self) -> None:
        n = self.n_billed()
        if n != self.replica_trace[-1][1]:
            self.replica_trace.append((self.sim.now, n))

    def add_replica(self, on_cold_start=None) -> None:
        """Spawn one replica: it warms off the decision path (the
        bench's ``add_replica_async`` shape) and joins the pools when
        ready.  The first boot ever is AOT-cache-cold; every later
        boot takes the warm path — the r21 split ``note_cold_start``
        tags so the scale-to-zero gate budgets the warm EWMA."""
        if self.n_billed() >= self.max_replicas:
            raise RuntimeError("at max replicas")
        self._made += 1
        url = f"sim://replica-{self._made}"
        domain = ""
        if self.domain_names:
            # zone-aware placement: never schedule INTO a domain that
            # is currently down (the live scheduler's unhealthy-zone
            # avoidance) — otherwise a mid-outage scale-up would plant
            # healthy members in the dead zone and the outage detector
            # could never see the domain fully dark
            down = {r.domain for r in self.replicas.values()
                    if r.state == "down"}
            cands = [d for d in self.domain_names if d not in down]
            cands = cands or self.domain_names
            domain = cands[self._made % len(cands)]
        rep = SimReplica(url, domain, self.slots)
        self.replicas[url] = rep
        self.pending += 1
        warm = self.warm_cache_seeded
        base = self.warm_start_s if warm else self.cold_start_s
        cold = base * math.exp(self.sim.rng.gauss(0.0, 0.2))
        self._trace_point()

        def ready():
            self.pending -= 1
            if rep.state != "warming":     # killed while warming
                return
            rep.state = "up"
            self.warm_cache_seeded = True
            self.cold_samples.append((cold, warm))
            self._wire()
            self._trace_point()
            if on_cold_start is not None:
                on_cold_start(cold, warm=warm)
            self._flush_unrouted()
        self.sim.after(cold, ready)

    def remove_replica(self) -> None:
        """Retire the least-loaded UP replica losslessly: it leaves
        the pools now, finishes its in-flight work, then disappears —
        the drain-through-migration semantics of the live fleet."""
        up = [r for r in self.replicas.values() if r.state == "up"]
        if len(up) <= 1:
            raise RuntimeError("at replica floor")
        victim = min(up, key=lambda r: r.load)
        victim.state = "draining"
        self._wire()
        self._trace_point()
        self._reap_drained(victim)

    def scale_to_zero(self) -> None:
        for rep in list(self.replicas.values()):
            if rep.state == "up":
                rep.state = "draining"
                self._reap_drained(rep)
        self._wire()
        self._trace_point()

    def wake(self, on_cold_start=None) -> None:
        self.wakes += 1
        if self.n_billed() == 0:
            self.add_replica(on_cold_start)

    def _reap_drained(self, rep: SimReplica) -> None:
        if rep.state == "draining" and rep.load == 0:
            self.replicas.pop(rep.url, None)

    def kill_domain(self, domain: str) -> None:
        """Correlated failure: every replica of ``domain`` dies at
        once.  In-flight requests hit the handler's retry path — each
        pays a ``_backend_down`` (circuit evidence) and a budgeted
        re-pick that avoids the failing domain, exactly the live
        storm shape from PR 16."""
        for url, rep in list(self.replicas.items()):
            if rep.domain != domain or rep.state in ("down",):
                continue
            was_warming = rep.state == "warming"
            rep.state = "down"
            rep.epoch += 1
            victims = list(rep.active) + list(rep.queue)
            rep.active.clear()
            rep.queue.clear()
            if was_warming:
                # a replica killed mid-warm-up never became ready, so
                # the controller never wired it — it is a failed
                # creation, not a pool member.  Keeping it wired would
                # plant a zero-traffic corpse whose circuit stays
                # closed forever and the outage detector ("EVERY
                # member open") could never fire.
                del self.replicas[url]
                continue
            for req in victims:
                self.router._note(url, -1, error=True)
                req.state = "retrying"
                self._retry(req, url, {url})
        self._wire()
        self._trace_point()

    def revive_domain(self, domain: str) -> None:
        """The outage window closed: the domain's replicas restart
        (fresh epoch, empty queues) and the next successful forward
        re-arms the outage detector via ``_backend_up``."""
        for rep in self.replicas.values():
            if rep.domain == domain and rep.state == "down":
                rep.state = "up"
                rep.epoch += 1
        self._wire()

    # -- autoscaler wiring -------------------------------------------------

    def signals(self, target_concurrency: float) -> dict:
        """The sensor snapshot, MiniFleet.signals' shape plus the
        fleet-scope keys (``unhealthy_frac`` feeds emergency surge,
        ``idle_s``/``pending`` feed scale-to-zero/wake)."""
        up = [r for r in self.replicas.values() if r.state == "up"]
        live = sum(r.load for r in up)
        pool = self.router.backends()
        open_n = sum(1 for u in pool
                     if self.router.health.state(u) == "open")
        n = len(up) + self.pending
        sig = {
            "replicas": n, "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "util": live / max(len(up), 1)
            / max(target_concurrency, 1e-9),
            "free_block_ratio": 1.0,
            "live": float(live),
            "unhealthy_frac": open_n / max(len(pool), 1),
        }
        if self.min_replicas == 0:
            sig["idle_s"] = self.sim.now - self._last_arrival
            sig["pending"] = float(len(self._unrouted)
                                   + len(self._door_waiting))
        return sig

    def make_autoscaler(self, policy: AutoscalePolicy, *,
                        failpoint=None,
                        record: Optional[list] = None
                        ) -> ClusterAutoscaler:
        """A REAL :class:`ClusterAutoscaler` on the virtual clock,
        actuating this fleet.  ``record`` (if given) collects
        ``(now, raw_signals)`` per tick — the parity test replays
        exactly that stream through a fresh autoscaler to prove the
        twin's decisions come from the production ``decide``/``tick``
        and nothing else."""
        def sensors():
            sig = self.signals(policy.target_concurrency)
            if record is not None:
                record.append((self.sim.now, dict(sig)))
            return sig

        auto = ClusterAutoscaler(
            policy, sensors=sensors, clock=self.sim.clock,
            failpoint=failpoint,
            actuators={
                "replica_up": lambda dec: self._grow(
                    dec, auto.note_cold_start),
                "replica_down": lambda dec: self.remove_replica(),
                "zero": lambda dec: self.scale_to_zero(),
            })
        return auto

    def _grow(self, dec, on_cold_start) -> None:
        if dec.action == "wake":
            self.wakes += 1
        want = max(int(dec.replicas or 0) - self.n_billed(), 1)
        for _ in range(want):
            if self.n_billed() >= self.max_replicas:
                break
            self.add_replica(on_cold_start)

    # -- the request path --------------------------------------------------

    def submit(self, cls: str, *, tenant: Optional[str] = None,
               session: str = "", keys=None,
               prompt_tokens: int = 8,
               new_tokens: int = 16) -> SimRequest:
        """One arrival: real door (``offer``), then real route
        (``Router._pick``), then modeled service.  Every request is
        bounded by ``request_timeout_s`` — the client deadline — so a
        hung request shows up as a failed row, never a stuck event."""
        now = self.sim.now
        self._last_arrival = now
        req = SimRequest(len(self.requests), cls, tenant or cls, now,
                         session=session, keys=keys,
                         prompt_tokens=prompt_tokens,
                         new_tokens=new_tokens)
        self.requests.append(req)
        self.latencies.setdefault(cls, [])
        ticket = self.plane.offer(req.tenant)
        req.ticket = ticket
        if ticket.ok:
            self.admitted += 1
            self._route(req)
        elif ticket.reason == "queued":
            req.state = "queued"
            self._door_waiting.append(req)
            self.sim.after(self.queue_timeout_s,
                           lambda: self._door_timeout(req))
        else:
            self._shed(req, ticket.reason)
        if not req.terminal:
            self.sim.after(self.request_timeout_s,
                           lambda: self._client_deadline(req))
        return req

    def _client_deadline(self, req: SimRequest) -> None:
        """The client's end-to-end deadline, enforced at every stage:
        a request still door-queued, unrouted, engine-queued or even
        mid-service when the deadline passes is a hung-up client, not
        a forever-parked event.  Without this, one hotspotted replica
        (sticky sessions all rebinding to the same survivor during an
        outage) parks a queue of requests past the end of the run and
        the leak audit cannot tell a slow drain from a true hang."""
        if req.terminal:
            return
        if req.state == "queued":
            self._door_timeout(req)
            return
        if req in self._unrouted:
            self._unrouted.remove(req)
        rep = self.replicas.get(req.backend) if req.backend else None
        if rep is not None:
            if req in rep.queue:
                rep.queue.remove(req)
                self.router._note(rep.url, -1)
            elif req in rep.active:
                rep.active.remove(req)
                self.router._note(rep.url, -1)
                if rep.queue and rep.state in ("up", "draining"):
                    self._begin(rep, rep.queue.popleft())
                self._reap_drained(rep)
        self._fail(req, "deadline_exceeded")

    def _shed(self, req: SimRequest, reason: str) -> None:
        req.state = "shed"
        req.reason = reason
        req.t_done = self.sim.now
        self.shed[reason] = self.shed.get(reason, 0) + 1
        self.latencies[req.cls].append(float("inf"))

    def _fail(self, req: SimRequest, reason: str) -> None:
        req.state = "failed"
        req.reason = reason
        req.t_done = self.sim.now
        self.failed[reason] = self.failed.get(reason, 0) + 1
        self.latencies[req.cls].append(float("inf"))
        self._release(req)

    def _release(self, req: SimRequest) -> None:
        if req.ticket is not None and req.ticket.ok:
            self.plane.release(req.ticket)
            req.ticket = None
            self._drain_door()

    def _door_timeout(self, req: SimRequest) -> None:
        if req.state != "queued":
            return
        self.plane.abandon(req.ticket)
        if req in self._door_waiting:
            self._door_waiting.remove(req)
        self._shed(req, "queue_timeout")

    def _drain_door(self) -> None:
        """A slot freed: promote door-queued arrivals (head-of-class
        rule enforced by the plane itself) in arrival order."""
        progressed = True
        while progressed:
            progressed = False
            for req in list(self._door_waiting):
                if req.state != "queued":
                    self._door_waiting.remove(req)
                    continue
                if self.plane.promote(req.ticket):
                    self._door_waiting.remove(req)
                    req.state = "pending"
                    self.admitted += 1
                    self._route(req)
                    progressed = True

    def _route(self, req: SimRequest) -> None:
        # only an unrouted request may route: the no-backend client
        # retry and the replica-ready flush can both fire for the same
        # request — whichever lands second must no-op, or the request
        # would be double-forwarded (double-booked slots, torn counts)
        if req.state != "pending":
            return
        backend = self.router._pick(keys=req.keys,
                                    session=req.session or None)
        if backend is None:
            # no ready replicas: the live router 503s with Retry-After
            # and pokes the activator; the modeled client re-tries on
            # that hint until its deadline
            self.router.no_backend_total += 1
            if req not in self._unrouted:
                self._unrouted.append(req)
            if self.sim.now - req.t_arrive >= self.request_timeout_s:
                self._unrouted.remove(req)
                self._fail(req, "no_ready_replicas")
                return
            self.sim.after(
                min(jittered_retry_after(0.2, rng=self.sim.rng), 0.5),
                lambda: self._route(req))
            return
        if req in self._unrouted:
            self._unrouted.remove(req)
        self._forward(req, backend, set())

    def _flush_unrouted(self) -> None:
        for req in list(self._unrouted):
            if not req.terminal:
                self._route(req)

    def _forward(self, req: SimRequest, backend: str,
                 tried: set) -> None:
        """One connect attempt — the Handler forward loop's policy on
        modeled transport."""
        if req.terminal:
            return
        self.forwards += 1
        req.attempts += 1
        self.router._note(backend, +1)
        rep = self.replicas.get(backend)
        if rep is None or rep.state not in ("up", "draining"):
            self.router._note(backend, -1, error=True)
            self._retry(req, backend, tried | {backend})
            return
        req.state = "active"
        req.backend = backend
        if len(rep.active) < rep.slots:
            self._begin(rep, req)
        else:
            rep.queue.append(req)

    def _retry(self, req: SimRequest, failed: str, tried: set) -> None:
        """Connection failure: circuit evidence first, then a budgeted
        re-pick that excludes every corpse tried and avoids their
        failure domains — the Handler's exact policy sequence."""
        self.router._backend_down(failed)
        if not self.router.retry_budget.try_retry():
            self._fail(req, "retry_budget_exhausted")
            return
        self.retries_granted += 1
        avoid = {self.router.domain_of(u) for u in tried
                 if self.router.domain_of(u)}
        nxt = self.router._pick(keys=req.keys,
                                session=req.session or None,
                                exclude=tried, avoid_domains=avoid)
        if nxt is None:
            self._fail(req, "no_ready_replicas")
            return
        req.state = "retrying"
        self.sim.after(
            self.sim.rng.uniform(self.reroute_min_s,
                                 self.reroute_max_s),
            lambda: self._forward(req, nxt, tried))

    def _begin(self, rep: SimReplica, req: SimRequest) -> None:
        rep.active.append(req)
        svc = self.costs.sample(self.sim.rng, req.prompt_tokens,
                                req.new_tokens)
        epoch = rep.epoch
        self.sim.after(svc, lambda: self._finish(rep, req, epoch))

    def _finish(self, rep: SimReplica, req: SimRequest,
                epoch: int) -> None:
        if rep.epoch != epoch or req.state != "active":
            return                      # replica died mid-stream
        rep.active.remove(req)
        self.router._note(rep.url, -1)
        self.router._backend_up(rep.url)
        req.state = "done"
        req.t_done = self.sim.now
        self.completed += 1
        self.latencies[req.cls].append(req.t_done - req.t_arrive)
        self._release(req)
        if rep.queue and rep.state in ("up", "draining"):
            self._begin(rep, rep.queue.popleft())
        self._reap_drained(rep)

    # -- audit -------------------------------------------------------------

    def leaked(self) -> dict:
        """End-of-run leak audit: non-terminal requests (hung), and
        affinity/session rows still pointing at dead replicas (state
        the mass-forget should have reclaimed)."""
        hung = sum(1 for r in self.requests if not r.terminal)
        dead = {u for u, r in self.replicas.items()
                if r.state == "down"}
        stale = 0
        for reg in (self.plane.affinity, self.plane.sessions):
            amap = getattr(reg, "_map", {})
            stale += sum(1 for b in amap.values() if b in dead)
        return {"hung_requests": hung, "stale_affinity_rows": stale}
