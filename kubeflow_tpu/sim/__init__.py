"""Fleet-scale digital twin: virtual-clock simulation of the REAL
serving policies (ISSUE 20).

The twin inverts the usual simulator bargain.  Instead of re-modeling
the control logic (and silently drifting from production), it runs the
*production objects* — the router's smooth-WRR pick + health circuits
+ retry budget + domain mass-forget, the traffic plane's
``door_decision`` QoS admission, the autoscaler's ``decide``/``tick``
with cooldowns and emergency surge — on a virtual clock and a seeded
rng, and models only the physics around them: service times from the
r17 phase tiles, cold starts from the r21 warm/cold split, re-route
hops from the handler's jitter window.  A 500-replica day replays in
seconds; the same seed replays the same bytes.

- :mod:`.core`      — :class:`VirtualClock` + seeded event loop
- :mod:`.traces`    — the shared trace/scorer helpers (the live bench
  imports these too: one trace, one scorer, two harnesses)
- :mod:`.fleet`     — modeled replicas/transport around the real
  Router/TrafficPlane/ClusterAutoscaler
- :mod:`.scenarios` — the scored catalog (``scripts/twin_bench.py``)
"""

from .core import Simulator, VirtualClock
from .fleet import PhaseCosts, SimFleet
from .scenarios import SCENARIOS, run_scenario, score_json
from .traces import (
    CLASSES,
    chip_seconds,
    diurnal_arrivals,
    diurnal_policy,
    slo_attainment,
    static_replicas_for,
)

__all__ = [
    "Simulator", "VirtualClock", "PhaseCosts", "SimFleet",
    "SCENARIOS", "run_scenario", "score_json",
    "CLASSES", "chip_seconds", "diurnal_arrivals", "diurnal_policy",
    "slo_attainment", "static_replicas_for",
]
