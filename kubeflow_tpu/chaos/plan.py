"""FaultPlan: a deterministic, seed-driven fault-injection schedule.

The chaos layer that makes every recovery path in this platform testable
without real processes (ISSUE 1 tentpole; SURVEY §4's envtest gap —
restart policies go untested upstream because nothing ever *fails* in
envtest).  A plan is a list of faults with firing conditions; the same
seed always yields the same member choices and the same schedule, so a
failing chaos test reproduces byte-for-byte.

Integration points:

- ``plan.script_fn()`` -> a :class:`~..controlplane.fake_kubelet.ScriptFn`
  for :class:`FakeKubelet`: pod-level faults (crash at t, barrier hang,
  flaky-then-succeed, coordinator kill) become multi-phase
  :class:`PodScript`s, tracked per pod *incarnation* so a fault can hit
  the first N lives of a pod and spare the rest;
- ``FakeKubelet(..., chaos=plan)`` -> cluster-level faults: kubelet
  stalls (the loop stops stepping pods, modelling detection latency) and
  node drains/preemptions (the Node object vanishes and its pods fail
  with the preemption exit code);
- ``plan.socket_wrapper(role)`` -> an injectable wrapper for
  :class:`~..serving.gang.GangChannel` sockets: connection drops and
  send delays on the gang control stream (chaos/net.py).

Times are relative to ``plan.activate()`` (called by the kubelet's
``start()``/first tick, or explicitly by a test).
"""

from __future__ import annotations

import enum
import random
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Callable, Optional

#: the exit code a preempted/drained pod dies with (SIGKILL-style,
#: retryable under RestartPolicy.EXIT_CODE)
PREEMPTION_EXIT_CODE = 137


class FaultKind(str, enum.Enum):
    CRASH = "crash"                  # pod dies at t with exit_code
    BARRIER_HANG = "barrier_hang"    # pod runs but never reaches the barrier
    FLAKY = "flaky"                  # first N incarnations fail, then succeed
    KUBELET_STALL = "kubelet_stall"  # kubelet loop pauses for a window
    NODE_DRAIN = "node_drain"        # node vanishes; its pods are preempted
    SOCKET_DROP = "socket_drop"      # gang control socket dies mid-stream
    SOCKET_DELAY = "socket_delay"    # gang control sends are delayed
    CONTROL_PLANE_CRASH = "control_plane_crash"  # kill -9 at a WAL offset
    REPLICA_KILL = "replica_kill"    # serving replica dies mid-storm
    GANG_MEMBER_LOSS = "gang_member_loss"  # gang member dies, maybe forever
    RESIZE_KILL = "resize_kill"      # elastic resize dies at a phase
    SPILL_TORN = "spill_torn"        # published spill file loses its tail
    SPILL_KILL = "spill_kill"        # process dies mid-spill-write
    TIER_IO_STALL = "tier_io_stall"  # storage-tier I/O wedges for a window
    AUTOSCALE_ACTUATOR_FAIL = "autoscale_actuator_fail"  # actuator dies
    DOMAIN_OUTAGE = "domain_outage"  # failure domain dies at once


@dataclass
class Fault:
    kind: FaultKind
    #: worker replica index the fault targets (pod-level faults)
    index: Optional[int] = None
    #: job-name filter; None = any job
    job: Optional[str] = None
    #: seconds after activation (cluster faults) or after pod start
    #: (pod faults) when the fault fires
    at: float = 0.0
    duration: float = 0.0
    exit_code: int = PREEMPTION_EXIT_CODE
    #: how many pod incarnations the fault applies to (CRASH/FLAKY)
    times: int = 1
    node: Optional[str] = None
    #: "leader" | "follower" — which side's sockets a net fault wraps
    role: str = "follower"
    #: SOCKET_DROP: sendall/recv calls on the wrapped socket before the
    #: drop (None = drop on connect)
    after_calls: Optional[int] = None
    delay: float = 0.0
    #: CONTROL_PLANE_CRASH: bytes of the in-flight WAL record that reach
    #: disk before the machine dies (a torn tail for recovery to chew on)
    torn_bytes: int = 0
    #: bookkeeping: consumed count (pod faults), fired flag (cluster)
    fired: int = field(default=0, compare=False)


class FaultPlan:
    """Seed-driven fault schedule; see module docstring for the hooks."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: list[Fault] = []
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        #: pod-name -> incarnations seen (a new uid = a new life)
        self._lives: dict[str, set[str]] = defaultdict(set)
        #: memoized WalCrashPoint (wal_crashpoint())
        self._crashpoint = None

    # -- builders (chainable) ---------------------------------------------

    def crash_pod(self, index: int, at: float = 0.0,
                  exit_code: int = PREEMPTION_EXIT_CODE, times: int = 1,
                  job: Optional[str] = None) -> "FaultPlan":
        """Worker ``index`` dies ``at`` seconds into its run, for the
        first ``times`` incarnations."""
        self.faults.append(Fault(FaultKind.CRASH, index=index, at=at,
                                 exit_code=exit_code, times=times, job=job))
        return self

    def crash_random_member(self, world: int, at: float = 0.0,
                            exit_code: int = PREEMPTION_EXIT_CODE,
                            times: int = 1,
                            job: Optional[str] = None) -> "FaultPlan":
        """Seeded random gang member dies mid-run — the canonical chaos
        scenario (the choice is frozen at plan-build time, so the same
        seed kills the same rank)."""
        return self.crash_pod(self.rng.randrange(world), at=at,
                              exit_code=exit_code, times=times, job=job)

    def coordinator_kill(self, at: float = 0.0,
                         exit_code: int = PREEMPTION_EXIT_CODE,
                         times: int = 1,
                         job: Optional[str] = None) -> "FaultPlan":
        """Kill rank 0 — the worst member to lose (it is the
        jax.distributed coordinator AND the serving-gang leader)."""
        return self.crash_pod(0, at=at, exit_code=exit_code, times=times,
                              job=job)

    def flaky(self, index: int, failures: int = 1, run_seconds: float = 0.02,
              exit_code: int = PREEMPTION_EXIT_CODE,
              job: Optional[str] = None) -> "FaultPlan":
        """First ``failures`` incarnations of worker ``index`` die early,
        then it behaves — the flapping-node shape that used to trigger a
        fixed-interval restart storm."""
        self.faults.append(Fault(FaultKind.FLAKY, index=index,
                                 at=run_seconds, exit_code=exit_code,
                                 times=failures, job=job))
        return self

    def barrier_hang(self, index: int,
                     job: Optional[str] = None) -> "FaultPlan":
        """Worker ``index`` runs but never reaches its first collective
        barrier (a wedged rendezvous)."""
        self.faults.append(Fault(FaultKind.BARRIER_HANG, index=index, job=job))
        return self

    def kubelet_stall(self, at: float = 0.0,
                      duration: float = 1.0) -> "FaultPlan":
        """The kubelet loop freezes for ``duration`` seconds starting
        ``at`` seconds after activation: pods bound in the window start
        late, failures in the window are detected late."""
        self.faults.append(
            Fault(FaultKind.KUBELET_STALL, at=at, duration=duration))
        return self

    def node_drain(self, node: str, at: float = 0.0) -> "FaultPlan":
        """Node ``node`` vanishes ``at`` seconds after activation
        (preemption/maintenance): its non-terminal pods die with the
        preemption exit code and the gang must re-form elsewhere."""
        self.faults.append(Fault(FaultKind.NODE_DRAIN, node=node, at=at))
        return self

    def socket_drop(self, role: str = "follower",
                    after_calls: Optional[int] = None,
                    times: int = 1) -> "FaultPlan":
        """Drop a gang control-stream socket after ``after_calls``
        send/recv calls (None = at connect) — the follower-reconnect
        scenario.  Applies to the first ``times`` sockets wrapped for
        ``role``; reconnected sockets beyond that are clean."""
        self.faults.append(Fault(FaultKind.SOCKET_DROP, role=role,
                                 after_calls=after_calls, times=times))
        return self

    def kv_migrate_drop(self, after_frames: Optional[int] = None,
                        times: int = 1,
                        max_frames: int = 12) -> "FaultPlan":
        """Kill a live KV migration mid-stream: the next ``times``
        sockets wrapped for role ``"kv_migrate"`` die after
        ``after_frames`` send/recv calls (None = seeded random offset in
        ``[0, max_frames)`` — early kills hit the handshake/kv_begin,
        late ones land mid-block or between commit and ack).  Consume
        via ``sock_wrap=plan.socket_wrapper("kv_migrate")`` on
        ``migrate_sequence`` / ``KvMigrationServer``.  The contract
        under test is copy-then-cutover (ISSUE 8): the source sequence
        keeps decoding, no client token is duplicated or dropped, and
        neither allocator leaks a block."""
        if after_frames is None:
            after_frames = self.rng.randrange(max_frames)
        self.faults.append(Fault(FaultKind.SOCKET_DROP, role="kv_migrate",
                                 after_calls=after_frames, times=times))
        return self

    def control_plane_crash(self, after_records: Optional[int] = None,
                            max_records: int = 64,
                            torn_bytes: Optional[int] = None) -> "FaultPlan":
        """kill -9 the control plane once its WAL has appended
        ``after_records`` records (None = seeded random offset in
        ``[0, max_records)``), with ``torn_bytes`` of the record
        in flight at death reaching disk (None = seeded draw between a
        clean cut and a mid-record tear) — the one fault PR 1 could not
        reach.  Nothing later persists; the surviving kubelets/pods keep
        running unadopted until a restarted Cluster (same ``data_dir``)
        replays the log and re-adopts them.  Consume via
        ``Cluster(data_dir=..., wal_crashpoint=plan.wal_crashpoint())``;
        ``plan.wal_crashpoint().fired`` is the death notification."""
        if after_records is None:
            after_records = self.rng.randrange(max_records)
        if torn_bytes is None:
            torn_bytes = self.rng.choice((0, 0, 5, 11, 23))
        self.faults.append(Fault(FaultKind.CONTROL_PLANE_CRASH,
                                 after_calls=after_records,
                                 torn_bytes=torn_bytes))
        return self

    def wal_crashpoint(self):
        """The :class:`~kubeflow_tpu.controlplane.wal.WalCrashPoint` for
        this plan's CONTROL_PLANE_CRASH fault (built once, so tests can
        both hand it to the Cluster and wait on ``.fired``); None when
        the plan has no control-plane fault."""
        from ..controlplane.wal import WalCrashPoint

        with self._lock:
            if getattr(self, "_crashpoint", None) is None:
                f = next((f for f in self.faults
                          if f.kind == FaultKind.CONTROL_PLANE_CRASH), None)
                if f is None:
                    return None
                self._crashpoint = WalCrashPoint(
                    after_records=f.after_calls or 0,
                    torn_bytes=f.torn_bytes)
            return self._crashpoint

    def replica_kill_mid_storm(self, world: int,
                               at: Optional[float] = None,
                               min_at: float = 0.2,
                               max_at: float = 2.0) -> "FaultPlan":
        """Kill one of ``world`` serving replicas at a seeded offset
        into a traffic storm (ISSUE 9): the member choice AND the kill
        time are frozen at plan-build time, so a failing storm run
        reproduces byte-for-byte.  The open-loop traffic bench /
        chaos test polls :meth:`due_replica_kills` from its arrival
        loop and abruptly stops the chosen replica server.  The
        contract under test: already-shed requests got their explicit
        429 (never a hang), in-flight requests on the dead replica
        surface as a bounded re-route or 5xx (never a hang), and
        prefix affinity forgets the corpse — same-prefix traffic
        re-routes to the survivors."""
        if at is None:
            at = min_at + self.rng.random() * max(max_at - min_at, 0.0)
        self.faults.append(Fault(FaultKind.REPLICA_KILL,
                                 index=self.rng.randrange(world), at=at))
        return self

    def due_replica_kills(self, now: Optional[float] = None) -> list[int]:
        """Replica indices whose seeded kill is due (each fault fires
        at most once) — the actuator poll for the storm driver."""
        t = self.elapsed(now)
        out: list[int] = []
        with self._lock:
            for f in self.faults:
                if (f.kind == FaultKind.REPLICA_KILL and not f.fired
                        and t >= f.at):
                    f.fired = 1
                    out.append(f.index)
        return out

    def domain_outage(self, domains, at: Optional[float] = None,
                      min_at: float = 0.2, max_at: float = 2.0,
                      duration: float = 0.0) -> "FaultPlan":
        """Correlated failure (ISSUE 16): one of ``domains`` (a list of
        failure-domain names) dies WHOLE at a seeded offset — every
        replica labeled with that domain stops at once, the
        rack/zone-loss shape no single-replica fault exercises.  The
        victim domain AND the outage time are frozen at plan-build
        time (same seed = same domain dies at the same offset).  The
        outage driver polls :meth:`due_domain_outages` from its
        arrival loop and abruptly stops every replica of the named
        domain.  ``duration > 0`` means the domain comes back after
        the window (the driver restarts it); 0 = permanent for the
        run.  Contract under test: the router's circuits open, the
        domain's sessions/affinity/registry rows mass-forget in one
        pass, retry amplification stays inside the budget, and the
        surge path brings the fleet back under SLO."""
        names = [str(d) for d in domains]
        if not names:
            raise ValueError("domain_outage needs at least one domain")
        if at is None:
            at = min_at + self.rng.random() * max(max_at - min_at, 0.0)
        self.faults.append(Fault(
            FaultKind.DOMAIN_OUTAGE,
            node=names[self.rng.randrange(len(names))],
            at=at, duration=duration))
        return self

    def due_domain_outages(self, now: Optional[float] = None) -> list[str]:
        """Failure-domain names whose seeded outage is due (each fault
        fires at most once) — the actuator poll for the outage driver,
        mirroring :meth:`due_replica_kills`."""
        t = self.elapsed(now)
        out: list[str] = []
        with self._lock:
            for f in self.faults:
                if (f.kind == FaultKind.DOMAIN_OUTAGE and not f.fired
                        and t >= f.at):
                    f.fired = 1
                    out.append(f.node)
        return out

    def gang_member_loss(self, world: int, at: Optional[float] = None,
                         permanent: bool = True, min_at: float = 0.1,
                         max_at: float = 1.0, spare_leader: bool = True,
                         job: Optional[str] = None) -> "FaultPlan":
        """Seeded gang-member loss (ISSUE 10).  Today's socket faults
        are all TRANSIENT — the member reconnects and PR 1's replay
        heals the stream.  ``permanent=True`` is the fault that
        machinery cannot absorb: the member never comes back (a dead
        chip), so the gang must either go fatal past the re-attach
        grace or — with elastic resize configured — shrink to the
        surviving degree.  The member choice and kill time are frozen
        at plan-build time (same seed = same rank dies at the same
        offset).  ``spare_leader`` keeps rank 0 alive: losing the
        leader is a full gang restart, not a resize.  Pod-level runs
        consume it through :meth:`script_fn` (a crash for effectively
        unlimited incarnations when permanent); in-process gang tests
        poll :meth:`due_member_losses` and sever the chosen member's
        channel for good."""
        if at is None:
            at = min_at + self.rng.random() * max(max_at - min_at, 0.0)
        lo = 1 if (spare_leader and world > 1) else 0
        rank = self.rng.randrange(lo, world)
        self.faults.append(Fault(
            FaultKind.GANG_MEMBER_LOSS, index=rank, at=at, job=job,
            times=(1_000_000 if permanent else 1)))
        return self

    def due_member_losses(self, now: Optional[float] = None) -> list[int]:
        """Gang ranks whose seeded loss is due (each fault fires at
        most once from this poll) — the actuator for in-process gang
        tests, mirroring :meth:`due_replica_kills`."""
        t = self.elapsed(now)
        out: list[int] = []
        with self._lock:
            for f in self.faults:
                if (f.kind == FaultKind.GANG_MEMBER_LOSS and not f.fired
                        and t >= f.at):
                    f.fired = 1
                    out.append(f.index)
        return out

    RESIZE_PHASES = ("export", "reshard", "commit")

    def kill_mid_resize(self, phases=RESIZE_PHASES,
                        phase: Optional[str] = None,
                        times: int = 1) -> "FaultPlan":
        """Seeded kill at an elastic-resize phase (ISSUE 10): the
        returned plan's :meth:`resize_failpoint` raises inside
        ``GangResizer`` at the chosen phase offset — mid-export,
        mid-reshard or mid-commit.  Contract under test
        (copy-then-cutover): the old-degree gang keeps serving,
        every client token is delivered exactly once, and neither
        allocator leaks a block."""
        if phase is None:
            phase = phases[self.rng.randrange(len(phases))]
        self.faults.append(Fault(FaultKind.RESIZE_KILL, role=str(phase),
                                 times=times))
        return self

    def resize_failpoint(self):
        """A ``callable(phase)`` for ``GangResizer(failpoint=...)``:
        raises at the plan's seeded RESIZE_KILL phase, at most
        ``times`` firings; clean pass-through otherwise."""
        def fp(phase: str) -> None:
            with self._lock:
                for f in self.faults:
                    if (f.kind == FaultKind.RESIZE_KILL
                            and f.role == phase and f.fired < f.times):
                        f.fired += 1
                        raise RuntimeError(
                            f"chaos: resize killed mid-{phase}")
        return fp

    # -- autoscale actuator faults (ISSUE 15) ------------------------------
    #
    # The ClusterAutoscaler's actuators are multi-step live-state moves
    # (replica drain, TP resize, tier rebalance, scale-to-zero
    # hibernation) — any of them can fail mid-flight (a wedged drain, a
    # follower nack, an unreachable new replica).  The loop's contract
    # under injected failure: exponential backoff, at most
    # ``max_retries`` attempts per demand episode (then the channel
    # PARKS), and no flapping — pinned by the seeded sweep in
    # tests/test_chaos.py.

    AUTOSCALE_ACTUATORS = ("replica_up", "replica_down", "resize",
                           "tier", "zero")

    def autoscale_actuator_fail(self, actuator: Optional[str] = None,
                                times: int = 1) -> "FaultPlan":
        """Seeded failure of one autoscaler actuator channel (None =
        seeded draw over :data:`AUTOSCALE_ACTUATORS` — a failed
        placement, failed drain, failed resize, failed rebalance or
        failed zero).  Consumed by :meth:`autoscale_failpoint`: the
        loop's next ``times`` firings of that channel raise before the
        actuator body runs."""
        if actuator is None:
            actuator = self.AUTOSCALE_ACTUATORS[
                self.rng.randrange(len(self.AUTOSCALE_ACTUATORS))]
        if actuator not in self.AUTOSCALE_ACTUATORS:
            raise ValueError(
                f"unknown autoscale actuator {actuator!r} "
                f"(one of {self.AUTOSCALE_ACTUATORS})")
        self.faults.append(Fault(FaultKind.AUTOSCALE_ACTUATOR_FAIL,
                                 role=str(actuator), times=times))
        return self

    def autoscale_failpoint(self):
        """A ``callable(channel)`` for
        ``ClusterAutoscaler(failpoint=...)``: raises when the loop
        fires the seeded channel, at most ``times`` firings; clean
        pass-through otherwise."""
        def fp(channel: str) -> None:
            with self._lock:
                for f in self.faults:
                    if (f.kind == FaultKind.AUTOSCALE_ACTUATOR_FAIL
                            and f.role == channel and f.fired < f.times):
                        f.fired += 1
                        raise RuntimeError(
                            f"chaos: autoscale {channel} actuator "
                            "failed")
        return fp

    def due_autoscale_fails(self) -> list[str]:
        """Actuator channels whose seeded failures are NOT yet
        exhausted — the paired read-only probe (tests assert the sweep
        consumed every injected failure; consuming happens in
        :meth:`autoscale_failpoint`, once per loop firing)."""
        with self._lock:
            return [f.role for f in self.faults
                    if f.kind == FaultKind.AUTOSCALE_ACTUATOR_FAIL
                    and f.fired < f.times]

    # -- storage-tier faults (ISSUE 12: crash-safe KV tiering) -------------
    #
    # The spill path (serving/storage.py KvSpillStore) has three failure
    # shapes the hibernate/thaw contract must absorb: the writer dies
    # mid-spill (nothing may publish — the session resumes in place),
    # a PUBLISHED spill loses bytes at rest (torn write / bit rot — the
    # thaw must detect it via the manifest hashes and re-prefill, never
    # serve corrupt KV), and the tier's I/O wedges (a hung NFS mount —
    # bounded stall, not a scheduler hang).  Each has a builder here and
    # a ``due_*`` actuator the store polls at its phase boundaries.

    SPILL_PHASES = ("payload", "meta", "publish")

    def spill_kill_mid_write(self, phase: Optional[str] = None,
                             times: int = 1) -> "FaultPlan":
        """The spilling process dies at a seeded write phase (payload
        bytes / manifest / publish rename).  Consumed by
        ``KvSpillStore(chaos=plan)``: the write raises after the chosen
        phase's bytes hit the staging dir, so nothing is ever published
        — a half-written spill is a stale staging dir, and the source
        engine resumes the sequence in place (copy-then-cutover,
        lifted to the storage tier)."""
        if phase is None:
            phase = self.SPILL_PHASES[
                self.rng.randrange(len(self.SPILL_PHASES))]
        self.faults.append(Fault(FaultKind.SPILL_KILL, role=str(phase),
                                 times=times))
        return self

    def spill_torn(self, torn_bytes: Optional[int] = None,
                   times: int = 1) -> "FaultPlan":
        """A PUBLISHED spill file loses its last ``torn_bytes`` bytes
        (torn write at the device layer, the PR 5 WAL-tail shape one
        tier down; None = seeded draw).  Consumed by
        ``KvSpillStore(chaos=plan)`` right after publish: the entry
        exists and its manifest is intact, but a payload hash no longer
        matches — thaw must detect it and re-prefill from the manifest's
        token record instead of serving wrong KV."""
        if torn_bytes is None:
            torn_bytes = self.rng.choice((1, 7, 64, 4096))
        self.faults.append(Fault(FaultKind.SPILL_TORN,
                                 torn_bytes=int(torn_bytes), times=times))
        return self

    def tier_io_stall(self, seconds: float = 0.2,
                      times: int = 1) -> "FaultPlan":
        """Storage-tier I/O wedges for ``seconds`` on the next
        ``times`` spill/thaw operations (a hung remote mount).
        Consumed by ``KvSpillStore(chaos=plan)`` at operation start —
        the stall lands on the HIBERNATION WORKER thread by
        construction (spill I/O never runs on an engine scheduler: the
        analyzer roots ``*Tier``/``*Spill``/``*Hibernate`` classes),
        so live decode traffic keeps flowing through the window."""
        self.faults.append(Fault(FaultKind.TIER_IO_STALL,
                                 delay=float(seconds), times=times))
        return self

    def due_spill_kills(self) -> list[str]:
        """Spill-write phases whose seeded kill is due — polled by the
        store ONCE per write.  At most ONE kill is drawn per call: a
        write dies at a single phase, and draining every seeded kill
        into one doomed write would consume later-phase kills without
        ever firing them (two seeded kills = two killed writes)."""
        out: list[str] = []
        with self._lock:
            for f in self.faults:
                if (f.kind == FaultKind.SPILL_KILL
                        and f.fired < f.times):
                    f.fired += 1
                    out.append(f.role)
                    break
        return out

    def due_spill_torn(self) -> list[int]:
        """Byte counts to tear off the just-published spill's payload
        tail — polled by the store after each publish."""
        out: list[int] = []
        with self._lock:
            for f in self.faults:
                if (f.kind == FaultKind.SPILL_TORN
                        and f.fired < f.times):
                    f.fired += 1
                    out.append(int(f.torn_bytes))
        return out

    def due_tier_stalls(self) -> list[float]:
        """Seconds of storage-tier stall due for the next I/O op —
        polled by the store at operation start."""
        out: list[float] = []
        with self._lock:
            for f in self.faults:
                if (f.kind == FaultKind.TIER_IO_STALL
                        and f.fired < f.times):
                    f.fired += 1
                    out.append(float(f.delay))
        return out

    def socket_delay(self, role: str = "leader", delay: float = 0.01,
                     times: int = 1) -> "FaultPlan":
        """Add ``delay`` seconds to every send on the next ``times``
        sockets wrapped for ``role`` (a slow cross-host link)."""
        self.faults.append(Fault(FaultKind.SOCKET_DELAY, role=role,
                                 delay=delay, times=times))
        return self

    # -- activation / clock ------------------------------------------------

    def activate(self, now: Optional[float] = None) -> "FaultPlan":
        """Start the plan clock (idempotent).  FakeKubelet calls this on
        ``start()``; tests may call it explicitly."""
        with self._lock:
            if self._t0 is None:
                self._t0 = time.time() if now is None else now
        return self

    def elapsed(self, now: Optional[float] = None) -> float:
        if self._t0 is None:
            return 0.0
        return (time.time() if now is None else now) - self._t0

    # -- FakeKubelet integration ------------------------------------------

    def kubelet_stalled(self, now: Optional[float] = None) -> bool:
        """True while a KUBELET_STALL window is open."""
        t = self.elapsed(now)
        return any(
            f.kind == FaultKind.KUBELET_STALL
            and f.at <= t < f.at + f.duration
            for f in self.faults
        )

    def apply_cluster_faults(self, store, now: Optional[float] = None) -> None:
        """Fire due cluster-level faults (node drains) against the store.

        Called from ``FakeKubelet.step()`` — the kubelet is the one
        component that already touches every pod, so it doubles as the
        chaos actuator, exactly once per fault.
        """
        from ..controlplane.objects import KIND_NODE, KIND_POD, PodPhase

        t = self.elapsed(now)
        for f in self.faults:
            if f.kind != FaultKind.NODE_DRAIN or f.fired or t < f.at:
                continue
            f.fired = 1
            store.try_delete(KIND_NODE, f.node)
            for pod in store.list(KIND_POD):
                if pod.spec.node_name != f.node or pod.terminal:
                    continue

                def preempt(o, code=f.exit_code):
                    o.status.phase = PodPhase.FAILED
                    o.status.exit_code = code
                    o.status.message = f"node {o.spec.node_name} drained"
                    o.status.finish_time = time.time()

                try:
                    store.update_with_retry(
                        KIND_POD, pod.metadata.name,
                        pod.metadata.namespace, preempt)
                except Exception:  # noqa: BLE001 — pod raced deletion
                    pass

    def _incarnation(self, pod) -> int:
        """0-based life count for this pod name (a new uid = a new life)."""
        with self._lock:
            lives = self._lives[
                f"{pod.metadata.namespace}/{pod.metadata.name}"]
            lives.add(pod.metadata.uid)
            return len(lives) - 1

    def pod_script(self, pod, default=None):
        """Resolve the PodScript for one pod incarnation (the ScriptFn
        body); ``default`` supplies the healthy behavior."""
        from ..controlplane.fake_kubelet import DEFAULT_SCRIPT, PodScript

        base = default(pod) if default is not None else DEFAULT_SCRIPT
        job = pod.metadata.labels.get("job-name")
        try:
            idx = int(pod.metadata.labels.get("replica-index", -1))
        except (TypeError, ValueError):
            idx = -1
        incarnation = self._incarnation(pod)
        for f in self.faults:
            if f.job is not None and f.job != job:
                continue
            if f.kind == FaultKind.BARRIER_HANG and f.index == idx:
                return PodScript(hang=True, barrier_after=None)
            if f.kind in (FaultKind.CRASH, FaultKind.FLAKY,
                          FaultKind.GANG_MEMBER_LOSS) and f.index == idx:
                if incarnation < f.times:
                    return PodScript(run_seconds=f.at,
                                     exit_code=f.exit_code,
                                     barrier_after=base.barrier_after)
        return base

    def script_fn(self, default=None) -> Callable:
        """A ScriptFn for FakeKubelet: chaos faults first, ``default``
        (healthy behavior) otherwise."""
        return lambda pod: self.pod_script(pod, default=default)

    # -- gang-socket integration ------------------------------------------

    def socket_wrapper(self, role: str) -> Callable:
        """A ``sock -> sock`` wrapper for GangChannel injection: applies
        the next unconsumed SOCKET_* fault for ``role``; clean
        pass-through once the plan's net faults are spent."""
        from .net import ChaosSocket

        def wrap(sock):
            with self._lock:
                for f in self.faults:
                    if f.role != role or f.fired >= f.times:
                        continue
                    if f.kind == FaultKind.SOCKET_DROP:
                        f.fired += 1
                        return ChaosSocket(sock, drop_after_calls=f.after_calls)
                    if f.kind == FaultKind.SOCKET_DELAY:
                        f.fired += 1
                        return ChaosSocket(sock, send_delay=f.delay)
            return sock

        return wrap
