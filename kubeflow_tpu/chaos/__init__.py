"""Fault injection for the control plane and the serving gang.

``FaultPlan`` is the one entry point: build a seeded plan, then hand it
to ``FakeKubelet(..., chaos=plan)`` (pod crashes, kubelet stalls, node
drains), to ``GangChannel`` via ``plan.socket_wrapper(role)``
(control-stream drops/delays), and/or to a durable ``Cluster`` via
``Cluster(data_dir=..., wal_crashpoint=plan.wal_crashpoint())`` (kill -9
the control plane at a seeded WAL offset).  See chaos/plan.py for the
fault model and tests/test_chaos.py for the recovery paths it exercises.
"""

from .net import ChaosSocket
from .plan import PREEMPTION_EXIT_CODE, Fault, FaultKind, FaultPlan

__all__ = [
    "ChaosSocket",
    "Fault",
    "FaultKind",
    "FaultPlan",
    "PREEMPTION_EXIT_CODE",
]
