"""ChaosSocket: fault-injecting wrapper around a real socket.

The injectable seam :class:`~..serving.gang.GangChannel` exposes
(``sock_wrap=``): every socket the channel creates — leader accepts,
follower dials, follower *re*-dials — passes through the wrapper, so a
:class:`~.plan.FaultPlan` can kill or slow the gang control stream at a
precise point mid-protocol without touching the channel code.

A drop closes the underlying socket and surfaces as ``OSError`` on the
next call — exactly what a yanked cable / OOM-killed peer looks like to
the channel's recovery machinery.
"""

from __future__ import annotations

import socket
import time
from typing import Optional


class ChaosSocket:
    """Wraps a socket; counts sendall/recv calls and injects faults.

    ``drop_after_calls``: total sendall+recv calls before the connection
    dies (None with ``send_delay`` unset means drop immediately).
    ``send_delay``: seconds added to every sendall (slow link).
    """

    def __init__(self, sock: socket.socket,
                 drop_after_calls: Optional[int] = None,
                 send_delay: float = 0.0) -> None:
        self._sock = sock
        self._calls = 0
        self._send_delay = send_delay
        if send_delay and drop_after_calls is None:
            self._drop_after = None  # delay-only wrapper never drops
        else:
            self._drop_after = drop_after_calls or 0
        self._dropped = False

    def _tick(self) -> None:
        if self._drop_after is None:
            return
        self._calls += 1
        if self._calls > self._drop_after and not self._dropped:
            self._dropped = True
            try:
                self._sock.close()
            except OSError:
                pass
        if self._dropped:
            raise OSError("chaos: injected connection drop")

    def sendall(self, data: bytes) -> None:
        if self._send_delay:
            time.sleep(self._send_delay)
        self._tick()
        return self._sock.sendall(data)

    def recv(self, n: int) -> bytes:
        self._tick()
        return self._sock.recv(n)

    def close(self) -> None:
        self._dropped = True
        try:
            self._sock.close()
        except OSError:
            pass

    def __getattr__(self, name):
        # settimeout / setsockopt / getpeername / fileno ... pass through
        return getattr(self._sock, name)
