"""Elastic serving gangs: TP-degree resize of a live gang (ISSUE 10).

A serving gang used to have exactly one legal shape: its birth degree.
Lose a member permanently (a dead chip) and the ISvc parked in
``Degraded`` routing forever, waiting for a re-form a dead host can
never grant — the one failure mode PR 1's recovery machinery could not
absorb.  Tenplex (PAPERS.md) shows parallelism degree can be a runtime
variable; PR 7 made sequence state transferable
(``export_sequence``/``import_sequence``).  This module makes the GANG
itself reshapeable, composing both:

- :class:`GangResizer` — a COPY-THEN-CUTOVER degree change of a live
  engine: quiesce admissions at a dispatch boundary, export every live
  sequence through the PR 7 snapshot path (slots freeze, nothing is
  freed), repartition the weight PyTree from TP=N to TP=M through
  ``parallel/sharding.py`` reshard plans, rebuild the paged pool +
  warmed programs at the new degree, then re-import every sequence
  FROZEN onto its original ``Request`` handle and flip ownership in one
  cutover — SSE streams survive on the same handle, greedy tokens stay
  bit-identical (CPU stand-in: exactly; on chip, up to reduction-order
  epsilons the parity suite pins), and ``jit_recompiles_total`` stays 0
  after the new degree's warmup.
- the ``reshard`` wire family — the leader coordinates followers over
  the authenticated :class:`~.gang.GangChannel` (a ``resize`` control
  op), and ships the repartitioned weights over a kv_migrate-shaped
  stream: token-authenticated hello, length-framed JSON headers + RAW
  numpy bytes, never pickle, with the follower allocating its
  new-degree engine only at ``rs_commit``.
- :class:`ElasticGangSupervisor` — the two consumers: shrink-to-survive
  (a member evicted past ``resize_deadline_s`` escalates into a resize
  to the surviving degree — ``Degraded`` becomes a bounded recovery
  with a ``GangResized`` event, not a terminal wait) and grow-back (a
  returned or freshly added member triggers the inverse resize).

Failure discipline (the PR 7 contract, lifted to the whole gang): the
old-degree engine keeps serving until the new shape acks.  Every import
lands ``hold=True`` (installed frozen), so a resize that dies at ANY
phase — mid-export, mid-reshard, mid-commit, proven by the seeded
``kill_mid_resize`` chaos sweep — discards the half-built new shape
wholesale and resumes every frozen sequence in place: exactly-once
tokens, zero leaked blocks on either allocator.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from struct import error as struct_error
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..parallel.sharding import reshard_plan
from . import continuous as contlib
from . import sharded as shardedlib
from .gang import (
    KV_HELLO_MAX,
    ChannelClosed,
    GangEngine,
    _kv_recv,
    _kv_send,
    _np_dtype,
)
from .paged import resize_block_budget

log = logging.getLogger("kubeflow_tpu.serving")


class ResizeAborted(RuntimeError):
    """A resize died before cutover; the source resumed in place."""

    def __init__(self, phase: str, cause: Optional[BaseException] = None):
        super().__init__(
            f"gang resize aborted during {phase}: {cause!r} — "
            "old-degree engine resumed in place")
        self.phase = phase
        self.cause = cause


# ---------------------------------------------------------------------------
# weight PyTree <-> wire leaves
# ---------------------------------------------------------------------------


def flatten_params(params) -> list[tuple[str, np.ndarray]]:
    """Sorted (path, host array) pairs for a weight PyTree — the reshard
    wire's transfer unit.  Unboxes flax metadata and unfreezes
    FrozenDicts so every engine's params (raw init output, placed
    device trees, quantized variants) flatten to the same "/"-joined
    paths.  Runs on the resize supervisor/worker thread (never a
    scheduler thread): the device fetch here is the copy half of
    copy-then-cutover."""
    from flax import linen as nn
    from flax.core import unfreeze
    from flax.traverse_util import flatten_dict

    tree = unfreeze(nn.meta.unbox(params))
    flat = flatten_dict(tree, sep="/")
    # ONE batched device->host fetch for the whole tree: per-leaf
    # device_get would serialize a transfer per parameter inside the
    # reshard window, while every live conversation sits frozen
    # analysis: ok host-sync-in-dispatch — resize worker thread copy
    host = jax.device_get(flat)
    # analysis: ok host-sync-in-dispatch — host leaves post-fetch
    return [(k, np.asarray(v)) for k, v in sorted(host.items())]


def unflatten_params(leaves: dict[str, np.ndarray]):
    """Rebuild the nested weight dict from wire (path, array) leaves."""
    from flax.traverse_util import unflatten_dict

    return unflatten_dict(dict(leaves), sep="/")


def degree_of(mesh_axes: Optional[dict]) -> int:
    """TP degree a mesh-axes dict denotes (None/empty = 1)."""
    if not mesh_axes:
        return 1
    n = 1
    for v in mesh_axes.values():
        n *= int(v)
    return n


# ---------------------------------------------------------------------------
# the reshard wire family (rs_*): JSON headers + raw numpy, never pickle
# ---------------------------------------------------------------------------
#
#   follower -> rs_hello {token, rank}      leader -> rs_ready
#   leader   -> rs_plan {degree, leaves: [{path, shape, dtype, dst}]}
#   leader   -> rs_leaf {i, path} + bytes   (buffered host-side)
#   leader   -> rs_commit
#   follower builds the new-degree engine (allocation at commit), then
#   follower -> rs_ack {ok, rank, error?}
#
# Mirrors kv_migrate's trust shape: per-deployment token, length-capped
# JSON handshake, hard frame caps — a corrupted length costs a closed
# connection, not an OOM.  The reproduction streams each FULL logical
# leaf (every CPU stand-in process addresses the whole mesh); a real
# multi-host gang would slice each leaf to the byte ranges the
# follower's shards need — the plan's src/dst specs carry exactly the
# information to do it.


class ReshardServer:
    """Leader side of the ``reshard`` wire family: serves the
    repartition plan + weight leaves to each surviving/joining follower
    and collects the follower's post-build ack — the "new shape acks"
    gate of copy-then-cutover."""

    def __init__(self, leaves: list[tuple[str, np.ndarray]],
                 plan: list[dict], *, degree: int, token: str = "",
                 port: Optional[int] = None, host: str = "127.0.0.1",
                 sock_wrap=None, trace_ctx: Optional[dict] = None):
        from ..utils.net import allocate_port

        if host != "127.0.0.1" and not token:
            raise ValueError(
                "a non-loopback ReshardServer requires a token")
        self._leaves = leaves
        self._plan = plan
        #: resize-trace context (ISSUE 13): rides the rs_plan header so
        #: a follower's logs/tooling can correlate its rebuild with the
        #: leader's resize trace
        self._trace_ctx = trace_ctx
        self._degree = int(degree)
        self._token = token
        self._sock_wrap = sock_wrap or (lambda s: s)
        self._closing = threading.Event()
        self._acks: dict[int, tuple[bool, str]] = {}
        self._ack_cv = threading.Condition()
        self.port = port or allocate_port()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, self.port))
        srv.listen(8)
        srv.settimeout(0.2)
        self._srv = srv
        threading.Thread(target=self._accept_loop, name="reshard-srv",
                         daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            srv = self._srv
            if srv is None:
                return
            try:
                raw, _addr = srv.accept()
            except (socket.timeout, TimeoutError):
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_one, args=(self._sock_wrap(raw),),
                name="reshard-conn", daemon=True).start()

    def _serve_one(self, c) -> None:
        import hmac

        rank = -1
        try:
            c.settimeout(30.0)
            hello, _ = _kv_recv(c, KV_HELLO_MAX)
            if hello.get("t") != "rs_hello" or not hmac.compare_digest(
                    str(hello.get("token", "")), self._token):
                raise ChannelClosed("bad reshard handshake")
            rank = int(hello.get("rank", -1))
            _kv_send(c, {"t": "rs_ready"})
            _kv_send(c, {"t": "rs_plan", "degree": self._degree,
                         "nleaves": len(self._leaves),
                         "leaves": self._plan,
                         "trace": self._trace_ctx})
            for i, (path, arr) in enumerate(self._leaves):
                _kv_send(c, {"t": "rs_leaf", "i": i, "path": path},
                         np.ascontiguousarray(arr).tobytes())
            _kv_send(c, {"t": "rs_commit"})
            # the follower builds its new-degree engine now; give the
            # build (pool allocation, program-factory setup — compiles
            # happen later via warmup replay) a generous bound
            c.settimeout(120.0)
            ack, _ = _kv_recv(c, 1 << 16)
            if ack.get("t") != "rs_ack":
                raise ChannelClosed(f"expected rs_ack, got {ack.get('t')!r}")
            rank = int(ack.get("rank", rank))
            with self._ack_cv:
                self._acks[rank] = (bool(ack.get("ok")),
                                    str(ack.get("error", "")))
                self._ack_cv.notify_all()
        except (OSError, ChannelClosed, ValueError, struct_error,
                EOFError) as e:
            log.debug("reshard transfer aborted (rank %d): %s", rank, e)
            if rank >= 0:
                with self._ack_cv:
                    self._acks.setdefault(rank, (False, str(e)))
                    self._ack_cv.notify_all()
        finally:
            try:
                c.close()
            except OSError:
                pass

    def await_acks(self, ranks, timeout: float = 120.0) -> dict[int, tuple]:
        """Block until every rank in ``ranks`` acked (or the deadline):
        rank -> (ok, error).  Missing ranks report a timeout failure."""
        deadline = time.monotonic() + timeout
        want = set(int(r) for r in ranks)
        with self._ack_cv:
            while not want.issubset(self._acks):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._ack_cv.wait(remaining)
            out = {r: self._acks.get(r, (False, "no ack before deadline"))
                   for r in want}
        return out

    def close(self) -> None:
        self._closing.set()
        srv, self._srv = self._srv, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass


class ReshardClient:
    """Follower side of the ``reshard`` wire family: receive the plan +
    leaves (buffered host-side — nothing device-allocated until the
    caller commits by building the engine), then ack the build outcome
    on the same connection."""

    def __init__(self, host: str, port: int, *, token: str = "",
                 rank: int = 0, sock_wrap=None, timeout: float = 120.0):
        raw = socket.create_connection((host, port), timeout=timeout)
        self._c = (sock_wrap or (lambda s: s))(raw)
        self._rank = int(rank)
        try:
            self._c.settimeout(timeout)
        except OSError:
            pass
        _kv_send(self._c, {"t": "rs_hello", "token": token,
                           "rank": self._rank})
        ready, _ = _kv_recv(self._c, KV_HELLO_MAX)
        if ready.get("t") != "rs_ready":
            raise ChannelClosed("reshard server refused the handshake")

    def receive(self) -> tuple[dict, dict[str, np.ndarray]]:
        """(plan header, path -> host array).  Raises on a short or
        malformed stream; the caller then acks failure and keeps its
        old-degree engine."""
        header, _ = _kv_recv(self._c)
        if header.get("t") != "rs_plan":
            raise ChannelClosed(f"expected rs_plan, got {header.get('t')!r}")
        specs = {e["path"]: e for e in header.get("leaves") or []}
        nleaves = int(header.get("nleaves", 0))
        leaves: dict[str, np.ndarray] = {}
        while True:
            frame, payload = _kv_recv(self._c)
            t = frame.get("t")
            if t == "rs_leaf":
                path = str(frame.get("path"))
                spec = specs.get(path)
                if spec is None:
                    raise ChannelClosed(f"rs_leaf for unplanned {path!r}")
                dt = _np_dtype(spec["dtype"])
                want = int(np.prod(spec["shape"],
                                   dtype=np.int64)) * dt.itemsize
                if len(payload) != want:
                    raise ChannelClosed(
                        f"rs_leaf {path!r}: {len(payload)}B != spec {want}B")
                leaves[path] = np.frombuffer(payload, dtype=dt).reshape(
                    spec["shape"]).copy()
            elif t == "rs_commit":
                break
            else:
                raise ChannelClosed(f"unknown reshard frame {t!r}")
        if len(leaves) != nleaves:
            raise ChannelClosed(
                f"rs_commit with {len(leaves)}/{nleaves} leaves")
        return header, leaves

    def ack(self, ok: bool, error: str = "") -> None:
        _kv_send(self._c, {"t": "rs_ack", "ok": bool(ok),
                           "rank": self._rank, "error": error[:500]})

    def close(self) -> None:
        try:
            self._c.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# GangResizer: the copy-then-cutover orchestration
# ---------------------------------------------------------------------------


class GangResizer:
    """Copy-then-cutover TP-degree resize of a live engine/gang.

    Drives the whole sequence from a supervisor/worker thread (never an
    engine scheduler — the analyzer roots every ``*Resizer`` method for
    exactly that discipline; the declared fetch/socket sites carry
    pragmas).  Phases, in order, with the chaos sweep's seeded
    failpoints between items of each:

      quiesce  — admissions defer (the old pool keeps decoding);
      export   — every live sequence freezes at a dispatch boundary and
                 snapshots through the PR 7 path (source keeps
                 everything);
      reshard  — weight PyTree repartitioned via
                 ``parallel.sharding.reshard_plan``; gang followers are
                 told to rebuild (``resize`` op) and fed the new layout
                 over the rs_* wire; the new-degree engine + paged pool
                 + warmed programs are built (the old engine still owns
                 every sequence);
      commit   — snapshots import ``hold=True`` (installed frozen) onto
                 their ORIGINAL Request handles;
      cutover  — only once the new shape acked: release on the old,
                 resume on the new, waiting queue adopted, engine
                 reference swapped.  Forward-only; everything before it
                 rolls back by discarding the new shape wholesale.

    ``set_engine`` re-points the serving runtime (e.g.
    ``model.engine``); ``failpoint(phase)`` is the chaos seam
    (``FaultPlan.resize_failpoint``); ``on_event(reason, message)``
    receives ``GangResized`` / ``ResizeAborted`` notifications.
    """

    PHASES = ("export", "reshard", "commit")

    def __init__(self, engine, *, set_engine: Optional[Callable] = None,
                 reshard_token: str = "", failpoint: Optional[Callable] = None,
                 on_event: Optional[Callable] = None,
                 warmup_groups: Optional[list] = None, sock_wrap=None,
                 ack_timeout_s: float = 120.0, tracer=None):
        if not getattr(engine, "paged", False):
            raise ValueError(
                "elastic resize requires the paged pool (block_size > 0):"
                " the transferable unit of sequence state is the block")
        self.engine = engine
        #: trace sink (ISSUE 13): every resize records its own trace
        #: (freeze/reshard/commit/cutover phases) so Tenplex-style cost
        #: decomposition is a /traces read, not a bench run.  Falls
        #: back to the engine's attached tracer (text.py wires one).
        self.tracer = tracer if tracer is not None \
            else getattr(engine, "tracer", None)
        self._set_engine = set_engine
        self._token = reshard_token
        self._failpoint = failpoint
        self._on_event = on_event
        self._warmup_groups = warmup_groups
        self._sock_wrap = sock_wrap
        #: how long the leader waits for every follower's post-rebuild
        #: ack — a follower that cannot even handshake never acks, so
        #: this bounds the whole "new shape acks" gate
        self._ack_timeout = float(ack_timeout_s)
        self._lock = threading.Lock()
        self.resizes_total = 0
        self.resize_failures_total = 0
        #: phase timings of the last successful resize (the
        #: recovery-bench row): drain_s = quiesce+export, reshard_s =
        #: plan+weights+build+warmup, resume_s = commit+cutover
        self.last_timings: dict[str, float] = {}

    # -- helpers -----------------------------------------------------------

    def _fail(self, phase: str) -> None:
        if self._failpoint is not None:
            self._failpoint(phase)

    def _emit(self, reason: str, message: str) -> None:
        if self._on_event is not None:
            try:
                self._on_event(reason, message)
            except Exception:  # noqa: BLE001 — an observer must never
                # turn a successful resize into a failure
                log.debug("resize event sink failed", exc_info=True)

    @staticmethod
    def _engine_kwargs_of(src, *, orig_policy) -> dict:
        """Rebuild kwargs from a live engine (the knobs the ISvc froze,
        read back off the instance so resize needs no config plumbing)."""
        return dict(
            num_slots=src.num_slots, decode_chunk=src.decode_chunk,
            prefill_budget=src.prefill_budget,
            temperature=src.temperature, eos_id=src.eos_id,
            seq_buckets=list(src.seq_buckets),
            default_max_new_tokens=src.default_max_new_tokens,
            pipeline_depth=src.pipeline_depth,
            prefix_cache=src.prefix_cache, min_prefix=src.min_prefix,
            spec_k=src.spec_k, spec_ngram=src.spec_ngram,
            draft_proposer=src._proposer, block_size=src.block_size,
            # host KV tier (ISSUE 12): the mirror is rebuilt empty at
            # the new degree (its bytes are shaped for the old pool);
            # the watermark policy carries over.  num_blocks is scaled
            # separately, so the host budget just carries verbatim.
            host_blocks=src.host_blocks,
            host_watermark=(src._host_watermark_blocks
                            / max(src.num_blocks, 1)),
            admission_policy=orig_policy, role=src.role,
        )

    @staticmethod
    def _wire_kwargs(kw: dict, num_blocks: int) -> dict:
        """The JSON-safe kwargs subset a follower rebuild needs (no
        proposer/policy objects — followers never schedule)."""
        out = {k: kw[k] for k in (
            "num_slots", "decode_chunk", "prefill_budget", "temperature",
            "eos_id", "seq_buckets", "default_max_new_tokens",
            "pipeline_depth", "prefix_cache", "min_prefix", "spec_k",
            "spec_ngram", "block_size", "role")}
        out["num_blocks"] = int(num_blocks)
        return out

    @staticmethod
    def _snapshot_blocks(snap: dict) -> int:
        """Full worst-case block span one snapshot needs on import."""
        bs = int(snap["block_size"])
        if snap.get("phase") == "prefill":
            total = len(snap["prompt"]) + int(snap["max_new_tokens"])
        else:
            total = int(snap["position"]) + int(snap["remaining"])
        return max(-(-total // bs), len(snap.get("blocks", ())), 1)

    def degree(self) -> int:
        """Current TP degree (mesh size; 1 = unmeshed)."""
        mesh = getattr(self.engine, "mesh", None)
        return int(mesh.size) if mesh is not None else 1

    def resize_to_degree(self, degree: int) -> Any:
        """Degree-targeted actuator entry point (ISSUE 15): the
        autoscaler reasons in TP degrees, not mesh-axes dicts — map the
        target onto the single-axis layout every elastic consumer uses
        (``{"model": N}``; ``_resize_locked`` normalizes degree 1 on
        unmeshed engines).  A same-degree target is a no-op returning
        the live engine, NOT a resync-by-rebuild — the supervisor owns
        that path."""
        d = int(degree)
        if d < 1:
            raise ValueError(f"target TP degree must be >= 1, got {d}")
        if d == self.degree():
            return self.engine
        return self.resize({"model": d})

    # -- the resize --------------------------------------------------------

    def resize(self, mesh_axes: Optional[dict], *,
               num_blocks: Optional[int] = None) -> Any:
        """Resize the live engine to ``mesh_axes`` (None = degree 1,
        unmeshed).  Returns the NEW engine on success (also installed
        via ``set_engine`` and as ``self.engine``); raises
        :class:`ResizeAborted` with the old engine resumed in place on
        any pre-cutover failure."""
        with self._lock:
            # the resize lock IS the drain barrier: one resize at a
            # time, callers block by design while the gang quiesces,
            # reshards and cuts over
            # analysis: ok lock-blocking-call — lock is the drain barrier
            return self._resize_locked(mesh_axes, num_blocks)

    def _resize_locked(self, mesh_axes, num_blocks):
        src = self.engine
        channel = getattr(src, "_channel", None)
        if degree_of(mesh_axes) == 1 and channel is None:
            # degree 1 IS the unmeshed engine: a 1-device mesh oscillates
            # between equivalent-but-unequal replicated output specs
            # (PartitionSpec() vs PartitionSpec(None, ...)), costing one
            # silent executable-cache re-entry per program — exactly the
            # stall class the recompile guard counts.  Gang leaders keep
            # their mesh (the channel machinery needs it for grow-back).
            mesh_axes = None
        old_degree = self.degree()
        new_degree = degree_of(mesh_axes)
        phase = "export"
        t0 = time.perf_counter()
        timings: dict[str, float] = {}
        rtr = None
        if self.tracer is not None:
            # one trace PER RESIZE (freeze/reshard/commit/cutover
            # phases): the Tenplex decomposition as a /traces row, with
            # the context propagated on the resize replay op and the
            # rs_plan wire header
            from .trace import Trace

            rtr = Trace(name="resize", old_degree=old_degree,
                        new_degree=new_degree)
        orig_policy = src.admission_policy
        prebuilt = None
        if channel is None and getattr(src, "program_cache", None) is not None:
            # PREBUILD (local engines with an AOT artifact cache):
            # construct and warm the destination-degree engine
            # CONCURRENTLY with old-degree serving, so copy-then-cutover
            # finally covers the programs, not just the state — the
            # quiesce window below no longer contains the compile wall.
            # The block budget is estimated from the live set
            # (position+remaining is dispatch-stable); admissions during
            # the prebuild can push the real budget past the estimate,
            # in which case the prebuilt engine is discarded and the
            # serial path rebuilds against the just-published artifacts
            # — still fast, never wrong.
            tp = time.perf_counter()
            if rtr is not None:
                rtr.phase("resize.prebuild")
            try:
                reserved_est = 0
                if src.paged:
                    bs = src.block_size
                    for i, r in enumerate(src._slots):
                        if r is None:
                            continue
                        total = int(src._positions[i]) + int(
                            src._remaining[i])
                        reserved_est += max(
                            -(-max(total, 1) // bs),
                            len(src._slot_blocks[i]), 1)
                nb_est = (int(num_blocks) if num_blocks
                          else resize_block_budget(
                              src.num_blocks, old_degree, new_degree,
                              reserved=reserved_est))
                kwp = self._engine_kwargs_of(
                    src, orig_policy=orig_policy)
                kwp["num_blocks"] = nb_est
                kwp["program_cache"] = src.program_cache
                pre_params = unflatten_params(
                    dict(flatten_params(src.params)))
                prebuilt = contlib.ContinuousEngine(
                    src.cfg, pre_params, mesh_axes=mesh_axes, **kwp)
                if self.tracer is not None:
                    prebuilt.tracer = self.tracer
                pre_groups = self._warmup_groups
                if pre_groups != []:
                    prebuilt.warmup([tuple(g) for g in pre_groups]
                                    if pre_groups else None)
            except Exception:  # noqa: BLE001 — the prebuild is an
                # optimization: ANY failure here falls back to the
                # serial rebuild inside the quiesce window
                log.warning("resize prebuild failed; falling back to "
                            "serial rebuild", exc_info=True)
                if prebuilt is not None:
                    prebuilt.stop()
                prebuilt = None
            timings["prebuild_s"] = time.perf_counter() - tp
        if rtr is not None:
            rtr.phase("resize.export")
        exported: list[tuple[Any, dict]] = []
        published = False
        server: Optional[ReshardServer] = None
        new = None
        try:
            # QUIESCE: new admissions defer (the policy hook runs on the
            # scheduler thread each cycle); live slots keep decoding
            # until their export freezes them — tokens flow through the
            # copy phase, exactly-once.  The drain clock starts HERE:
            # the prebuild above overlaps live serving and must not be
            # billed to the disruption window
            td = time.perf_counter()
            src.admission_policy = lambda req: False

            # EXPORT: freeze + snapshot every live sequence at its
            # dispatch boundary; the source keeps every block.  The
            # export set is read ON the scheduler thread so a request
            # admitted concurrently with the quiesce swap cannot slip
            # between the policy and the snapshot
            for req in src.quiesced_live_requests():
                snap = src.export_sequence(req)
                if snap is not None:
                    exported.append((req, snap))
                    if req.trace is not None:
                        # the sequence's own trace shows the stall
                        # CAUSE: frozen for a resize until the cutover
                        # resume re-opens engine.decode
                        req.trace.phase("resize.frozen",
                                        resize=(rtr.trace_id
                                                if rtr else ""))
                self._fail("export")
            timings["drain_s"] = time.perf_counter() - td

            # RESHARD: repartition weights through the sharding table's
            # plan; tell followers; build the new-degree engine + pool
            phase = "reshard"
            t1 = time.perf_counter()
            if rtr is not None:
                rtr.phase("resize.reshard")
            src_mesh = getattr(src, "mesh", None)
            dst_mesh = (shardedlib.build_serving_mesh(mesh_axes)
                        if mesh_axes else None)
            host_leaves = flatten_params(src.params)
            # ONE rebuilt tree serves both the plan (shapes/dtypes) and
            # the new engine's weights (host leaves, device_put by its
            # constructor)
            new_params = unflatten_params(dict(host_leaves))
            plan = reshard_plan(
                new_params,
                (shardedlib.llama_param_shardings(src.cfg, src_mesh)
                 if src_mesh is not None else
                 jax.tree.map(lambda _: None, new_params)),
                (shardedlib.llama_param_shardings(src.cfg, dst_mesh)
                 if dst_mesh is not None else
                 jax.tree.map(lambda _: None, new_params)))
            self._fail("reshard")
            reserved = sum(self._snapshot_blocks(s) for _, s in exported)
            nb = int(num_blocks) if num_blocks else resize_block_budget(
                src.num_blocks, old_degree, new_degree, reserved=reserved)
            kw = self._engine_kwargs_of(src, orig_policy=orig_policy)
            kw["num_blocks"] = nb
            # the new degree shares the old engine's artifact cache:
            # its warmup loads what some replica already published
            kw["program_cache"] = getattr(src, "program_cache", None)
            follower_ranks: list[int] = []
            if channel is not None:
                follower_ranks = channel.follower_ranks()
                server = ReshardServer(
                    host_leaves, plan, degree=new_degree,
                    token=self._token, sock_wrap=self._sock_wrap,
                    trace_ctx=(rtr.wire_context() if rtr is not None
                               else None))
                channel.publish(("resize", {
                    "mesh_axes": mesh_axes,
                    "kwargs": self._wire_kwargs(kw, nb),
                    "reshard": {"host": "127.0.0.1", "port": server.port,
                                "token": self._token},
                    # trace context rides the replay op: follower logs
                    # correlate their rebuild with the leader's trace
                    "trace": (rtr.wire_context() if rtr is not None
                              else None),
                }))
                published = True
                acks = server.await_acks(follower_ranks,
                                         timeout=self._ack_timeout)
                bad = {r: e for r, (ok, e) in acks.items() if not ok}
                if bad:
                    raise RuntimeError(
                        f"follower rebuild failed: {bad} — the new "
                        "shape never acked")
            pre_used = False
            if channel is not None:
                new = GangEngine(src.cfg, new_params, channel=channel,
                                 mesh_axes=mesh_axes, **kw)
            elif (prebuilt is not None
                  and prebuilt.num_blocks >= nb):
                # the concurrent prebuild covers the real budget: adopt
                # it wholesale — programs already warm, nothing to
                # compile inside the quiesce window
                new, prebuilt, pre_used = prebuilt, None, True
            else:
                new = contlib.ContinuousEngine(
                    src.cfg, new_params, mesh_axes=mesh_axes, **kw)
            if self.tracer is not None and getattr(
                    new, "tracer", None) is None:
                new.tracer = self.tracer
            if getattr(src, "block_ledger", None) is not None and new.paged:
                # the zero-leaked-blocks audit follows the pool across
                # the resize: one ledger, both degrees' allocators —
                # kill-mid-resize leaks on EITHER side land in the same
                # kv_blocks_leaked_total tally
                new.attach_block_ledger(src.block_ledger)
            if getattr(src, "spill_store", None) is not None:
                # durable sessions (ISSUE 12) survive a degree change:
                # the storage tier re-attaches so hibernated entries
                # stay thaw-able and the session gauges keep reporting
                new.attach_spill_store(src.spill_store)
            self._fail("reshard")
            # rebuild the warmed-program ladder at the new degree: a
            # post-resize dispatch must never compile mid-serving (gang
            # warmup ops replay to the followers' new engines)
            groups = self._warmup_groups
            if groups != [] and not pre_used:
                new.warmup([tuple(g) for g in groups] if groups else None)
            timings["reshard_s"] = time.perf_counter() - t1

            # COMMIT: install every sequence FROZEN on its original
            # handle — both pools now hold the bytes; only the old one
            # may decode, and it is quiesced
            phase = "commit"
            t2 = time.perf_counter()
            if rtr is not None:
                rtr.phase("resize.commit")
            for req, snap in exported:
                new.import_sequence(snap, req=req, hold=True)
                self._fail("commit")
        except Exception as e:  # noqa: BLE001 — ANY pre-cutover death
            # (chaos failpoint, follower nack, pool exhaustion, compile
            # failure) takes the same rollback: discard the new shape
            # wholesale and resume in place
            self.resize_failures_total += 1
            if published:
                try:
                    channel.publish(("resize_abort",))
                except ChannelClosed:
                    pass
            if new is not None:
                for req, _snap in exported:
                    try:
                        # drops the held copy if it was imported; no-op
                        # for sequences the failure preceded
                        new.release_sequence(req)
                    except (RuntimeError, TimeoutError):
                        pass
                if isinstance(new, GangEngine):
                    new.keep_channel_open = True
                new.stop()
            for req, _snap in exported:
                try:
                    src.resume_sequence(req)
                except (RuntimeError, TimeoutError):
                    log.warning("resize rollback: resume failed for a "
                                "sequence", exc_info=True)
            src.admission_policy = orig_policy
            if rtr is not None:
                rtr.meta["aborted"] = phase
                self.tracer.finish(rtr)
            self._emit("ResizeAborted",
                       f"resize {old_degree}->{new_degree} died during "
                       f"{phase}; old degree resumed")
            raise ResizeAborted(phase, e) from e
        finally:
            if server is not None:
                server.close()
            if prebuilt is not None:
                # unused prebuild (budget overrun or rollback): release
                # its pool before the serial engine's lifetime begins
                prebuilt.stop()
                prebuilt = None

        # CUTOVER (forward-only): the new shape acked — flip ownership.
        # From here failure handling COMPLETES FORWARD, never rolls
        # back: sources may already be released, so the new engine owns
        # the state; anything that cannot be resumed is resolved with
        # an error rather than left for a client to wait on forever.
        # The commit op tells followers the abort window is closed, so
        # they can FREE the previous-degree engine (weights + pool):
        # without it a follower that resized once would hold two full
        # device copies until the next resize.
        cut_err: Optional[Exception] = None
        if rtr is not None:
            rtr.phase("resize.cutover")
        if channel is not None:
            try:
                channel.publish(("resize_commit",))
            except ChannelClosed as e:
                cut_err = e

        def _adopt(req) -> None:
            """Hand one withdrawn/waiting request to the new engine; a
            failed adoption resolves the handle with the error — a
            request withdrawn from the source queue belongs to NEITHER
            engine, and nothing else would ever wake its client."""
            nonlocal cut_err
            try:
                new.adopt_request(req)
            except Exception as e:  # noqa: BLE001 — resolve, not strand
                cut_err = cut_err or e
                if not req.done.is_set():
                    req.error = RuntimeError(
                        f"resize cutover failed: {e!r}")
                    req.done.set()

        # per-sequence cutover with failure isolation: a release that
        # never landed means the SOURCE still owns that sequence — its
        # held copy on the new engine is dropped (resuming it would
        # fork ownership and double-decode), and the source's stop()
        # below resolves the handle loudly.  A resume that fails after
        # a successful release resolves the handle too: the source
        # already let go, so silence would strand the client forever.
        for req, _snap in exported:
            try:
                src.release_sequence(req)
            except Exception as e:  # noqa: BLE001 — per-sequence
                # isolation: the source still owns this one (release
                # never landed); drop the held copy and move on
                cut_err = cut_err or e
                try:
                    new.release_sequence(req)
                except (RuntimeError, TimeoutError):
                    pass
                continue
            try:
                new.resume_sequence(req)
            except Exception as e:  # noqa: BLE001 — the source already
                # let go: resolve the handle, never strand the client
                cut_err = cut_err or e
                if not req.done.is_set():
                    req.error = RuntimeError(
                        f"resize cutover failed: {e!r}")
                    req.done.set()
        try:
            for req in src.take_waiting():
                _adopt(req)
        except (RuntimeError, TimeoutError) as e:
            cut_err = cut_err or e
        self.engine = new
        if self._set_engine is not None:
            self._set_engine(new)
        # second straggler sweep AFTER the engine swap: a request that
        # grabbed the old engine reference mid-cutover and enqueued
        # after the first sweep follows the pool instead of being
        # failed by stop() (the race narrows to callers still holding
        # the old reference past this point — the same window any
        # engine swap has)
        try:
            for req in src.take_waiting():
                _adopt(req)
        except (RuntimeError, TimeoutError) as e:
            cut_err = cut_err or e
        if isinstance(src, GangEngine):
            src.keep_channel_open = True
        src.stop()
        if cut_err is not None:
            self.resize_failures_total += 1
            if rtr is not None:
                rtr.meta["aborted"] = "cutover"
                self.tracer.finish(rtr)
            self._emit("ResizeAborted",
                       f"cutover completed forward with an error: "
                       f"{cut_err!r}")
            raise ResizeAborted("cutover", cut_err) from cut_err
        timings["resume_s"] = time.perf_counter() - t2
        timings["total_s"] = time.perf_counter() - t0
        self.last_timings = timings
        self.resizes_total += 1
        if rtr is not None:
            rtr.meta["sequences"] = len(exported)
            self.tracer.finish(rtr)
        self._emit(
            "GangResized",
            f"TP {old_degree} -> {new_degree}: {len(exported)} live "
            f"conversations repartitioned in {timings['total_s']:.3f}s "
            f"(drain {timings['drain_s']:.3f}s, reshard "
            f"{timings['reshard_s']:.3f}s, resume "
            f"{timings['resume_s']:.3f}s)")
        return new


# ---------------------------------------------------------------------------
# ElasticGangSupervisor: shrink-to-survive / grow-back
# ---------------------------------------------------------------------------


class ElasticGangSupervisor:
    """Rank-0 watcher that turns gang membership changes into resizes.

    Shrink-to-survive: a follower evicted from the
    :class:`~.gang.GangChannel` and still gone past
    ``resize_deadline_s`` is escalated into a resize to the surviving
    degree (``degree_per_member * live_members``), floored at
    ``min_degree`` — the rank is forgotten on the channel first, so the
    planned degree change never races the reattach-fatality clock
    (operators set ``resize_deadline_s`` below the channel's
    ``reattach_timeout``; serve_main widens the latter automatically
    when ``elastic`` is configured).

    Grow-back: a member count above the current degree's (a re-attached
    rank, or a fresh elastic join admitted after ``set_want``) triggers
    the inverse resize, capped at ``max_degree``.
    """

    def __init__(self, resizer: GangResizer, channel, *,
                 degree_per_member: int, max_degree: int,
                 min_degree: int = 1, resize_deadline_s: float = 2.0,
                 max_resize_attempts: int = 5,
                 poll_s: float = 0.1, on_event: Optional[Callable] = None):
        self.resizer = resizer
        self.channel = channel
        self.degree_per_member = int(degree_per_member)
        self.max_degree = int(max_degree)
        self.min_degree = int(min_degree)
        self.resize_deadline_s = float(resize_deadline_s)
        #: shrink attempts before the supervisor stops restarting the
        #: reattach-fatality clock and lets the JaxJob restart take over;
        #: also bounds grow/fresh-rebuild retries (a persistently
        #: nacking joiner must not become a resize storm — attempts
        #: reset when the membership changes)
        self.max_resize_attempts = int(max_resize_attempts)
        self._shrink_attempts = 0
        self._grow_attempts = 0
        self._last_live: tuple = ()
        self._poll = float(poll_s)
        self._on_event = on_event
        #: an admitted fresh joiner awaits its rebuild resize (survives
        #: ticks that cannot act — min_degree floor, failed resize)
        self._pending_fresh = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="elastic-gang", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — a failed escalation must
                # not kill the watcher; the next tick retries (the
                # resizer already resumed the old degree in place)
                log.warning("elastic supervisor tick failed",
                            exc_info=True)

    def _tick(self) -> None:
        now = time.monotonic()
        lost = self.channel.lost_since()
        live = self.channel.follower_ranks()
        if self.channel.take_fresh_joins():
            # a fresh joiner skips ops until a resize rebuilds it; keep
            # the obligation in a supervisor flag so it survives ticks
            # that cannot act yet (min_degree floor, a failed resize)
            self._pending_fresh = True
        cur = self.resizer.degree()
        if tuple(live) != self._last_live:
            # membership changed: the world the failed attempts saw is
            # gone — both retry budgets start over
            self._last_live = tuple(live)
            self._grow_attempts = 0
            self._shrink_attempts = 0
        overdue = [r for r, t in lost.items()
                   if now - t > self.resize_deadline_s]
        if overdue:
            target = self.degree_per_member * (1 + len(live))
            if target < self.min_degree:
                # nothing legal to shrink to: leave the fatality clock
                # running — the JaxJob restart remains the backstop
                return
            # restart the reattach clock BEFORE resizing: the rebuild
            # (weight reshard + new-degree warmup) can outlive the
            # remaining grace, and a fatality mid-shrink is exactly the
            # gang restart this path exists to avoid.  Bounded touches:
            # past max_resize_attempts the clock runs out and the
            # JaxJob restart backstop takes over.
            if self._shrink_attempts < self.max_resize_attempts:
                self.channel.touch_lost(overdue)
            self._shrink_attempts += 1
            # resize FIRST, bookkeeping after: a failed shrink must be
            # retried (the rank stays in the eviction ledger) and must
            # leave the reattach-fatality backstop armed — forgetting
            # up front would wedge the gang at the old degree with no
            # retry and no restart.  The admission cap (_want) is never
            # lowered: surviving ranks keep their ids.
            if target != cur or self._pending_fresh:
                self.resizer.resize(self._axes_for(target))
            for r in overdue:
                self.channel.forget_rank(r)
            self._pending_fresh = False
            self._shrink_attempts = 0
            return
        target = min(self.degree_per_member * (1 + len(live)),
                     self.max_degree)
        if target > cur or self._pending_fresh:
            if self._grow_attempts >= self.max_resize_attempts:
                return  # gave up until the membership changes — a
                # persistently failing rebuild must not quiesce the
                # live pool at poll frequency forever
            self._grow_attempts += 1
            # grow-back (a member returned or was added) — or a FRESH
            # rejoin at the current degree, which skips ops until a
            # resize rebuilds it (resync-by-rebuild: same-degree resizes
            # are legal and exercised by the parity suite)
            self.resizer.resize(self._axes_for(max(target, cur)))
            self._grow_attempts = 0
            self._pending_fresh = False

    @staticmethod
    def _axes_for(degree: int) -> Optional[dict]:
        return {"model": int(degree)}
