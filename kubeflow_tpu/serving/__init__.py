"""Serving plane: InferenceService controller, model server, JAX runtimes
(the KServe capability tier, SURVEY.md §2.2)."""

from .controller import InferenceServiceController, Router
from .model import Model
from .runtimes import EchoModel, JaxFunctionModel, LlamaGenerator
from .server import MicroBatcher, ModelServer
from .resize import ElasticGangSupervisor, GangResizer
from .storage import (
    KvSpillStore,
    SpillCorrupt,
    StorageError,
    download,
    fetch_mem,
    register_mem,
)
from .traffic import (
    KvBlockRegistry,
    QosClass,
    SessionAffinity,
    TrafficPlane,
    validate_qos,
)
from .transformer import Transformer

__all__ = [
    "EchoModel",
    "ElasticGangSupervisor",
    "GangResizer",
    "InferenceServiceController",
    "JaxFunctionModel",
    "KvBlockRegistry",
    "KvSpillStore",
    "LlamaGenerator",
    "MicroBatcher",
    "Model",
    "ModelServer",
    "QosClass",
    "Router",
    "SessionAffinity",
    "SpillCorrupt",
    "StorageError",
    "TrafficPlane",
    "Transformer",
    "validate_qos",
    "download",
    "fetch_mem",
    "register_mem",
]
