"""ModelServer: V1/V2 inference protocols over HTTP with micro-batching.

[upstream: kserve/kserve -> python/kserve/kserve/model_server.py +
protocol/{v1,v2} handlers].  Endpoints:

V1:  POST /v1/models/<name>:predict   {"instances": [...]} -> {"predictions": [...]}
     GET  /v1/models/<name>           readiness per model
V2:  POST /v2/models/<name>/infer     {"inputs": [{name,shape,datatype,data}]}
     GET  /v2/models/<name>           model metadata
     GET  /v2/health/live | /v2/health/ready
Also GET /metrics (request count/latency, Prometheus text format).

TPU-first: a micro-batcher sits between HTTP threads and the model —
concurrent single-instance requests coalesce (up to ``batch_max_size`` or
``batch_timeout_ms``) into one ``predict_batch`` call so the XLA callable
sees real batches.  The reference gets this from Triton's dynamic batcher on
GPU; here it is native.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ..utils.net import allocate_port
from .model import Model

log = logging.getLogger("kubeflow_tpu.serving")


@dataclass
class _Pending:
    instances: list
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[list] = None
    error: Optional[Exception] = None


class MicroBatcher:
    """Coalesce concurrent requests into batched predict calls."""

    def __init__(self, model: Model, max_size: int = 8, timeout_ms: float = 2.0):
        self.model = model
        self.max_size = max(1, max_size)
        self.timeout_s = max(timeout_ms, 0.0) / 1e3
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._gate = threading.Lock()  # serializes enqueue vs. shutdown
        self._thread = threading.Thread(
            target=self._loop, name=f"batcher-{model.name}", daemon=True)
        self._thread.start()

    def submit(self, instances: list) -> list:
        p = _Pending(instances)
        # check-and-enqueue under the gate: stop() flips _stop under the
        # same lock, so no submit can slip into the queue after the drain
        with self._gate:
            if self._stop.is_set():
                raise RuntimeError(f"model {self.model.name} is shutting down")
            self._q.put(p)
        p.done.wait()
        if p.error is not None:
            raise p.error
        assert p.result is not None
        return p.result

    def stop(self) -> None:
        with self._gate:
            self._stop.set()
        self._thread.join(timeout=2)
        # fail any requests that raced the shutdown — their HTTP threads
        # are blocked in submit() and would otherwise hang forever
        while True:
            try:
                p = self._q.get_nowait()
            except queue.Empty:
                break
            p.error = RuntimeError(f"model {self.model.name} shut down")
            p.done.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            n = len(first.instances)
            deadline = time.perf_counter() + self.timeout_s
            while n < self.max_size:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                batch.append(nxt)
                n += len(nxt.instances)
            flat: list = []
            for p in batch:
                flat.extend(p.instances)
            try:
                out = self.model(flat)
                if len(out) != len(flat):
                    raise RuntimeError(
                        f"model returned {len(out)} predictions for {len(flat)} instances")
                i = 0
                for p in batch:
                    p.result = out[i : i + len(p.instances)]
                    i += len(p.instances)
            except Exception as e:  # noqa: BLE001 — propagate per request
                for p in batch:
                    p.error = e
            finally:
                for p in batch:
                    p.done.set()


#: request-latency histogram bucket upper bounds (seconds), fixed by
#: contract: dynamic buckets cannot be aggregated across replicas by a
#: scrape, and p99 regressions are invisible to a count+sum exposition
#: (the gap this histogram closes — ISSUE 13 satellite)
REQUEST_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                           1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class ServerMetrics:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.request_count: dict[str, int] = {}
        self.error_count: dict[str, int] = {}
        self.latency_sum: dict[str, float] = {}
        #: model -> per-bucket counts (len(buckets) + 1, last = +Inf)
        self.latency_buckets: dict[str, list[int]] = {}
        self.inflight = 0

    def observe(self, model: str, seconds: float, error: bool) -> None:
        with self.lock:
            self.request_count[model] = self.request_count.get(model, 0) + 1
            self.latency_sum[model] = self.latency_sum.get(model, 0.0) + seconds
            counts = self.latency_buckets.get(model)
            if counts is None:
                counts = self.latency_buckets[model] = \
                    [0] * (len(REQUEST_LATENCY_BUCKETS) + 1)
            for i, b in enumerate(REQUEST_LATENCY_BUCKETS):
                if seconds <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            if error:
                self.error_count[model] = self.error_count.get(model, 0) + 1

    def prometheus(self) -> str:
        from .traffic import prom_histogram_lines, prom_label

        lines = [
            "# TYPE kft_request_count counter",
            "# TYPE kft_error_count counter",
            "# TYPE kft_requests_inflight gauge",
        ]
        with self.lock:
            for m, c in self.request_count.items():
                lines.append(
                    f'kft_request_count{{model="{prom_label(m)}"}} {c}')
            for m, c in self.error_count.items():
                lines.append(
                    f'kft_error_count{{model="{prom_label(m)}"}} {c}')
            lines.append(f"kft_requests_inflight {self.inflight}")
            # request latency as a REAL fixed-bucket histogram
            # (_bucket/_sum/_count): the previous count+sum exposition
            # could only answer "mean", so a p99 regression was
            # invisible to every scrape.  One shared renderer with the
            # trace layer's phase histograms (traffic.py).
            if self.latency_buckets:
                lines.append("# TYPE kft_request_latency_seconds "
                             "histogram")
                for m in sorted(self.latency_buckets):
                    lines.extend(prom_histogram_lines(
                        "kft_request_latency_seconds",
                        f'model="{prom_label(m)}"',
                        REQUEST_LATENCY_BUCKETS,
                        self.latency_buckets[m],
                        self.latency_sum.get(m, 0.0)))
        return "\n".join(lines) + "\n"


class InferenceLogger:
    """Async request/response payload logging to a sink URL [upstream:
    kserve -> pkg/agent/logger — the ISvc ``logger`` field POSTs
    CloudEvents-framed copies of every inference to a collector].
    Fire-and-forget off a bounded queue: a slow or dead sink drops log
    events (counted) instead of backpressuring the predict path."""

    def __init__(self, url: str, mode: str = "all",
                 service: str = "") -> None:
        if mode not in ("all", "request", "response"):
            raise ValueError(f"logger mode {mode!r}: all|request|response")
        self.url = url
        self.mode = mode
        self.service = service
        self.dropped = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=256)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, name="inference-logger", daemon=True)
        self._thread.start()

    def log(self, kind: str, model: str, req_id: str, payload) -> None:
        if self.mode != "all" and self.mode != kind:
            return
        try:
            self._q.put_nowait((kind, model, req_id, payload))
        except queue.Full:
            self.dropped += 1

    def _pump(self) -> None:
        import urllib.request as _rq

        while not self._stop.is_set():
            try:
                kind, model, req_id, payload = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                # one unserializable payload costs ONE event, never the
                # logger thread (json.dumps can raise on exotic outputs)
                body = json.dumps(payload, default=str).encode()
                req = _rq.Request(self.url, data=body, headers={
                    "Content-Type": "application/json",
                    # CloudEvents binary-mode framing (the kserve contract)
                    "ce-specversion": "1.0",
                    "ce-type": f"org.kubeflow.serving.inference.{kind}",
                    "ce-source": self.service or model,
                    "ce-id": req_id,
                    "ce-modelid": model,
                })
                with _rq.urlopen(req, timeout=2.0):
                    pass
            except Exception as e:  # noqa: BLE001 — delivery is best-effort
                log.debug("inference log delivery to %s failed: %s",
                          self.url, e)
                self.dropped += 1

    def stop(self, drain_timeout: float = 2.0) -> None:
        """Graceful shutdown: give the pump up to ``drain_timeout``
        seconds to deliver what is already enqueued BEFORE raising the
        stop flag — stopping immediately silently discarded everything
        still queued.  Whatever still could not be flushed is counted in
        ``dropped``, never silently lost."""
        deadline = time.monotonic() + max(0.0, drain_timeout)
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)
        self._stop.set()
        self._thread.join(timeout=2)
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
            self.dropped += 1


class ModelServer:
    """Hosts models behind the V1/V2 HTTP protocols (one per replica)."""

    def __init__(self, port: Optional[int] = None):
        self.port = port or allocate_port()
        self._models: dict[str, Model] = {}
        self._batchers: dict[str, MicroBatcher] = {}
        #: name -> (class, config, batch_max, batch_timeout): rebuild specs
        #: for the V2 repository API's unload/load cycle
        self._specs: dict[str, tuple] = {}
        #: serializes repository mutations — load/unload arrive on
        #: concurrent HTTP threads; racing registers would leak batcher
        #: threads and model instances
        self._repo_lock = threading.Lock()
        self.metrics = ServerMetrics()
        #: optional request/response payload logger (set_logger)
        self.logger: Optional[InferenceLogger] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._grpc = None

    def enable_grpc(self, port: Optional[int] = None) -> str:
        """Serve the V2 protocol over gRPC too (kserve's grpc_port analog);
        both wire formats share this repository + micro-batcher.  Returns
        the gRPC address."""
        from .grpc_server import GrpcInferenceServer

        if self._grpc is None:
            self._grpc = GrpcInferenceServer(self, port=port).start()
        elif port and self._grpc.port != port:
            raise RuntimeError(
                f"gRPC already serving on port {self._grpc.port}; "
                f"cannot rebind to {port}")
        return self._grpc.address

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- model repository (dynamic load/unload) ---------------------------

    def register(
        self, model: Model, *, batch_max_size: int = 8, batch_timeout_ms: float = 2.0
    ) -> None:
        if not model.ready:  # idempotent: a live model re-registers as-is
            model.start()
        old_batcher = self._batchers.pop(model.name, None)
        if old_batcher is not None:
            # re-registration must not leak the previous batcher's thread
            old_batcher.stop()
        self._models[model.name] = model
        # remember how to rebuild it: the V2 repository API's unload/load
        # cycle re-instantiates from this spec
        self._specs[model.name] = (
            type(model), dict(model.config), batch_max_size, batch_timeout_ms)
        # self-batching models (continuous.py) coalesce requests inside
        # their own decode loop; routing them through the micro-batcher
        # would serialize requests and defeat token-boundary admission
        if not getattr(model, "self_batching", False):
            self._batchers[model.name] = MicroBatcher(
                model, batch_max_size, batch_timeout_ms)

    def unregister(self, name: str) -> None:
        b = self._batchers.pop(name, None)
        if b:
            b.stop()
        m = self._models.pop(name, None)
        if m:
            m.stop()
        self._specs.pop(name, None)

    # -- V2 repository API (dynamic load/unload) --------------------------

    def unload_model(self, name: str) -> bool:
        """Unload but KEEP the spec so a later load can rebuild (the V2
        repository contract: unloaded models stay indexed, not-ready).
        Idempotent: unloading an already-unloaded (but known) model
        succeeds — retry-safe automation depends on it."""
        with self._repo_lock:
            if name not in self._models:
                return name in self._specs  # known-but-unloaded: no-op ok
            spec = self._specs.get(name)
            self.unregister(name)
            if spec is not None:
                self._specs[name] = spec
            return True

    def load_model(self, name: str) -> bool:
        with self._repo_lock:
            if name in self._models:
                return True  # already live
            spec = self._specs.get(name)
            if spec is None:
                return False
            cls, cfg, bmax, btimeout = spec
            self.register(cls(name, cfg), batch_max_size=bmax,
                          batch_timeout_ms=btimeout)
            return True

    def repository_index(self) -> list[dict]:
        out = []
        for name, spec in self._specs.items():
            live = self._models.get(name)
            out.append({
                "name": name,
                "state": "READY" if live is not None and live.ready else "UNAVAILABLE",
                "reason": "" if live is not None else "unloaded",
            })
        return out

    def engines(self) -> dict[str, Any]:
        """Engine-backed models' engines by model name — the surface
        replica drain (ISSUE 8) walks to migrate live paged
        conversations onto a peer replica before this server stops."""
        out: dict[str, Any] = {}
        for name, model in list(self._models.items()):
            engine = getattr(model, "engine", None)
            if engine is not None:
                out[name] = engine
        return out

    def models(self) -> dict[str, Model]:
        return dict(self._models)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "ModelServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, payload: Any, raw: Optional[bytes] = None,
                      content_type: str = "application/json") -> None:
                body = raw if raw is not None else json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:
                server._handle_get(self)

            def do_POST(self) -> None:
                server._handle_post(self)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"model-server-{self.port}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._grpc is not None:
            self._grpc.stop()
            self._grpc = None
        if self.logger is not None:
            self.logger.stop()
            self.logger = None
        for name in list(self._models):
            self.unregister(name)
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)

    # -- request handling -------------------------------------------------

    def _handle_get(self, h) -> None:
        path = h.path
        if path in ("/v2/health/live", "/healthz"):
            h._send(200, {"live": True})
            return
        if path == "/v2/health/ready":
            ready = all(m.ready for m in self._models.values())
            h._send(200 if ready else 503, {"ready": ready})
            return
        if path == "/traces" or path.startswith("/traces?"):
            # recent completed request traces as JSONL (ISSUE 13):
            # ?slowest=N returns the N slowest retained traces — N
            # TOTAL across models, merged through the shared helper
            # (the router handler uses the same one, so the query
            # contract cannot drift between the two surfaces)
            from .trace import parse_slowest, traces_body

            ok, slowest = parse_slowest(path)
            if not ok:
                h._send(400, {"error": "slowest must be an int"})
                return
            sinks = []
            for _name, model in sorted(self._models.items()):
                tracer = getattr(model, "tracer", None)
                if tracer is not None:
                    tracer.reap()  # finalize adopted (wire) traces
                    sinks.append(tracer.sink)
            h._send(200, None, raw=traces_body(sinks, slowest).encode(),
                    content_type="application/x-ndjson")
            return
        if path == "/metrics":
            # exemplar trace ids are OpenMetrics syntax: attach them
            # ONLY when the scraper negotiated the format (Accept
            # header) — the classic text/plain parser reads the
            # trailer as a malformed timestamp and fails the page
            openmetrics = "application/openmetrics-text" in str(
                h.headers.get("Accept") or "")
            text = self.metrics.prometheus()
            # engine-backed models export their scheduler gauges too
            # (slots, queue depth, prefix-cache economy); one TYPE line
            # per metric family, gauge names without the _total suffix
            # (OpenMetrics reserves it for counters)
            families: dict[str, list[str]] = {}
            for name, model in list(self._models.items()):
                engine = getattr(model, "engine", None)
                stats = getattr(engine, "stats", None)
                if callable(stats):
                    for k, v in stats().items():
                        if isinstance(v, (int, float)):
                            families.setdefault(f"kft_engine_{k}", []).append(
                                f'kft_engine_{k}{{model="{name}"}} {v}')
                # block-registry digest (ISSUE 12): the replica's hot
                # prefixes as chained content keys — the cluster
                # KvBlockRegistry probes these rows (rank-0 for gangs)
                # to route a cold replica's kv_fetch at a peer that
                # already holds the KV
                census = getattr(engine, "prefix_census", None)
                if callable(census) and getattr(engine, "paged", False):
                    from .paged import prefix_digest
                    from .traffic import prom_label

                    try:
                        digest = prefix_digest(census(),
                                               engine.block_size)
                    except Exception as e:  # noqa: BLE001 — a wedged
                        # scheduler must degrade the scrape, not 500 it
                        log.debug("prefix census failed: %s", e)
                        digest = {}
                    for key, depth in sorted(digest.items()):
                        families.setdefault(
                            "kft_kv_prefix_key", []).append(
                            f'kft_kv_prefix_key{{model='
                            f'"{prom_label(name)}",key="{key}"}} '
                            f'{depth}')
                # traffic-plane gauges (QoS admission/shed/preemption
                # accounting — serving/traffic.py) ride the same
                # export; per-class counters carry the class as a
                # LABEL (class names are tenant strings — splicing
                # them into the metric name breaks the exposition)
                plane = getattr(model, "traffic", None)
                if plane is not None:
                    from .traffic import prom_label, prom_stat_lines

                    for fam, lines in prom_stat_lines(
                            plane.stats(), "kft_traffic_",
                            f'model="{prom_label(name)}"').items():
                        families.setdefault(fam, []).extend(lines)
                # AOT program-artifact cache (ISSUE 17): its own
                # kft_aot_* family from the cache itself, dropping the
                # aot_cache_ stat prefix — hit/miss economics + store
                # bytes for the compile-wall dashboards (the engine
                # loop above also exports them as kft_engine_aot_*;
                # these are the canonical names the runbooks use)
                pcache = getattr(engine, "program_cache", None)
                if pcache is not None:
                    from .traffic import prom_label, prom_stat_lines

                    aot_stats = {
                        k[len("aot_cache_"):]: v
                        for k, v in pcache.stats().items()}
                    for fam, lines in prom_stat_lines(
                            aot_stats, "kft_aot_cache_",
                            f'model="{prom_label(name)}"').items():
                        families.setdefault(fam, []).extend(lines)
                # trace-layer gauges ride the same export (sampling
                # accounting); the phase histograms append below as a
                # pre-rendered block — they carry their own TYPE line
                tracer = getattr(model, "tracer", None)
                if tracer is not None:
                    from .traffic import prom_label, prom_stat_lines

                    for fam, lines in prom_stat_lines(
                            tracer.stats(), "kft_trace_",
                            f'model="{prom_label(name)}"').items():
                        families.setdefault(fam, []).extend(lines)
            for fam in sorted(families):
                text += f"# TYPE {fam} gauge\n" + \
                    "\n".join(families[fam]) + "\n"
            # phase-attributed latency histograms
            # (kft_phase_seconds{phase=...} with exemplar trace ids):
            # the scrape-side view of the trace layer — p99s per phase,
            # not just totals (ISSUE 13).  ONE TYPE header across all
            # models: duplicate TYPE lines are an exposition error the
            # promtool-style lint test pins.
            phase_lines: list[str] = []
            for name, model in sorted(self._models.items()):
                tracer = getattr(model, "tracer", None)
                if tracer is not None:
                    from .traffic import prom_label

                    lines = tracer.sink.phase_metrics(
                        base_labels=f'model="{prom_label(name)}"',
                        exemplars=openmetrics)
                    if lines:
                        phase_lines.extend(
                            lines if not phase_lines else lines[1:])
            if phase_lines:
                text += "\n".join(phase_lines) + "\n"
            if openmetrics:
                text += "# EOF\n"
            h._send(200, None, raw=text.encode(),
                    content_type=(
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8" if openmetrics
                        else "text/plain; version=0.0.4"))
            return
        if path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):]
            m = self._models.get(name)
            if m is None:
                h._send(404, {"error": f"model {name} not found"})
                return
            h._send(200, {"name": name, "ready": m.ready})
            return
        if path.startswith("/v2/models/"):
            name = path[len("/v2/models/"):].split("/")[0]
            m = self._models.get(name)
            if m is None:
                h._send(404, {"error": f"model {name} not found"})
                return
            h._send(200, m.metadata())
            return
        h._send(404, {"error": f"unknown path {path}"})

    def _handle_post(self, h) -> None:
        try:
            length = int(h.headers.get("Content-Length", "0"))
            payload = json.loads(h.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            h._send(400, {"error": f"bad request body: {e}"})
            return
        path = h.path
        # V1: /v1/models/<name>:predict
        if path.startswith("/v1/models/") and path.endswith(":predict"):
            name = path[len("/v1/models/"):-len(":predict")]
            self._predict_v1(h, name, payload)
            return
        # V1: /v1/models/<name>:explain (explainer components)
        if path.startswith("/v1/models/") and path.endswith(":explain"):
            name = path[len("/v1/models/"):-len(":explain")]
            self._explain_v1(h, name, payload)
            return
        # V2: /v2/models/<name>/infer
        if path.startswith("/v2/models/") and path.endswith("/infer"):
            name = path[len("/v2/models/"):-len("/infer")]
            self._predict_v2(h, name, payload)
            return
        # OpenAI completions + chat completions (huggingfaceserver
        # parity): routed to models implementing openai_completions /
        # openai_chat (serving/text.py)
        if path in ("/openai/v1/completions",
                    "/openai/v1/chat/completions"):
            chat = path.endswith("/chat/completions")
            call_attr = "openai_chat" if chat else "openai_completions"
            stream_attr = ("openai_chat_stream" if chat
                           else "openai_stream")
            name = payload.get("model", "")
            m = self._models.get(name)
            if m is None or not hasattr(m, call_attr):
                h._send(404, {"error": f"no completions model {name!r}"})
                return
            # request-lifecycle trace (ISSUE 13): continue the router's
            # context (X-KFT-Trace) or sample fresh at this door.  The
            # replica.door phase opens HERE so QoS queue wait at this
            # door is attributed; the engine advances the phase track
            # from submit on, and the trace finalizes in the finally
            # below — on THIS HTTP thread, never the scheduler's.
            tracer = getattr(m, "tracer", None)
            trace = None
            if tracer is not None:
                from .trace import TRACE_HEADER

                trace = tracer.start(h.headers.get(TRACE_HEADER))
                if trace is not None:
                    trace.meta["model"] = name
                    trace.phase("replica.door", stream=bool(
                        payload.get("stream")))
            if payload.get("priority") is not None:
                # validate the client field up front: an unknown tier
                # is a 400 (client mistake), not a mid-generation 500
                # that inflates the router's backend-error counters
                from .traffic import priority_tier

                try:
                    priority_tier(payload["priority"])
                except ValueError as e:
                    if tracer is not None:
                        tracer.finish(trace)
                    h._send(400, {"error": str(e)})
                    return
            # per-tenant QoS front door (serving/traffic.py, ISSUE 9):
            # shed with an explicit 429 + Retry-After BEFORE any engine
            # work — on the SSE path this acquire (which may block,
            # bounded, in the class's admission queue) is the
            # backpressure that replaces unbounded buffering.  A router
            # that already charged the tenant's token bucket forwards
            # X-KFT-Admitted so the bucket is charged exactly once.
            plane = getattr(m, "traffic", None)
            ticket = None
            if plane is not None:
                from .traffic import shed_http

                tenant = str(h.headers.get("X-KFT-Tenant")
                             or payload.get("user") or "default")
                # credentialed tenants prove their claim HERE too —
                # replicas bind loopback, but the class contract must
                # not hinge on which door a local client picked.
                # (X-KFT-Admitted skipping the rate charge remains a
                # loopback-trust convenience, consistent with the rest
                # of ModelServer's unauthenticated local surface.)
                if trace is not None:
                    trace.meta["tenant"] = tenant
                if not plane.authenticate(
                        tenant, h.headers.get("Authorization")):
                    if trace is not None:
                        trace.meta["stall"] = "bad_tenant_credential"
                        tracer.finish(trace)
                    h._send(401, {
                        "error": "tenant credential required",
                        "reason": "bad_tenant_credential",
                        "tenant": tenant,
                    })
                    return
                ticket = plane.acquire(
                    tenant,
                    charge_rate=h.headers.get("X-KFT-Admitted") != "1")
                if not ticket.ok:
                    if trace is not None:
                        # the shed REASON is the stall cause the
                        # autoscaler summary aggregates (ISSUE 13)
                        trace.meta["stall"] = f"shed:{ticket.reason}"
                        tracer.finish(trace)
                    shed_http(h, ticket)
                    return
                if trace is not None and ticket.cls is not None:
                    trace.meta["class"] = ticket.cls.name
            # the class tier is the CONTRACT: this plane's ticket (or,
            # when this replica has no class for the tenant, the
            # router's X-KFT-Priority cluster classification) bounds
            # the payload priority — clients may self-demote, never
            # outrank their class (a spoofed "priority": "high" from a
            # bulk tenant would admit ahead of and preempt for gold)
            if ticket is not None or h.headers.get("X-KFT-Priority"):
                from .traffic import bound_priority

                bound_priority(payload, ticket=ticket,
                               header=h.headers.get("X-KFT-Priority"),
                               classed=(plane is not None
                                        and bool(plane.classes())))
            if trace is not None and hasattr(m, "accept_trace"):
                # thread-local handoff to the runtime (same HTTP
                # thread) — NEVER via the payload dict: the async
                # inference logger serializes that dict off-thread,
                # and an internal key would leak into (or race) the
                # CloudEvents log
                m.accept_trace(trace)
            t0 = time.perf_counter()
            req_id = f"{name}-{time.time_ns()}"
            if self.logger is not None:
                # the LoggerSpec contract covers EVERY request, the
                # OpenAI surface included (streams log the request side)
                self.logger.log("request", name, req_id, payload)
            with self.metrics.lock:  # inflight gauge covers completions too
                self.metrics.inflight += 1
            streaming = False  # SSE headers already on the wire?
            try:
                if payload.get("stream") and hasattr(m, stream_attr):
                    # SSE: tokens stream as the engine emits decode chunks
                    h.send_response(200)
                    h.send_header("Content-Type", "text/event-stream")
                    h.send_header("Cache-Control", "no-cache")
                    h.end_headers()
                    streaming = True
                    for chunk in getattr(m, stream_attr)(payload):
                        h.wfile.write(chunk)
                        h.wfile.flush()
                    self.metrics.observe(
                        name, time.perf_counter() - t0, error=False)
                    return
                out = getattr(m, call_attr)(payload)
                self.metrics.observe(name, time.perf_counter() - t0, error=False)
                if self.logger is not None:
                    self.logger.log("response", name, req_id, out)
                h._send(200, out)
            except BrokenPipeError:
                # client hung up mid-stream: not a server error
                self.metrics.observe(name, time.perf_counter() - t0, error=False)
            except Exception as e:  # noqa: BLE001 — surfaced as 500/SSE error
                log.debug("generate %s failed: %s", name, e)
                self.metrics.observe(name, time.perf_counter() - t0, error=True)
                if streaming:
                    # headers are on the wire: a second status line would
                    # corrupt the event stream.  Emit a terminal SSE error
                    # event + [DONE] so clients terminate cleanly.
                    try:
                        err = json.dumps(
                            {"error": f"{type(e).__name__}: {e}"})
                        h.wfile.write(
                            f"data: {err}\n\ndata: [DONE]\n\n".encode())
                        h.wfile.flush()
                    except (BrokenPipeError, OSError):
                        pass
                else:
                    try:
                        h._send(500, {"error": f"{type(e).__name__}: {e}"})
                    except (BrokenPipeError, OSError):
                        pass
            finally:
                with self.metrics.lock:
                    self.metrics.inflight -= 1
                if plane is not None and ticket is not None:
                    plane.release(ticket)
                if tracer is not None:
                    # finalization (histograms + ring) on this HTTP
                    # worker thread — the response is on the wire
                    tracer.finish(trace)
            return
        # V2 repository API: dynamic load/unload + index
        if path == "/v2/repository/index":
            h._send(200, self.repository_index())
            return
        if path.startswith("/v2/repository/models/"):
            rest = path[len("/v2/repository/models/"):]
            name, _, verb = rest.rpartition("/")
            if verb in ("load", "unload") and name:
                try:
                    ok = (self.load_model(name) if verb == "load"
                          else self.unload_model(name))
                except Exception as e:  # noqa: BLE001 — load() may raise
                    h._send(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                h._send(200 if ok else 404,
                        {"ok": ok} if ok else {"error": f"model {name} unknown"})
                return
        h._send(404, {"error": f"unknown path {path}"})

    def _dispatch(self, name: str, instances: list) -> list:
        batcher = self._batchers.get(name)
        if batcher is None:
            model = self._models.get(name)
            if model is None or not getattr(model, "self_batching", False):
                raise KeyError(name)
        with self.metrics.lock:
            self.metrics.inflight += 1
        try:
            if batcher is not None:
                return batcher.submit(instances)
            # self-batching: call from this request thread; concurrency is
            # the model's own scheduler's job (continuous batching engine)
            return model(instances)
        finally:
            with self.metrics.lock:
                self.metrics.inflight -= 1

    def set_logger(self, url: str, mode: str = "all",
                   service: str = "") -> None:
        """Enable payload logging (the ISvc ``logger`` field)."""
        if self.logger is not None:
            self.logger.stop()
        self.logger = InferenceLogger(url, mode, service)

    def _predict_v1(self, h, name: str, payload: dict) -> None:
        t0 = time.perf_counter()
        req_id = f"{name}-{time.time_ns()}"
        if self.logger is not None:
            self.logger.log("request", name, req_id, payload)
        try:
            instances = payload["instances"]
            out = self._dispatch(name, instances)
            self.metrics.observe(name, time.perf_counter() - t0, error=False)
            if self.logger is not None:
                self.logger.log("response", name, req_id,
                                {"predictions": out})
            h._send(200, {"predictions": out})
        except KeyError as e:
            self.metrics.observe(name, time.perf_counter() - t0, error=True)
            h._send(404 if str(e).strip("'") == name else 400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — surfaced to the client as 500
            log.debug("predict %s failed: %s", name, e)
            self.metrics.observe(name, time.perf_counter() - t0, error=True)
            h._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _explain_v1(self, h, name: str, payload: dict) -> None:
        # explanations are per-request heavy (each fans out its own batched
        # predictor calls), so they bypass the micro-batcher
        t0 = time.perf_counter()
        try:
            instances = payload["instances"]
            m = self._models.get(name)
            if m is None:
                raise KeyError(name)
            with self.metrics.lock:
                self.metrics.inflight += 1
            try:
                out = m.explain_batch(m.preprocess(instances))
            finally:
                with self.metrics.lock:
                    self.metrics.inflight -= 1
            self.metrics.observe(name, time.perf_counter() - t0, error=False)
            h._send(200, {"explanations": out})
        except KeyError as e:
            self.metrics.observe(name, time.perf_counter() - t0, error=True)
            h._send(404 if str(e).strip("'") == name else 400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — surfaced to the client as 500
            log.debug("explain %s failed: %s", name, e)
            self.metrics.observe(name, time.perf_counter() - t0, error=True)
            h._send(500, {"error": f"{type(e).__name__}: {e}"})

    @staticmethod
    def v2_to_instances(payload: dict) -> list:
        """V2 request tensors -> row-major instances of the first input
        (shared by the HTTP and gRPC wire formats)."""
        first = payload["inputs"][0]
        data, shape = first["data"], first.get("shape", [len(first["data"])])
        batch = shape[0] if shape else len(data)
        per = max(1, len(data) // max(batch, 1))
        return [
            data[i * per : (i + 1) * per] if per > 1 else data[i]
            for i in range(batch)
        ]

    @staticmethod
    def v2_response(name: str, out: list) -> dict:
        return {
            "model_name": name,
            "outputs": [{
                "name": "output0",
                "shape": [len(out)],
                "datatype": "FP32",
                "data": out,
            }],
        }

    def _predict_v2(self, h, name: str, payload: dict) -> None:
        t0 = time.perf_counter()
        req_id = payload.get("id") or f"{name}-{time.time_ns()}"
        if self.logger is not None:
            self.logger.log("request", name, req_id, payload)
        try:
            instances = self.v2_to_instances(payload)
            out = self._dispatch(name, instances)
            self.metrics.observe(name, time.perf_counter() - t0, error=False)
            resp = self.v2_response(name, out)
            if self.logger is not None:
                self.logger.log("response", name, req_id, resp)
            h._send(200, resp)
        except KeyError as e:
            self.metrics.observe(name, time.perf_counter() - t0, error=True)
            h._send(404 if str(e).strip("'") == name else 400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — surfaced to the client as 500
            log.debug("predict(v2) %s failed: %s", name, e)
            self.metrics.observe(name, time.perf_counter() - t0, error=True)
            h._send(500, {"error": f"{type(e).__name__}: {e}"})
