"""Explainer component: model explanations next to a predictor.

[upstream: kserve/kserve -> python/kserve explainer examples +
pkg/apis/serving/v1beta1/explainer.go]: KServe's explainer is a third
serving component that answers ``:explain`` by calling the *predictor* for
model outputs and computing attributions around it (Alibi anchors, ART
gradients).  Same topology here: an Explainer is a Model that proxies
``:predict`` to the predictor replicas and implements ``explain_batch`` by
perturbing inputs and scoring them through batched predictor calls — so the
predictor's micro-batcher still sees real batches and the XLA callable runs
full tiles even during explanation.

Built-in method: occlusion saliency (model-agnostic, black-box): mask one
feature segment at a time to a baseline and report the score drop.  It needs
nothing from the predictor but the V1 protocol, which is exactly the
coupling KServe's black-box explainers have.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Optional

from .model import Instances, Model


class Explainer(Model):
    """Base explainer: black-box access to the predictor over V1 HTTP."""

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.predictor_urls: list[str] = list(self.config.get("predictor_urls", []))
        self.model_name = self.config.get("model_name", name)
        self._rr = 0

    def load(self) -> None:
        if not self.predictor_urls:
            raise RuntimeError(f"explainer {self.name}: no predictor_urls")
        self.ready = True

    def _predict_remote(self, instances: Instances) -> Instances:
        if not self.predictor_urls:
            # predictors scaled to zero; the router's activator path owns
            # wake-up, so surface a retryable error instead of crashing
            raise RuntimeError(
                f"explainer {self.name}: no live predictor replicas")
        self._rr = (self._rr + 1) % len(self.predictor_urls)
        url = f"{self.predictor_urls[self._rr]}/v1/models/{self.model_name}:predict"
        body = json.dumps({"instances": instances}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())["predictions"]

    # ``:predict`` through the explainer behaves like a pass-through so one
    # routed URL serves both verbs (KServe routes the verbs to different
    # components; our router does the same via explain backends)
    def predict_batch(self, instances: Instances) -> Instances:
        return self._predict_remote(instances)

    def explain_batch(self, instances: Instances) -> Instances:
        raise NotImplementedError


def _score(pred: Any, class_index: Optional[int]) -> tuple[float, Optional[int]]:
    """Scalar score of one prediction; returns (score, class used)."""
    if isinstance(pred, (int, float)):
        return float(pred), None
    probs = list(pred)
    idx = class_index if class_index is not None else max(
        range(len(probs)), key=lambda i: probs[i])
    return float(probs[idx]), idx


class OcclusionExplainer(Explainer):
    """Occlusion saliency: attribution[i] = score(x) - score(x with segment i
    masked to ``baseline``).  Config:

    - ``num_segments``: feature groups to occlude (default 16, clamped to
      the feature count) — one predictor call of num_segments+1 instances
      per explained instance;
    - ``baseline``: mask value (default 0.0);
    - ``class_index``: fixed output class to score; default = the model's
      top class for the unmasked input.
    """

    def explain_batch(self, instances: Instances) -> Instances:
        out = []
        for inst in instances:
            x = [float(v) for v in inst]
            n_seg = min(int(self.config.get("num_segments", 16)), len(x)) or 1
            baseline = float(self.config.get("baseline", 0.0))
            class_index = self.config.get("class_index")
            bounds = [
                (len(x) * s // n_seg, len(x) * (s + 1) // n_seg)
                for s in range(n_seg)
            ]
            batch: list[list[float]] = [x]
            for lo, hi in bounds:
                masked = list(x)
                masked[lo:hi] = [baseline] * (hi - lo)
                batch.append(masked)
            preds = self._predict_remote(batch)
            base_score, cls = _score(preds[0], class_index)
            attributions = [
                base_score - _score(p, cls if class_index is None else class_index)[0]
                for p in preds[1:]
            ]
            out.append({
                "prediction": preds[0],
                "class_index": cls,
                "base_score": base_score,
                "segments": bounds,
                "attributions": attributions,
            })
        return out
