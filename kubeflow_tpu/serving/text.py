"""Text-in/text-out LLM serving: tokenizer in the server + OpenAI-style
completions.

[upstream: kserve -> python/huggingfaceserver] — the reference's LLM
runtime tokenizes inside the server (clients send text) and exposes the
OpenAI completions API in front of its vLLM/transformers backends.  This
module is that surface over the TPU generation engines:

- ``TextGenerator``: a self-batching Model wrapping ContinuousEngine —
  string prompts in, continuations out, with the tokenizer resolved from
  config (``bytes`` needs nothing; ``hf`` loads a local HuggingFace
  tokenizer directory, e.g. an ``hf://`` snapshot resolved by the
  storage initializer);
- ``ByteTokenizer``: UTF-8 bytes <-> ids — zero-asset, works with any
  vocab >= 256 (the tiny test model's vocab is exactly 256);
- ``HfTokenizer``: ``transformers.AutoTokenizer`` over a LOCAL directory
  (zero-egress deployment: snapshots come from $KFT_HF_HOME);
- the OpenAI completions contract (``openai_completions``), served by
  ModelServer at ``POST /openai/v1/completions``.
"""

from __future__ import annotations

from typing import Any, Optional

from .model import Model


class _ByteIncrementalDecoder:
    """Stateful id-stream decoder for :class:`ByteTokenizer`: feeds new
    ids only, holding incomplete UTF-8 tails instead of re-decoding the
    whole accumulated list (the O(len^2) fix in ``_StopScanner``)."""

    def __init__(self) -> None:
        import codecs

        self._dec = codecs.getincrementaldecoder("utf-8")("replace")

    def decode(self, ids) -> str:
        out: list[str] = []
        for i in ids:
            i = int(i)
            if 0 <= i < 256:
                out.append(self._dec.decode(bytes([i])))
            else:
                # mirror ByteTokenizer.decode: flush any partial char as
                # U+FFFD, then mark the out-of-range id
                out.append(self._dec.decode(b"", True))
                self._dec.reset()
                out.append("�")
        return "".join(out)


class ByteTokenizer:
    """UTF-8 bytes as token ids.  Asset-free; round-trips any text."""

    vocab_size = 256

    def incremental_decoder(self) -> _ByteIncrementalDecoder:
        return _ByteIncrementalDecoder()

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: list[int]) -> str:
        # out-of-range ids (model vocab > 256) become U+FFFD — aliasing
        # them mod 256 would return deterministic-looking garbage as if
        # it were a real completion
        out: list[str] = []
        buf = bytearray()
        for i in ids:
            i = int(i)
            if 0 <= i < 256:
                buf.append(i)
            else:
                out.append(buf.decode("utf-8", errors="replace"))
                buf = bytearray()
                out.append("�")
        out.append(buf.decode("utf-8", errors="replace"))
        return "".join(out)


class HfTokenizer:
    """HuggingFace tokenizer from a LOCAL directory (no hub egress)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

    @property
    def vocab_size(self) -> int:
        # len() covers added tokens beyond the base vocab; the model-vocab
        # compatibility guard in TextGenerator.load depends on this
        return len(self._tok)

    @property
    def eos_id(self) -> Optional[int]:
        return self._tok.eos_token_id

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


class _StopScanner:
    """Incremental stop-sequence search over a growing token stream.

    The 20 ms stop poll used to re-decode the whole accumulated id list
    AND re-scan every stop sequence over the whole text — O(len^2) per
    request (ADVICE r5).  This keeps a decoded-prefix cursor: tokenizers
    exposing ``incremental_decoder()`` (bytes) decode only the NEW ids
    each poll, and the stop search always resumes at the scanned-text
    cursor minus a max-stop-length overlap, so each poll costs O(new
    text), not O(all text).  Tokenizers without incremental decode (HF)
    still re-decode but get the tail-only scan.
    """

    def __init__(self, tokenizer, stops: list[str]) -> None:
        self._tok = tokenizer
        self._stops = [s for s in stops if s]
        self._overlap = max((len(s) for s in self._stops), default=1) - 1
        mk = getattr(tokenizer, "incremental_decoder", None)
        self._dec = mk() if callable(mk) else None
        #: True when ``text`` is maintained by incremental decode (an
        #: exact stable prefix of the full decode, minus any held
        #: incomplete UTF-8 tail) — callers may then reuse it instead of
        #: re-decoding the whole stream
        self.incremental = self._dec is not None
        self._n_ids = 0
        self.text = ""
        self._scanned = 0

    def scan(self, ids) -> Optional[int]:
        """Feed the full id list so far; returns the char index of the
        earliest (newly visible) stop hit, else None."""
        if self._dec is not None:
            if len(ids) > self._n_ids:
                self.text += self._dec.decode(ids[self._n_ids:])
                self._n_ids = len(ids)
        else:
            self.text = self._tok.decode(ids)
        start = max(0, self._scanned - self._overlap)
        cut = None
        end = None
        for ss in self._stops:
            i = self.text.find(ss, start)
            if i >= 0 and (cut is None or i < cut):
                cut, end = i, i + len(ss)
        self._scanned = len(self.text)
        #: char index just past the matched stop (valid when a scan
        #: returned a hit) — the exact-token retirement point for
        #: multi-token bursts (speculative decoding delivers up to
        #: spec_k+1 tokens per dispatch, so a stop routinely COMPLETES
        #: mid-burst and the tail tokens after it must be dropped)
        self.last_hit_end = end
        return cut


def _ids_covering(tokenizer, ids, end_char: int) -> list:
    """Smallest prefix of ``ids`` whose decoded text reaches
    ``end_char`` — the EXACT token at which a stop sequence completed.

    With multi-token bursts (speculative decoding retires up to
    spec_k+1 tokens from one dispatch) a stop routinely completes in
    the middle of a burst; the tokens after it were decoded but never
    belonged to the completion, so token accounting (OpenAI ``usage``)
    and downstream id consumers must not see them.  Uses the
    tokenizer's incremental decoder when it has one (O(len) once per
    hit); falls back to prefix re-decodes otherwise (HF path — one-off
    at the hit, not per poll)."""
    mk = getattr(tokenizer, "incremental_decoder", None)
    if callable(mk):
        dec = mk()
        total = 0
        for i, t in enumerate(ids):
            total += len(dec.decode([t]))
            if total >= end_char:
                return list(ids[: i + 1])
        return list(ids)
    # HF prefix decodes are NOT prefix-stable: a trailing incomplete
    # multi-byte char decodes to U+FFFD, and cleanup passes (HF's
    # clean_up_tokenization_spaces collapses " ," -> ",") shift char
    # counts — a length-only test can cut a token EARLY and drop the
    # stop's tail.  A prefix covers only when its decode actually
    # begins with the scanner's text up to end_char (the scanner's
    # offsets live in the FULL decode's coordinates); when no prefix
    # ever agrees, fall through to all ids — the safe pre-burst answer.
    full = tokenizer.decode(list(ids))
    lo, hi = 0, len(ids)
    while lo < hi:  # first index whose prefix length reaches end_char
        mid = (lo + hi) // 2
        if len(tokenizer.decode(list(ids[: mid + 1]))) < end_char:
            lo = mid + 1
        else:
            hi = mid
    # cleanup can move the boundary by a joiner or two around the
    # binary-searched index, so scan a CONSTANT window around it (the
    # stop's covering token sits within a few tokens of the length
    # boundary; an extended disagreement falls through to all ids, the
    # safe over-count) — O(log n) decodes + a constant tail instead of
    # re-decoding every prefix from 0 on the API worker thread
    for i in range(max(0, lo - 4), min(len(ids), lo + 16)):
        txt = tokenizer.decode(list(ids[: i + 1]))
        if (not txt.endswith("�") and len(txt) >= end_char
                and txt.startswith(full[:end_char])):
            return list(ids[: i + 1])
    return list(ids)


def resolve_tokenizer(config: dict):
    """config["tokenizer"]: "bytes" (default) | {"type": "hf", "path": dir}
    — the hf path may come from the storage initializer (storage_path)."""
    spec = config.get("tokenizer", "bytes")
    if spec == "bytes":
        return ByteTokenizer()
    if isinstance(spec, dict) and spec.get("type") == "hf":
        path = spec.get("path") or config.get("storage_path")
        if not path:
            raise ValueError("hf tokenizer needs a local path "
                             "(tokenizer.path or storage_uri)")
        return HfTokenizer(path)
    if isinstance(spec, str) and spec not in ("bytes",):
        # a bare string is a local tokenizer directory
        return HfTokenizer(spec)
    raise ValueError(f"unknown tokenizer spec {spec!r}")


class TextGenerator(Model):
    """Text completions over the continuous-batching engine.

    config:
      params_ref:  "mem://key" holding (LlamaConfig, params)
      tokenizer:   "bytes" | {"type": "hf", "path": dir}
      max_new_tokens, num_slots, decode_chunk, temperature, eos_id,
      warmup_groups: engine knobs (see serving/continuous.py)

    Instances are prompt STRINGS (or {"prompt": str, "max_tokens": int});
    predictions are continuation strings.  Self-batching: concurrent
    requests coalesce in the engine's slot pool at token boundaries.

    Live KV migration (ISSUE 8) is invisible at this layer BY CONTRACT:
    every wait/stream path below polls the Request HANDLE (tokens list +
    done event), never an engine slot — so when the engine (a
    ``DisaggregatedPool`` handoff, a drain, a rebalance) moves the
    sequence's KV to another pool mid-stream, the same handle simply
    keeps accruing tokens from the new owner.  SSE streams survive the
    hop without a client reconnect, and ``cancel()`` keeps working
    because whichever engine currently owns the slot observes the shared
    done event at its next chunk boundary.
    """

    self_batching = True

    #: seconds of zero stream progress before an SSE comment line is
    #: emitted.  TTFT semantics under chunked prefill (``prefill_budget``
    #: > 0, serving/continuous.py): a long prompt's first token arrives
    #: only after ceil(len/budget) fused dispatches, so a streaming
    #: client may legitimately see NOTHING for the whole admission —
    #: the keep-alive comment (ignored by SSE clients by spec) stops
    #: proxies/clients from timing the connection out mid-prefill.
    KEEPALIVE_S = 15.0

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None,
                 engine=None):
        super().__init__(name, config)
        #: a prebuilt engine (the serving gang's rank-0 GangEngine) —
        #: load() then attaches only the tokenizer: OpenAI completions
        #: on a multi-host predictor
        self.engine = engine
        self.tokenizer = None
        #: per-tenant QoS front door (serving/traffic.py) — built by
        #: load() from config["qos"]; ModelServer consults it on the
        #: OpenAI paths (429 + Retry-After sheds, priority injection)
        self.traffic = None
        #: durable-session storage tier (ISSUE 12) — built by load()
        #: from config["hibernation"] and attached to every paged
        #: engine (hibernate/thaw + the /metrics session gauges)
        self.spill_store = None
        #: idle-session reaper (ISSUE 15 satellite) — built by load()
        #: when config["hibernation"]["reap_idle_s"] is set: quiet
        #: sessions hibernate to the spill store on a clock instead of
        #: only by operator/API action
        self.reaper = None
        #: request-lifecycle tracer (ISSUE 13) — built by load() from
        #: config["tracing"] ({"sample": f, "ring": n}); ModelServer
        #: discovers it here (door spans, /traces, phase histograms)
        #: and every engine behind this runtime shares its sink
        self.tracer = None
        #: the door's trace rides a THREAD-LOCAL from ModelServer's
        #: accept_trace to the openai_* call on the same HTTP thread —
        #: never the payload dict: the async inference logger
        #: serializes that same dict off-thread, and an internal Trace
        #: object (or a pop racing json.dumps) must not leak into the
        #: CloudEvents log
        import threading as _threading

        self._door_trace = _threading.local()

    def _build_traffic(self) -> None:
        qos = self.config.get("qos")
        tokens = self.config.get("qos_tenant_tokens")
        # tokens alone still want a door: a config carrying only
        # qos_tenant_tokens must get its authenticate/401 enforcement,
        # not a silently-absent plane (the phantom-knob failure mode)
        if not qos and not tokens:
            return
        from .traffic import TrafficPlane

        self.traffic = TrafficPlane(
            qos or {}, tenants=self.config.get("qos_tenants"),
            tenant_tokens=tokens)
        if not bool(self.config.get("qos_preempt", True)):
            return
        # priority preemption needs an exportable (paged) pool AND the
        # demand + the victims in the SAME pool (the preemptor watches
        # one engine's waiting list against its own slot table): plain
        # paged engines and the tier ladder (one pool) qualify; the
        # DisaggregatedPool does not — its demand queues on prefill
        # engines while victims decode elsewhere, so evicting there
        # frees nothing the waiter can use.  Disagg still gets
        # priority-ordered admission on its prefill engines; targeted
        # preemption across the handoff is future work.
        engines = ([self.engine] if getattr(self.engine, "paged", False)
                   else [e for e in getattr(self.engine, "pools", [])
                         if getattr(e, "paged", False)
                         and getattr(e, "role", "mixed") == "mixed"])
        for eng in engines:
            self.traffic.attach_engine(eng)

    def accept_trace(self, trace) -> None:
        """ModelServer door -> runtime handoff for the request trace
        (same HTTP thread; the openai_* call takes it back)."""
        self._door_trace.trace = trace

    def _take_trace(self):
        tr = getattr(self._door_trace, "trace", None)
        self._door_trace.trace = None
        return tr

    def _build_tracing(self) -> None:
        """Build the sampling tracer from config["tracing"] and share
        it with every engine behind this runtime (engine-level phase
        observations — spills, wire-import trace adoption — land in
        the same sink the server scrapes)."""
        spec = self.config.get("tracing")
        if not spec:
            return
        from .trace import Tracer, validate_tracing

        self.tracer = Tracer(**validate_tracing(spec))
        engines = ([self.engine]
                   if not getattr(self.engine, "pools", None)
                   else list(self.engine.pools))
        for eng in engines:
            eng.tracer = self.tracer
            if hasattr(eng, "flush_warmup_trace"):
                # build_engine warmed BEFORE the tracer existed: the
                # stashed engine.warmup trace (per-family compile/
                # artifact-load spans) flushes into the sink now
                eng.flush_warmup_trace()

    def _build_hibernation(self) -> None:
        """Attach the manifest-verified spill store (ISSUE 12) to every
        paged engine behind this runtime: sessions hibernate through it
        and any replica configured with the same root can thaw them."""
        hib = self.config.get("hibernation")
        if not hib:
            return
        from .storage import KvSpillStore

        self.spill_store = KvSpillStore(
            str(hib["root"]), fsync=bool(hib.get("fsync", True)))
        for eng in self._hibernation_engines():
            eng.attach_spill_store(self.spill_store)
        reap = hib.get("reap_idle_s")
        if reap:
            from .autoscale import SessionReaper

            # the engine list is re-read every scan so an elastic
            # resize (swap_engine) retargets the clock automatically
            self.reaper = SessionReaper(
                self._hibernation_engines, float(reap),
                interval_s=float(hib.get("reap_interval_s", 1.0)),
            ).start()

    def _hibernation_engines(self) -> list:
        """The paged engines the store is attached to — for a
        DisaggregatedPool that is prefill AND decode tiers (a live
        request owns a slot on exactly one of them)."""
        if getattr(self.engine, "paged", False):
            return [self.engine]
        return [e for e in getattr(self.engine, "pools", [])
                if getattr(e, "paged", False)]

    def hibernate_session(self, req, session_id: str) -> bool:
        """Park a live request durably (engine.hibernate_sequence via
        the attached store) — the blocks spill to storage, the slot
        frees, and ``resume_session`` continues it on ANY replica
        sharing the store root (bit-identical greedy).  Tries every
        paged engine behind this runtime: under disaggregation (or
        after a migration) the sequence may live on any tier, and an
        engine that does not own it just reports nothing-to-export.
        False = the request already finished."""
        if self.spill_store is None:
            raise RuntimeError("no hibernation store configured")
        for eng in self._hibernation_engines():
            if eng.hibernate_sequence(req, session_id):
                return True
        return False

    def resume_session(self, session_id: str, req=None):
        """(req, info): thaw a hibernated session from the store.
        Prefers a decode-capable engine (a prefill-role engine would
        hand the sequence off instead of decoding it), most free
        blocks first."""
        if self.spill_store is None:
            raise RuntimeError("no hibernation store configured")
        engines = self._hibernation_engines()
        decodable = [e for e in engines
                     if getattr(e, "role", "mixed") != "prefill"]
        pool = decodable or engines
        eng = max(pool, key=lambda e: e._alloc.free_blocks)
        return eng.thaw_sequence(session_id, req=req)

    def load(self) -> None:
        from .continuous import build_engine, resolve_model_source

        self.tokenizer = resolve_tokenizer(self.config)
        if self.engine is not None:
            if getattr(self.tokenizer, "vocab_size", 0) > \
                    self.engine.cfg.vocab_size:
                raise ValueError(
                    f"tokenizer needs vocab {self.tokenizer.vocab_size} "
                    f"but the model has {self.engine.cfg.vocab_size}")
            if self.engine.eos_id is None:
                # the gang builds the engine before the tokenizer exists;
                # default the stop token the same way the standalone path
                # does, or gang and in-process deployments of one config
                # would stop differently
                self.engine.eos_id = getattr(self.tokenizer, "eos_id", None)
            self._build_traffic()
            self._build_hibernation()
            self._build_tracing()
            self.ready = True
            return
        cfg, params = resolve_model_source(self.config, name=self.name)
        if getattr(self.tokenizer, "vocab_size", 0) > cfg.vocab_size:
            raise ValueError(
                f"tokenizer needs vocab {self.tokenizer.vocab_size} but the "
                f"model has {cfg.vocab_size}")
        eos = self.config.get("eos_id", getattr(self.tokenizer, "eos_id", None))
        self.engine = build_engine(
            cfg, params, self.config, default_eos=eos,
            default_max_new_tokens=32)
        self._build_traffic()
        self._build_hibernation()
        self._build_tracing()
        self.ready = True

    def swap_engine(self, engine) -> None:
        """Elastic-resize hook (serving/resize.py ``set_engine``):
        re-point the runtime at the new-degree engine AND migrate the
        traffic plane's preemptors — each holds an engine reference
        (its poll thread would silently watch the stopped source
        forever) and possibly PARKED snapshots, which must follow the
        pool so an evicted victim re-imports into the LIVE engine."""
        old, self.engine = self.engine, engine
        if self.tracer is not None and getattr(engine, "tracer",
                                               None) is None:
            # the tracer follows the pool like the preemptors below —
            # phase observations must not silently stop at a resize
            engine.tracer = self.tracer
        if self.traffic is None:
            return
        carried: list = []
        for p in list(self.traffic.preemptors):
            if old is not None and p.engine is old:
                p.stop(fail_parked=False)
                with p._lock:
                    carried.extend(p._parked)
                    p._parked = []
                self.traffic.preemptors.remove(p)
        if getattr(engine, "paged", False) and bool(
                self.config.get("qos_preempt", True)):
            np_ = self.traffic.attach_engine(engine)
            if carried:
                with np_._lock:
                    np_._parked.extend(carried)

    def stop(self) -> None:
        if self.reaper is not None:
            self.reaper.stop()
            self.reaper = None
        if self.traffic is not None:
            self.traffic.stop()
            self.traffic = None
        if self.engine is not None:
            self.engine.stop()
            self.engine = None
        super().stop()

    @staticmethod
    def _priority(value):
        """Payload ``priority`` ("high"/"normal"/"low" or a tier int)
        -> engine tier, None when absent (engine default)."""
        if value is None:
            return None
        from .traffic import priority_tier

        return priority_tier(value)

    def _submit(self, inst):
        # NOTE: no ``priority`` here by design — the V1/V2 predict
        # paths carry no QoS door (no ticket, no header read), so an
        # instance-level priority would be an unbounded client field
        # that outranks every classed tenant.  Priority enters through
        # the OpenAI payload (bounded by ModelServer's
        # ``bound_priority``) or direct ``engine.submit`` calls.
        if isinstance(inst, dict):
            prompt = inst.get("prompt", "")
            max_new = inst.get("max_tokens")
            temp = inst.get("temperature")
            tp, tk = inst.get("top_p"), inst.get("top_k")
        else:
            prompt, max_new, temp, tp, tk = str(inst), None, None, None, None
        return self.engine.submit(self.tokenizer.encode(prompt), max_new,
                                  temperature=temp, top_p=tp, top_k=tk)

    def predict_batch(self, instances):
        assert self.engine is not None, "model not loaded"
        reqs = [self._submit(i) for i in instances]
        return [self.tokenizer.decode(r.wait(300.0)) for r in reqs]

    # -- OpenAI completions contract (huggingfaceserver parity) -----------

    def openai_stream(self, payload: dict):
        """``stream: true`` — yield OpenAI-style SSE chunks as tokens
        land.  The engine's Request accrues tokens per decode chunk, so
        streaming polls the growing token lists (ALL prompts of the
        request, one choice index each).  A delta is emitted only while
        the re-decoded text extends what was already sent — a decode
        boundary can change how the tail decodes (a split UTF-8
        multibyte char, BPE re-merges), and that tail must be HELD until
        it stabilizes, or chunk concatenation diverges from the full
        completion.
        """
        import json as jsonlib
        import time as timelib

        prompts = payload.get("prompt", "")
        if isinstance(prompts, str):
            prompts = [prompts]
        max_tokens = payload.get("max_tokens")
        temp = payload.get("temperature")
        tp, tk = payload.get("top_p"), payload.get("top_k")
        pr = self._priority(payload.get("priority"))
        # the door's request trace rides the FIRST engine request of
        # the fan-out (one trace = one lifecycle; sibling choices share
        # the HTTP-level phases, not the engine spans)
        trace = self._take_trace()
        n = max(1, int(payload.get("n", 1)))  # same fan-out as blocking
        reqs = [
            self.engine.submit(self.tokenizer.encode(str(p)), max_tokens,
                               temperature=temp, top_p=tp, top_k=tk,
                               priority=pr,
                               trace=(trace if i == 0 else None))
            for i, p in enumerate(
                [p for p in prompts for _ in range(n)])
        ]
        sent = [""] * len(reqs)
        finished = [False] * len(reqs)
        last_event = timelib.monotonic()
        model = payload.get("model", self.name)
        stops = self._stop_sequences(payload)
        scanners = ([_StopScanner(self.tokenizer, stops) for _ in reqs]
                    if stops else None)
        try:
            while not all(finished):
                progressed = False
                for i, req in enumerate(reqs):
                    if finished[i]:
                        continue
                    done = req.done.is_set()
                    ids = list(req.tokens)
                    cut = scanners[i].scan(ids) if scanners is not None \
                        else None
                    if scanners is not None and not done:
                        # mid-stream the scanner's text IS the decode —
                        # the stable incremental prefix (bytes) or the
                        # full decode scan() just computed (HF) — so no
                        # second O(len) decode per 20 ms poll (ADVICE
                        # r5); the final flush below still uses the
                        # authoritative full decode
                        full = scanners[i].text
                    else:
                        full = self.tokenizer.decode(ids)
                    if cut is not None:
                        # OpenAI ``stop`` while streaming: truncate at the
                        # earliest stop sequence and end this choice (its
                        # slot frees at the next chunk boundary).  Never
                        # truncate BEHIND already-sent text — a stop that
                        # straddled an emitted boundary can't be unsent,
                        # so the choice just ends where it stands.
                        full = full[:cut] if cut >= len(sent[i]) \
                            else sent[i]
                        done = True
                        req.cancel()
                    if done:
                        # final decode is authoritative; flush everything
                        delta = (full[len(sent[i]):]
                                 if full.startswith(sent[i]) else full)
                        finished[i] = True
                        if req.error is not None:
                            raise req.error
                    elif full.startswith(sent[i]):
                        delta = full[len(sent[i]):]
                    else:
                        continue  # tail not stable yet: hold
                    if delta:
                        sent[i] = sent[i] + delta if not done else full
                        progressed = True
                        last_event = timelib.monotonic()
                        yield ("data: " + jsonlib.dumps({
                            "object": "text_completion.chunk",
                            "model": model,
                            "choices": [{"index": i, "text": delta}],
                        }) + "\n\n").encode()
                if not all(finished) and not progressed:
                    if timelib.monotonic() - last_event > self.KEEPALIVE_S:
                        # a long chunked prefill produces no tokens for
                        # its whole admission — prove the stream alive
                        # (SSE comment; clients ignore it by spec)
                        last_event = timelib.monotonic()
                        yield b": keep-alive\n\n"
                    timelib.sleep(0.02)
            yield b"data: [DONE]\n\n"
        finally:
            # client hung up mid-stream (BrokenPipe -> GeneratorExit) or
            # a sibling prompt errored: stop spending decode slots on a
            # stream nobody is reading
            for req in reqs:
                if not req.done.is_set():
                    req.cancel()

    def openai_completions(self, payload: dict) -> dict:
        """``POST /openai/v1/completions`` body -> response (text
        completions; served by ModelServer for models providing this)."""
        prompts = payload.get("prompt", "")
        if isinstance(prompts, str):
            prompts = [prompts]
        max_tokens = payload.get("max_tokens")
        temp = payload.get("temperature")
        tp, tk = payload.get("top_p"), payload.get("top_k")
        pr = self._priority(payload.get("priority"))
        trace = self._take_trace()
        # OpenAI ``n``: independent samples per prompt — each is its own
        # engine request, coalescing in the slot pool like any burst;
        # the door's trace rides the first (one trace = one lifecycle)
        n = max(1, int(payload.get("n", 1)))
        reqs = [
            self.engine.submit(self.tokenizer.encode(str(p)), max_tokens,
                               temperature=temp, top_p=tp, top_k=tk,
                               priority=pr,
                               trace=(trace if i == 0 else None))
            for i, p in enumerate(
                [p for p in prompts for _ in range(n)])
        ]
        try:
            return self._collect_completions(payload, reqs)
        finally:
            # one prompt's wait() raising must not leave its siblings
            # decoding to nobody (same contract as the streaming path)
            for r in reqs:
                if not r.done.is_set():
                    r.cancel()

    @staticmethod
    def _stop_sequences(payload) -> list[str]:
        stop = payload.get("stop")
        if stop is None:
            return []
        return [stop] if isinstance(stop, str) else [str(x) for x in stop]

    def _apply_stop(self, text: str, stops: list[str]):
        """OpenAI ``stop``: truncate at the EARLIEST stop sequence (the
        sequence itself excluded).  Returns (text, hit)."""
        cut = None
        for ss in stops:
            if not ss:
                continue
            i = text.find(ss)
            if i >= 0 and (cut is None or i < cut):
                cut = i
        return (text if cut is None else text[:cut]), cut is not None

    # -- OpenAI chat completions ------------------------------------------

    def _chat_prompt(self, messages: list) -> str:
        """Messages -> one prompt string: the tokenizer's own chat
        template when it has one (HF tokenizers), else a transparent
        role-tagged transcript ending with the assistant cue."""
        tok = getattr(self.tokenizer, "_tok", None)
        if tok is not None and getattr(tok, "chat_template", None):
            return tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True)
        lines = [
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in messages
        ]
        return "\n".join(lines) + "\nassistant:"

    def openai_chat(self, payload: dict) -> dict:
        """``POST /openai/v1/chat/completions`` — rendered through the
        chat template onto the same engine path as completions (stop, n,
        temperature/top_p/top_k all apply)."""
        comp = {**payload,
                "prompt": self._chat_prompt(payload.get("messages", []))}
        out = self.openai_completions(comp)
        return {
            "object": "chat.completion",
            "model": out["model"],
            "choices": [{
                "index": c["index"],
                "message": {"role": "assistant", "content": c["text"]},
                "finish_reason": c["finish_reason"],
            } for c in out["choices"]],
            "usage": out["usage"],
        }

    def openai_chat_stream(self, payload: dict):
        """``stream: true`` chat — completions chunks re-labeled as
        chat.completion.chunk deltas."""
        import json as jsonlib

        comp = {**payload,
                "prompt": self._chat_prompt(payload.get("messages", []))}
        for chunk in self.openai_stream(comp):
            if not chunk.startswith(b"data: {"):
                yield chunk
                continue
            d = jsonlib.loads(chunk[len(b"data: "):])
            yield ("data: " + jsonlib.dumps({
                "object": "chat.completion.chunk",
                "model": d["model"],
                "choices": [{
                    "index": c["index"],
                    "delta": {"content": c["text"]},
                } for c in d["choices"]],
            }) + "\n\n").encode()

    def _wait_with_stops(self, r, stops: list[str]) -> list[int]:
        """Wait for a request, but with stop sequences the wait POLLS and
        cancels at the first hit — a stop at token 3 must not hold a
        decode slot (or the client) for the remaining max_tokens.  The
        poll is incremental (:class:`_StopScanner`): each pass decodes
        and scans only the tokens that landed since the last one."""
        if not stops:
            return r.wait(300.0)
        import time as timelib

        scanner = _StopScanner(self.tokenizer, stops)
        deadline = timelib.monotonic() + 300.0
        while True:
            done = r.done.is_set()
            ids = list(r.tokens)
            if scanner.scan(ids) is not None:
                # retire at the EXACT token where the stop completed: a
                # burst of accepted speculative tokens may carry the
                # stop mid-burst, and the tokens after it are not part
                # of this completion
                r.cancel()
                return _ids_covering(self.tokenizer, ids,
                                     scanner.last_hit_end)
            if done:
                if r.error is not None:
                    raise r.error
                return ids
            if timelib.monotonic() > deadline:
                raise TimeoutError("generation did not complete in time")
            timelib.sleep(0.02)

    def _collect_completions(self, payload, reqs) -> dict:
        stops = self._stop_sequences(payload)
        choices = []
        completion_tokens = 0
        # each prompt appears n times in reqs (one per choice) but the
        # OpenAI contract counts it ONCE
        n = max(1, int(payload.get("n", 1)))
        prompt_tokens = sum(len(r.prompt) for r in reqs) // n
        for i, r in enumerate(reqs):
            ids = self._wait_with_stops(r, stops)
            completion_tokens += len(ids)  # TOKENS, not decoded chars
            text = self.tokenizer.decode(ids)
            text, stop_hit = self._apply_stop(text, stops)
            eos_hit = (self.engine.eos_id is not None and ids
                       and ids[-1] == self.engine.eos_id)
            choices.append({
                "index": i,
                "text": text,
                "finish_reason": (
                    "stop" if (stop_hit or eos_hit) else "length"),
            })
        return {
            "object": "text_completion",
            "model": payload.get("model", self.name),
            "choices": choices,
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": completion_tokens,
                      "total_tokens": prompt_tokens + completion_tokens},
        }
