"""V2 inference protocol over gRPC — the data plane's second wire format.

[upstream: kserve/kserve -> python/kserve grpc server implementing
inference.GRPCInferenceService (ModelInfer/ModelReady/ServerLive...)].
Same service surface here, attached to an existing ModelServer so both
protocols share one model repository and one micro-batcher.  protoc stubs
aren't available in this image (no grpcio-tools), so like hpo/service.py
the methods ride grpc's generic handler with JSON payloads carrying the
exact V2 message content.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from ..utils.grpcjson import bind_insecure, deserialize as _de, serialize as _ser
from ..utils.net import allocate_port
from .server import ModelServer

SERVICE = "inference.GRPCInferenceService"


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, server: ModelServer):
        self.server = server
        unary = grpc.unary_unary_rpc_method_handler
        self._methods = {
            f"/{SERVICE}/ServerLive": unary(
                self._server_live, _de, _ser),
            f"/{SERVICE}/ServerReady": unary(
                self._server_ready, _de, _ser),
            f"/{SERVICE}/ModelReady": unary(
                self._model_ready, _de, _ser),
            f"/{SERVICE}/ModelMetadata": unary(
                self._model_metadata, _de, _ser),
            f"/{SERVICE}/ModelInfer": unary(
                self._model_infer, _de, _ser),
        }

    def service(self, handler_call_details):
        return self._methods.get(handler_call_details.method)

    def _server_live(self, request: dict, context) -> dict:
        return {"live": True}

    def _server_ready(self, request: dict, context) -> dict:
        return {"ready": all(m.ready for m in self.server.models().values())}

    def _model_ready(self, request: dict, context) -> dict:
        m = self.server.models().get(request.get("name", ""))
        if m is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"model {request.get('name')!r} not found")
        return {"ready": m.ready}

    def _model_metadata(self, request: dict, context) -> dict:
        m = self.server.models().get(request.get("name", ""))
        if m is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"model {request.get('name')!r} not found")
        return m.metadata()

    def _model_infer(self, request: dict, context) -> dict:
        import time

        name = request.get("model_name", "")
        t0 = time.perf_counter()
        if name not in self.server.models():
            self.server.metrics.observe(name, time.perf_counter() - t0, error=True)
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"model {name!r} not found")
        try:
            instances = ModelServer.v2_to_instances(request)
        except (KeyError, IndexError, TypeError) as e:
            self.server.metrics.observe(name, time.perf_counter() - t0, error=True)
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"malformed V2 request: {e}")
        try:
            # through the SAME micro-batcher as the HTTP path, so gRPC
            # requests coalesce with HTTP ones into full XLA batches
            out = self.server._dispatch(name, instances)
            self.server.metrics.observe(
                name, time.perf_counter() - t0, error=False)
            return ModelServer.v2_response(name, out)
        except KeyError as e:
            # the unregister race: model vanished between check and dispatch
            self.server.metrics.observe(
                name, time.perf_counter() - t0, error=True)
            if str(e).strip("'") == name:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"model {name!r} not found")
            context.abort(grpc.StatusCode.INTERNAL, f"KeyError: {e}")
        except Exception as e:  # noqa: BLE001 — surface as RPC error
            self.server.metrics.observe(
                name, time.perf_counter() - t0, error=True)
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")


class GrpcInferenceServer:
    """V2 gRPC front for a ModelServer (kserve's grpc_port analog)."""

    def __init__(self, model_server: ModelServer,
                 port: Optional[int] = None, max_workers: int = 4):
        self.port = port or allocate_port()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((_Handler(model_server),))
        bind_insecure(self._server, "127.0.0.1", self.port)

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "GrpcInferenceServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1.0)


class GrpcInferenceClient:
    """Minimal V2 gRPC client (infer/ready/metadata), JSON payloads."""

    def __init__(self, address: str):
        self._channel = grpc.insecure_channel(address)
        self._calls: dict = {}

    def _call(self, method: str, payload: dict, timeout: float = 30.0) -> dict:
        fn = self._calls.get(method)
        if fn is None:  # one multicallable per method, built once
            fn = self._channel.unary_unary(
                f"/{SERVICE}/{method}", request_serializer=_ser,
                response_deserializer=_de)
            self._calls[method] = fn
        return fn(payload, timeout=timeout)

    def server_live(self) -> bool:
        return bool(self._call("ServerLive", {})["live"])

    def model_ready(self, name: str) -> bool:
        return bool(self._call("ModelReady", {"name": name})["ready"])

    def model_metadata(self, name: str) -> dict:
        return self._call("ModelMetadata", {"name": name})

    def infer(self, model_name: str, data: list, shape: Optional[list] = None,
              timeout: float = 60.0) -> list:
        out = self._call("ModelInfer", {
            "model_name": model_name,
            "inputs": [{
                "name": "input0",
                "shape": shape or [len(data)],
                "datatype": "FP32",
                "data": data,
            }],
        }, timeout=timeout)
        return out["outputs"][0]["data"]

    def close(self) -> None:
        self._channel.close()
