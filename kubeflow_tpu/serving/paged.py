"""Paged KV: the block-granular cache economy under every engine variant.

[upstream: kserve huggingfaceserver's vLLM backend] — vLLM's defining
memory design is *PagedAttention*: KV lives in fixed-size blocks owned by
a free-list allocator, requests hold per-sequence block tables, and
prefix sharing/copy-on-write happen at block granularity (ISSUE 6,
ROADMAP item 1).  The slot pool this replaces reserved ``max_seq_len``
contiguous KV per slot — a 32-token conversation paid for 4096 — and its
four parallel sharing regimes (slot-copy prefix cache, refcounted
whole-segment LCP, the tier ladder, int8 KV) each needed their own
programs and admission paths.

TPU-first shape of the port (vs vLLM's CUDA paged-attention kernels):
XLA wants static shapes and the models' decode math already operates on
a contiguous per-row cache, so the paged programs in
serving/continuous.py GATHER each dispatch's working view from the
block pool (``gather_block_view``: per-slot block tables -> the exact
[slots, attend, ...] layout the existing decode/prefill/verify bodies
consume, warmed per attend rung so ``jit_recompiles_total`` stays 0)
and scatter the written blocks back (``scatter_block_view``).  The
attention/sampling math is byte-identical to the slot-pool programs —
greedy parity against every pre-paged variant is the refactor's bar —
while the *storage* becomes block-granular: allocation tracks actual
sequence length, prefixes share in ``block_size`` quanta across live
AND retired sequences, and a diverging request forks the boundary block
with one on-device copy (COW).

Host side, this module owns :class:`BlockAllocator`: free list with
LRU-ordered reuse (a freed block keeps its bytes AND its token-content
registration until reallocated, so the free list doubles as the prefix
cache — the vLLM free-list-as-cache move), refcounts for block sharing,
and the retired-sequence registry the engine's prefix matcher consults.
Everything here is host numpy on the scheduler thread; the analyzer's
``host-sync-in-dispatch`` rule walks ``*Allocator`` classes exactly so
a stray ``.item()`` on the free list can never creep into the dispatch
path.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def gather_block_view(pool, bt, block_axes, seq_axes):
    """Per-row contiguous KV view gathered from the block pool.

    ``pool``: cache pytree shaped like a slot pool but with the row axis
    = blocks and the seq axis = ``block_size`` (cache_shapes of a
    block-sized config).  ``bt``: [rows, nblk] int32 block tables; an
    out-of-range id (the pad sentinel) clips to the last block — its
    bytes are garbage the per-row causal mask already hides, exactly the
    slot pool's stale-KV argument.  ``block_axes``/``seq_axes``: per-leaf
    (row, seq) axis trees probed on the block pool; the view's layout
    mirrors the pool's (k/v keep seq right after the row axis, int8-KV
    scale buffers keep it LAST), so the same trees drive both hops.

    Returns the [rows, nblk*block_size, ...] view every leaf — the exact
    buffer layout the slot-pool decode/prefill/verify bodies consume.
    """
    def leaf(c, a, s):
        if a is None:  # cache_index bookkeeping: shape-free passthrough
            return c
        # mode="clip": the pad sentinel reads the LAST block — finite
        # garbage the causal mask hides (fill-mode NaNs would poison the
        # masked lanes instead of being ignored)
        g = jnp.take(c, bt, axis=a, mode="clip")  # rows at a, nblk at a+1
        g = jnp.moveaxis(g, s + 1, a + 2)    # [..., rows, nblk, bs, ...]
        sh = list(g.shape)
        sh[a + 1:a + 3] = [sh[a + 1] * sh[a + 2]]
        g = g.reshape(sh)                    # merged seq at a+1
        return jnp.moveaxis(g, a + 1, s)     # seq back to its layout slot

    return jax.tree.map(leaf, pool, block_axes, seq_axes)


def scatter_block_view(pool, view, bt, block_axes, seq_axes):
    """Write a gathered view's blocks back into the pool at ``bt``.

    Every gathered block scatters (mode="drop": the pad sentinel's
    writes vanish).  Blocks shared by several rows of one dispatch are
    full immutable prefix blocks — no row may write below its own front,
    so duplicate indices carry identical bytes and the write order XLA
    picks is invisible.
    """
    def leaf(c, v, a, s):
        if a is None:
            return c
        w = jnp.moveaxis(v, s, a + 1)        # seq right after the row axis
        sh = list(w.shape)
        sh[a + 1:a + 2] = [bt.shape[1], c.shape[s]]
        w = w.reshape(sh)                    # [..., rows, nblk, bs, ...]
        w = jnp.moveaxis(w, a + 2, s + 1)    # bs back to the pool's seq slot
        idx = (slice(None),) * a + (bt,)
        return c.at[idx].set(w, mode="drop")

    return jax.tree.map(leaf, pool, view, block_axes, seq_axes)


def write_window_tables(bt, front, block_size: int):
    """Scatter-side block tables narrowed to the WRITTEN suffix window.

    A dispatch writes row ``r`` only at positions >= ``front[r]`` (decode
    at the position front, a prefill chunk at its start offset, inactive
    rows nowhere — their front is the view length).  Blocks that END
    below the front — every shared prefix block under refcount > 1, and
    every block of a row this dispatch cannot write — were round-tripped
    through gather/scatter as an identity write (PERF r10's visible
    paged-KV tax).  Masking their table entries out of range makes the
    scatter's ``mode="drop"`` skip them: the gather still uses the full
    table (reads are the attention math), only the write-back narrows.
    """
    nblk = bt.shape[1]
    first = front.astype(jnp.int32) // jnp.int32(block_size)
    keep = jnp.arange(nblk, dtype=jnp.int32)[None, :] >= first[:, None]
    return jnp.where(keep, bt, jnp.int32(np.iinfo(np.int32).max))


def block_keys(tokens, block_size: int, max_blocks: int = 64) -> list[int]:
    """Chained content keys for a token sequence's FULL prefix blocks.

    ``key[i]`` identifies the exact token content of blocks ``[0, i]`` —
    each key hashes the previous key plus the block's tokens, so two
    sequences share ``key[i]`` iff their first ``(i+1) * block_size``
    tokens are identical.  This is the block economy's identity at the
    granularity the allocator shares KV (full blocks by refcount): the
    traffic plane's prefix-affinity router (serving/traffic.py) matches
    these keys against where it last routed them, because a replica that
    served a prefix holds its blocks — live, or retired-but-registered
    in the allocator's free-list-as-cache.  Host-side stdlib hashing
    only (runs per request on router/server threads, never on a
    scheduler thread)."""
    import hashlib

    n = min(len(tokens) // block_size, max_blocks)
    keys: list[int] = []
    h = hashlib.blake2b(digest_size=8)
    for i in range(n):
        blk = tokens[i * block_size:(i + 1) * block_size]
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        h.update(np.asarray(blk, np.int64).tobytes())
        keys.append(int.from_bytes(h.digest(), "little"))
        h = hashlib.blake2b(h.digest(), digest_size=8)
    return keys


def prefix_digest(token_records, block_size: int,
                  max_entries: int = 64) -> dict[str, int]:
    """``{hex key: block depth}`` for the deepest chained content key of
    each token record — the replica's block-registry digest (ISSUE 12).

    Exported at ``/metrics`` as ``kft_kv_prefix_key{key="..."}`` rows;
    a :class:`~.traffic.KvBlockRegistry` probing rank-0 metrics learns
    which replica holds which hot prefix, so a cold replica can fetch
    the KV over the ``kv_fetch`` wire instead of recomputing it.  The
    WHOLE chain publishes per record (a query sharing only the first i
    blocks probes ``key[i-1]``, which must be present), deduped across
    records and bounded at ``max_entries`` deepest-first.  Stdlib
    hashing on the caller's (HTTP scrape) thread — the engine hands
    out token copies via ``prefix_census``, never hashes on its
    scheduler."""
    depths: dict[str, int] = {}
    for toks in token_records:
        for i, k in enumerate(block_keys(toks, block_size)):
            kh = f"{k:016x}"
            depths[kh] = max(depths.get(kh, 0), i + 1)
    if len(depths) > max_entries:
        deepest = sorted(depths.items(), key=lambda kv: -kv[1])
        depths = dict(deepest[:max_entries])
    return depths


def resize_block_budget(num_blocks: int, src_degree: int, dst_degree: int,
                        *, reserved: int = 0) -> int:
    """Block count for a pool rebuilt at a new TP degree (ISSUE 10).

    The KV pool shards its kv_heads axis over the TP mesh, so per-chip
    pool HBM is ``total / degree``: a gang shrinking from N to M chips
    must shrink the pool to ``num_blocks * M / N`` to keep the per-chip
    bill constant (and may grow it back symmetrically).  Floored at
    ``reserved`` — the full worst-case span the surviving live sequences
    already hold (admission semantics: a resize must never evict
    mid-decode) — and at 1."""
    if src_degree < 1 or dst_degree < 1:
        raise ValueError("degrees must be >= 1")
    scaled = (int(num_blocks) * int(dst_degree)) // int(src_degree)
    return max(scaled, int(reserved), 1)


def lcp(content, prompt_arr: np.ndarray, cap: int) -> int:
    """Longest common prefix of a token sequence and the prompt array,
    capped — vectorized, runs per candidate per admission on the
    scheduler thread (the ONE implementation: the engine's slot/segment
    matchers and the allocator registry both import it)."""
    n = min(len(content), cap)
    if n <= 0:
        return 0
    # analysis: ok host-sync-in-dispatch — host token list, no device value
    c = np.asarray(content[:n], np.int64)
    neq = np.nonzero(c != prompt_arr[:n])[0]
    return int(neq[0]) if neq.size else n


class BlockAllocator:
    """Fixed-size KV block economy: free list, refcounts, COW counters,
    and the retired-sequence prefix registry.

    Block ids are [0, num_blocks); the dispatch-side pad sentinel is
    ``num_blocks`` itself (out of range: gathers clip, scatters drop) so
    every pool row is a real allocatable block.

    Free-list-as-cache: ``release`` appends a refcount-zero block to the
    tail of an ordered free map WITHOUT clearing it — its bytes stay in
    HBM and any sequence registered over it stays prefix-matchable.
    ``alloc`` pops from the head (oldest-freed first, the LRU eviction
    order) and only THEN invalidates registrations touching the block —
    reuse costs a dict pop, never a clearing dispatch.  ``ref`` on a
    zero-ref block resurrects it out of the free list (a prefix hit on
    a retired conversation's blocks).
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._refs = np.zeros(self.num_blocks, np.int64)
        #: insertion-ordered free map: keys are free block ids, oldest
        #: freed first (the eviction order); values unused
        self._free: "OrderedDict[int, None]" = OrderedDict(
            (b, None) for b in range(self.num_blocks))
        #: retired sequences still matchable: seq_id -> (tokens, blocks)
        #: (insertion-ordered: oldest registration evicts first)
        self._seqs: dict[int, tuple[np.ndarray, tuple[int, ...]]] = {}
        self._block_seqs: dict[int, set[int]] = {}
        self._next_seq = 0
        #: registry bound: a hot shared prefix re-registers on EVERY
        #: retirement while resurrection keeps its blocks off the
        #: alloc path (the only lazy pruner), so without a cap the
        #: registry — and the per-admission match() scan — grows with
        #: traffic, not with the pool.  There are at most num_blocks
        #: distinct useful first-blocks, so that is the natural bound.
        self._max_seqs = self.num_blocks
        self.cow_copies_total = 0
        self.prefix_block_hits_total = 0

    # -- capacity ---------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def pad_block(self) -> int:
        """Out-of-range id used to pad block tables (gather clips,
        scatter drops)."""
        return self.num_blocks

    # -- allocation / refcounts ------------------------------------------

    def alloc(self, n: int) -> Optional[list[int]]:
        """Pop ``n`` blocks off the free list (refcount 1 each), oldest
        freed first; None when fewer than ``n`` are free — the caller's
        admission backpressure, never a partial grant."""
        if n < 0:
            raise ValueError("alloc count must be >= 0")
        if n > len(self._free):
            return None
        out: list[int] = []
        for _ in range(n):
            b, _ = self._free.popitem(last=False)
            self._refs[b] = 1
            self._invalidate(b)
            out.append(b)
        return out

    def ref(self, blocks) -> None:
        """Take a reference on each block (prefix sharing).  A zero-ref
        block resurrects out of the free list — its bytes were never
        cleared, so the cached KV is still ground truth."""
        for b in blocks:
            if self._refs[b] == 0:
                self._free.pop(b, None)
            self._refs[b] += 1

    def release(self, blocks) -> None:
        """Drop one reference per block; refcount-zero blocks join the
        free-list TAIL uncleaned (reuse without clearing — the per-row
        causal mask hides stale bytes, and registrations stay valid)."""
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] < 0:
                raise RuntimeError(f"block {b} over-released")
            if self._refs[b] == 0:
                self._free[b] = None

    # -- retired-sequence prefix registry --------------------------------

    def register(self, tokens, blocks) -> None:
        """Record a retired sequence (its KV still sits in ``blocks``)
        for future prefix matches; entries die lazily when a covering
        block is reallocated."""
        cover = -(-len(tokens) // self.block_size)
        blocks = tuple(int(b) for b in blocks[:cover])
        if not blocks or len(tokens) < self.block_size:
            return  # nothing shareable at block granularity
        sid = self._next_seq
        self._next_seq += 1
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        self._seqs[sid] = (np.asarray(tokens, np.int64), blocks)
        for b in blocks:
            self._block_seqs.setdefault(b, set()).add(sid)
        while len(self._seqs) > self._max_seqs:
            self._drop_seq(next(iter(self._seqs)))

    def _drop_seq(self, sid: int) -> None:
        entry = self._seqs.pop(sid, None)
        if entry is None:
            return
        for b in entry[1]:
            peers = self._block_seqs.get(b)
            if peers:
                peers.discard(sid)
                if not peers:
                    del self._block_seqs[b]

    def _invalidate(self, block: int) -> None:
        for sid in list(self._block_seqs.pop(block, ())):  # content dies
            self._drop_seq(sid)

    def match(self, prompt_arr: np.ndarray, cap: int
              ) -> tuple[tuple[int, ...], int]:
        """Best retired-sequence prefix match: (blocks, lcp tokens).
        The caller shares ``lcp // block_size`` full blocks by ref and
        may COW-fork the boundary block for the partial remainder."""
        best_blocks: tuple[int, ...] = ()
        best = 0
        for tokens, blocks in self._seqs.values():
            lim = min(len(tokens), len(blocks) * self.block_size, cap)
            if lim <= best:
                continue
            n = lcp(tokens, prompt_arr, lim)
            if n > best:
                best, best_blocks = n, blocks
        return best_blocks, best

    def stats(self) -> dict:
        return {
            "kv_block_size": self.block_size,
            "kv_blocks_total": self.num_blocks,
            "kv_blocks_free": len(self._free),
            "kv_blocks_cow_copies_total": self.cow_copies_total,
            "prefix_block_hits_total": self.prefix_block_hits_total,
        }


class HostBlockPool:
    """Host-RAM tier of the paged-KV economy (ISSUE 12, ROADMAP 3).

    The HBM free-list-as-cache keeps a retired conversation's KV only
    until its blocks are REALLOCATED — at production load the hot
    prefix set outlives that window by hours.  This pool is the next
    rung down: a bounded numpy mirror of spilled sequences' block bytes
    (host RAM is ~10x the HBM pool and a restore-scatter is ~100x
    cheaper than re-prefilling the same tokens), content-addressed by
    token prefix exactly like the allocator registry, LRU-evicted at
    ``capacity_blocks``.

    Thread contract (the analyzer's ``*Tier``/``*Spill`` roots pin the
    inverse): everything here is host numpy under one flat lock.  The
    ENGINE dispatches spill gathers on its scheduler thread (pure
    dispatch, no fetch) and a tier worker thread materializes + ``put``s
    them here; admission-time ``match``/``take`` run on the scheduler
    thread and are dict walks over host arrays — no device value ever
    enters this class, and no method of it may block on I/O.
    """

    def __init__(self, capacity_blocks: int, block_size: int):
        if capacity_blocks < 1:
            raise ValueError("capacity_blocks must be >= 1")
        from threading import Lock

        self.capacity_blocks = int(capacity_blocks)
        self.block_size = int(block_size)
        self._lock = Lock()
        #: hid -> {"tokens": np.int64[], "blocks": [leaf-list per block],
        #: "nbytes": int} — insertion/touch-ordered (LRU eviction)
        self._seqs: "OrderedDict[int, dict]" = OrderedDict()
        self._next = 0
        self.blocks_held = 0
        self.bytes_held = 0
        self.spills_total = 0
        self.restores_total = 0
        self.evictions_total = 0

    def put(self, tokens, blocks: list, nbytes: Optional[int] = None) -> int:
        """Admit one spilled sequence (``blocks`` = host leaf-lists, one
        per FULL block of ``tokens``); LRU-evicts older entries to fit.
        Returns the entry id.  A sequence wider than the whole pool is
        truncated to the capacity prefix — the hot part of a prefix is
        its head."""
        blocks = list(blocks)[: self.capacity_blocks]
        n = len(blocks)
        if n == 0:
            return -1
        if nbytes is None:
            # analysis: ok host-sync-in-dispatch — leaves are host numpy (tier worker)
            nbytes = sum(int(np.asarray(x).nbytes)
                         for blk in blocks for x in blk)
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        toks = np.asarray(list(tokens)[: n * self.block_size], np.int64)
        with self._lock:
            hid = self._next
            self._next += 1
            self._seqs[hid] = {"tokens": toks, "blocks": blocks,
                               "nbytes": int(nbytes)}
            self.blocks_held += n
            self.bytes_held += int(nbytes)
            self.spills_total += 1
            # the truncation above bounds any single entry at capacity,
            # so evicting older entries always converges
            while self.blocks_held > self.capacity_blocks:
                self._evict_oldest()
            return hid if hid in self._seqs else -1

    def _evict_oldest(self) -> None:
        _hid, entry = self._seqs.popitem(last=False)
        self.blocks_held -= len(entry["blocks"])
        self.bytes_held -= entry["nbytes"]
        self.evictions_total += 1

    def match(self, prompt_arr: np.ndarray, cap: int
              ) -> tuple[int, int]:
        """(hid, lcp tokens) of the deepest host-tier prefix of the
        prompt; (-1, 0) on a miss.  Same contract as
        :meth:`BlockAllocator.match`, one tier down."""
        best_hid, best = -1, 0
        with self._lock:
            for hid, entry in self._seqs.items():
                toks = entry["tokens"]
                lim = min(len(toks), cap)
                if lim <= best:
                    continue
                n = lcp(toks, prompt_arr, lim)
                if n > best:
                    best_hid, best = hid, n
        return best_hid, best

    def take(self, hid: int, nblocks: int) -> Optional[list]:
        """The first ``nblocks`` host leaf-lists of entry ``hid`` (a
        restore reads only the matched full blocks), LRU-touched; None
        when the entry was evicted between match and take."""
        with self._lock:
            entry = self._seqs.get(hid)
            if entry is None:
                return None
            self._seqs.move_to_end(hid)
            self.restores_total += 1
            return entry["blocks"][:nblocks]

    def contains_prefix(self, tokens, min_tokens: int = 1) -> bool:
        """True when some entry already covers >= min_tokens of
        ``tokens`` — the spill path's dedup probe (re-spilling a hot
        shared prefix on every retirement would churn the LRU)."""
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        arr = np.asarray(list(tokens), np.int64)
        _hid, n = self.match(arr, len(arr))
        return n >= max(int(min_tokens), 1)

    def stats(self) -> dict:
        with self._lock:
            return {
                "kv_blocks_host_tier": self.blocks_held,
                "kv_host_bytes": self.bytes_held,
                "kv_host_capacity_blocks": self.capacity_blocks,
                "kv_host_spills_total": self.spills_total,
                "kv_host_restores_total": self.restores_total,
                "kv_host_evictions_total": self.evictions_total,
            }
