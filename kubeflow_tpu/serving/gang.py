"""Multi-host serving: the predictor as a gang.

The reference serves multi-accelerator models by giving the predictor pod
N GPUs and letting vLLM/Triton span them inside one container [upstream:
kserve/kserve -> python/huggingfaceserver; SURVEY.md §2.2 per-framework
runtimes, §3.3 predictor hot path].  A TPU pod slice is different: a
v5e-4x4 is 4 HOSTS x 4 chips — no single process addresses all 16 chips,
so a TP=16 predictor is necessarily a *gang* of cooperating host
processes executing the same SPMD programs in lockstep (the multi-host
jit contract, SURVEY.md §2.6) — exactly the shape this platform already
launches for training (runtime/bootstrap.py env triple ->
``jax.distributed.initialize`` -> global mesh).

Design — rank 0 decides, everyone dispatches:

- every gang member loads the same snapshot, builds the same
  ``ContinuousEngine`` programs over the same global serving mesh
  (``engine_kwargs`` keeps the knobs byte-identical), and contributes its
  addressable shards of the weights (serving/sharded.py
  ``place_params``);
- rank 0 additionally owns the HTTP frontend (``ModelServer``) and the
  engine's scheduler thread.  The scheduler's *decisions* — which
  requests admit into which slots, the decode schedule, sampling keys —
  are host-side numpy scalars/arrays; :class:`GangChannel` streams them
  to the followers as length-prefixed pickles over TCP **before** rank 0
  dispatches, so every host issues the identical dispatch sequence and
  XLA's collectives line up;
- device data never crosses the channel: weights, the KV slot pool and
  logits live sharded across the gang's chips; the only host fetch is
  rank 0's sampled-token read, which the decode program replicates
  (``constrain_replicated``) so rank 0 can read it locally.

The dispatched programs are the SAME ones the single-process engine (and
the AOT artifact, scripts/aot_7b_serving.py) compiles — the gang changes
where processes sit, not what runs.  ``__graft_entry__.dryrun_multichip``'s
serving leg therefore covers the gang's data plane.

Failure semantics ride the JaxJob machinery: the InferenceService
controller places the gang as a JaxJob (serving/controller.py
``_GangPredictor``); a crashed member fails its pod, the JaxJob
controller gang-restarts, and rank 0 re-binds the same frontend port.
"""

from __future__ import annotations

import json
import os
import pickle
import signal
import socket
import struct
import threading
import time
from typing import Any, Optional

import numpy as np

from . import continuous as contlib
from ..runtime import bootstrap

#: pod-env key holding the JSON serving config (engine knobs +
#: storage_path + serve_port + gang_port) the ISvc controller freezes at
#: gang-placement time
ENV_SERVE_CONFIG = "KFT_SERVE_CONFIG"

_LEN = struct.Struct("!Q")


class ChannelClosed(RuntimeError):
    """The control stream died (a peer crashed or shut down)."""


class GangChannel:
    """Rank-0 -> followers control stream: length-prefixed pickles over
    TCP.  Carries ONLY host-side scheduler decisions (op tag + numpy
    args) between mutually-trusting gang members of one job — never
    request payloads to the outside world and never device data.

    Trust boundary: the stream is pickle between processes of ONE JaxJob,
    so admission to it is guarded by a per-job shared ``token`` (frozen
    into the gang's env by the ISvc controller, like the pod's other
    credentials) — a follower must present it before it may occupy a
    slot, and rank 0 closes anything that doesn't.  Deserialization
    still trusts rank 0, which is the same trust a follower already
    extends to the process that chose its dispatch stream.
    """

    def __init__(self, conns: list[socket.socket], rank: int) -> None:
        self._conns = conns
        self.rank = rank
        self._lock = threading.Lock()

    # -- construction ------------------------------------------------------

    @classmethod
    def listen(cls, port: int, num_followers: int, token: str = "",
               timeout: float = 60.0) -> "GangChannel":
        """Rank 0: accept every follower (they dial after the gang
        barrier, so all are alive or the job already failed).  A
        connection that fails the token handshake is dropped without
        consuming a follower slot."""
        import hmac

        want = token.encode()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", port))
        srv.listen(max(num_followers, 1))
        srv.settimeout(timeout)
        deadline = time.monotonic() + timeout
        conns: list[socket.socket] = []
        try:
            while len(conns) < num_followers:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"only {len(conns)}/{num_followers} followers "
                        "passed the gang handshake")
                c, _addr = srv.accept()
                try:
                    c.settimeout(5.0)
                    (n,) = _LEN.unpack(cls._read_exact(c, _LEN.size))
                    got = cls._read_exact(c, n) if n <= 4096 else b""
                    if not hmac.compare_digest(got, want):
                        raise ChannelClosed("bad gang token")
                    c.settimeout(None)
                    c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    conns.append(c)
                except (OSError, ChannelClosed, struct.error):
                    c.close()
        finally:
            srv.close()
        return cls(conns, rank=0)

    @classmethod
    def connect(cls, host: str, port: int, rank: int, token: str = "",
                timeout: float = 60.0) -> "GangChannel":
        payload = token.encode()
        deadline = time.monotonic() + timeout
        while True:
            try:
                c = socket.create_connection((host, port), timeout=5.0)
                c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                c.sendall(_LEN.pack(len(payload)) + payload)
                c.settimeout(None)
                return cls([c], rank=rank)
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    # -- wire --------------------------------------------------------------

    def publish(self, msg: tuple) -> None:
        payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        frame = _LEN.pack(len(payload)) + payload
        with self._lock:
            for c in self._conns:
                try:
                    c.sendall(frame)
                except OSError as e:
                    raise ChannelClosed(f"follower gone: {e}") from e

    def next(self) -> tuple:
        (c,) = self._conns
        header = self._read_exact(c, _LEN.size)
        (n,) = _LEN.unpack(header)
        return pickle.loads(self._read_exact(c, n))

    @staticmethod
    def _read_exact(c: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = c.recv(n - len(buf))
            if not chunk:
                raise ChannelClosed("rank 0 closed the control stream")
            buf += chunk
        return buf

    def close(self) -> None:
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass


class GangEngine(contlib.ContinuousEngine):
    """Rank-0 engine: every compiled-program call publishes its host args
    before dispatching, so follower hosts replay the identical SPMD
    dispatch stream against their shards (see :func:`follow`).

    The wrap happens at the program-getter layer — the scheduler, the
    admission batching, prefix-cache routing and warmup all run
    UNMODIFIED; only the four dispatch sites gain a publish.  Host args
    are normalized to numpy on both sides of the wire (a process-local
    device array cannot feed a global-mesh jit).
    """

    def __init__(self, cfg, params, *, channel: GangChannel, **kw) -> None:
        if not kw.get("mesh_axes"):
            raise ValueError("a serving gang needs mesh_axes")
        self._channel = channel
        super().__init__(cfg, params, **kw)

    def _fatal(self, e: Exception) -> Exception:
        """A failed publish OR a rank-0-only dispatch failure after a
        successful publish both mean the gang's replicated pool state can
        no longer be trusted (followers may have applied an update rank 0
        skipped).  Mark the engine dead — the scheduler's per-request
        exception handling must not paper over it — so serve_main's
        watchdog exits non-zero and the JaxJob controller restarts the
        whole gang.

        Deliberately lock-free: warmup() holds the engine gate while
        calling the wrapped programs, so taking it here would deadlock
        rank 0 on a mid-warmup follower death.  The assignment is a
        single store read by the watchdog/submit; losing a first-error
        race to the scheduler thread is benign."""
        if self._error is None:
            self._error = e
        return e

    def _build_programs(self) -> None:
        super()._build_programs()
        ch = self._channel
        prefill_inner = self._prefill_for
        decode_inner = self._decode_for
        prefix_inner = self._prefix_admit_for
        merge_inner = self._merge

        def prefill_for(bucket: int):
            prog = prefill_inner(bucket)

            def call(params, toks, lengths):
                try:
                    toks = np.asarray(toks)
                    lengths = np.asarray(lengths)
                    ch.publish(("prefill", int(bucket), toks, lengths))
                    return prog(params, toks, lengths)
                except Exception as e:  # noqa: BLE001 — see _fatal
                    raise self._fatal(e)

            return call

        def decode_for(needed: int):
            prog = decode_inner(needed)

            def call(params, cache, logits, positions, active, temps,
                     top_ps, top_ks, key):
                try:
                    positions = np.asarray(positions)
                    active = np.asarray(active)
                    temps = np.asarray(temps)
                    top_ps = np.asarray(top_ps)
                    top_ks = np.asarray(top_ks)
                    key = np.asarray(key)
                    ch.publish(
                        ("decode", int(needed), positions, active, temps,
                         top_ps, top_ks, key))
                    return prog(params, cache, logits, positions, active,
                                temps, top_ps, top_ks, key)
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            return call

        def prefix_admit_for(total: int, suffix_bucket: int):
            prog = prefix_inner(total, suffix_bucket)

            def call(params, cache, logits, src, dst, lp, suffix, slen):
                try:
                    suffix = np.asarray(suffix)
                    ch.publish(("prefix", int(total), int(suffix_bucket),
                                int(src), int(dst), int(lp), suffix,
                                int(slen)))
                    return prog(params, cache, logits, np.int32(src),
                                np.int32(dst), np.int32(lp), suffix,
                                np.int32(slen))
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            return call

        def merge(pool_cache, pool_logits, row_cache, row_logits, slots):
            try:
                slots = np.asarray(slots)
                ch.publish(("merge", slots))
                return merge_inner(
                    pool_cache, pool_logits, row_cache, row_logits, slots)
            except Exception as e:  # noqa: BLE001
                raise self._fatal(e)

        self._prefill_for = prefill_for
        self._decode_for = decode_for
        self._prefix_admit_for = prefix_admit_for
        self._merge = merge

        if self.prefix_segments > 0:
            # shared-prefix segment ops join the control stream: segment
            # creation (prefill + merge into the segment pool), batched
            # suffix admission, and the prefix-aware decode — all
            # replayed by follow() against each host's segment shards
            seg_prefill_inner = self._seg_prefill_for
            seg_merge_inner = self._seg_merge
            suffix_inner = self._suffix_admit_for
            pdecode_inner = self._prefix_decode_for

            def seg_prefill_for(bucket: int):
                prog = seg_prefill_inner(bucket)

                def call(params, toks, lengths):
                    try:
                        toks = np.asarray(toks)
                        lengths = np.asarray(lengths)
                        ch.publish(("seg_prefill", int(bucket), toks,
                                    lengths))
                        return prog(params, toks, lengths)
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            def seg_merge(seg_cache, row_cache, rows):
                try:
                    rows = np.asarray(rows)
                    ch.publish(("seg_merge", rows))
                    return seg_merge_inner(seg_cache, row_cache, rows)
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            def suffix_admit_for(attend: int, seg_att: int, bucket: int):
                prog = suffix_inner(attend, seg_att, bucket)

                def call(params, seg_cache, toks, seg_ids, plens, slens):
                    try:
                        toks = np.asarray(toks)
                        seg_ids = np.asarray(seg_ids)
                        plens = np.asarray(plens)
                        slens = np.asarray(slens)
                        ch.publish(("suffix_admit", int(attend),
                                    int(seg_att), int(bucket), toks,
                                    seg_ids, plens, slens))
                        return prog(params, seg_cache, toks, seg_ids,
                                    plens, slens)
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            def prefix_decode_for(needed: int, seg_att: int):
                prog = pdecode_inner(needed, seg_att)

                def call(params, cache, logits, seg_cache, positions,
                         plens, seg_ids, active, temps, top_ps, top_ks,
                         key):
                    try:
                        positions = np.asarray(positions)
                        plens = np.asarray(plens)
                        seg_ids = np.asarray(seg_ids)
                        active = np.asarray(active)
                        temps = np.asarray(temps)
                        top_ps = np.asarray(top_ps)
                        top_ks = np.asarray(top_ks)
                        key = np.asarray(key)
                        ch.publish(("prefix_decode", int(needed),
                                    int(seg_att), positions, plens,
                                    seg_ids, active, temps, top_ps,
                                    top_ks, key))
                        return prog(params, cache, logits, seg_cache,
                                    positions, plens, seg_ids, active,
                                    temps, top_ps, top_ks, key)
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            self._seg_prefill_for = seg_prefill_for
            self._seg_merge = seg_merge
            self._suffix_admit_for = suffix_admit_for
            self._prefix_decode_for = prefix_decode_for

    def stop(self) -> None:
        super().stop()
        try:
            self._channel.publish(("stop",))
        except ChannelClosed:
            pass
        self._channel.close()


def follow(engine: contlib.ContinuousEngine, channel: GangChannel) -> None:
    """Follower executor: replay rank 0's dispatch stream.

    ``engine`` is a plain ContinuousEngine constructed from the same
    config — its scheduler never starts (that thread is lazy on submit,
    which followers never call); only its compiled programs and pool
    buffers are used.  Returns cleanly on the ``stop`` message; raises
    :class:`ChannelClosed` if rank 0 dies, which fails this pod and
    triggers the gang restart.
    """
    params = engine.params
    row: Optional[tuple] = None
    seg_row = None
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "stop":
            return
        if op == "prefill":
            _, bucket, toks, lengths = msg
            row = engine._prefill_for(bucket)(params, toks, lengths)
        elif op == "merge":
            (_, slots) = msg
            assert row is not None, "merge before prefill in gang stream"
            row_logits, row_cache = row
            engine._pool_cache, engine._pool_logits = engine._merge(
                engine._pool_cache, engine._pool_logits,
                row_cache, row_logits, slots)
            row = None
        elif op == "decode":
            _, needed, positions, active, temps, top_ps, top_ks, key = msg
            engine._pool_cache, engine._pool_logits, _toks = (
                engine._decode_for(needed)(
                    params, engine._pool_cache, engine._pool_logits,
                    positions, active, temps, top_ps, top_ks, key))
        elif op == "prefix":
            _, total, sb, src, dst, lp, suffix, slen = msg
            engine._pool_cache, engine._pool_logits = (
                engine._prefix_admit_for(total, sb)(
                    params, engine._pool_cache, engine._pool_logits,
                    np.int32(src), np.int32(dst), np.int32(lp),
                    suffix, np.int32(slen)))
        elif op == "seg_prefill":
            _, bucket, toks, lengths = msg
            seg_row = engine._seg_prefill_for(bucket)(
                params, toks, lengths)
        elif op == "seg_merge":
            (_, rows) = msg
            assert seg_row is not None, "seg_merge before seg_prefill"
            engine._seg_cache = engine._seg_merge(
                engine._seg_cache, seg_row[1], rows)
            seg_row = None
        elif op == "suffix_admit":
            _, attend, seg_att, bucket, toks, seg_ids, plens, slens = msg
            row = engine._suffix_admit_for(attend, seg_att, bucket)(
                params, engine._seg_cache, toks, seg_ids, plens, slens)
        elif op == "prefix_decode":
            (_, needed, seg_att, positions, plens, seg_ids, active,
             temps, top_ps, top_ks, key) = msg
            engine._pool_cache, engine._pool_logits, _toks = (
                engine._prefix_decode_for(needed, seg_att)(
                    params, engine._pool_cache, engine._pool_logits,
                    engine._seg_cache, positions, plens, seg_ids,
                    active, temps, top_ps, top_ks, key))
        else:
            raise RuntimeError(f"unknown gang op {op!r}")


# ---------------------------------------------------------------------------
# Gang entrypoint (what the ISvc controller's JaxJob runs in every pod)
# ---------------------------------------------------------------------------


def serve_main(ctx: bootstrap.PodContext) -> None:
    """Entrypoint for every member of a serving gang (via pod_main:
    ``jax.distributed`` is already initialized and the gang barrier
    passed when this runs).

    Config (``KFT_SERVE_CONFIG`` json): engine knobs per ``engine_kwargs``
    plus ``mesh_axes`` (the global serving mesh), ``storage_path`` or
    ``params_ref`` (every member loads the same weights), ``serve_port``
    (rank 0's HTTP frontend — stable across gang restarts) and
    ``gang_port`` (the control stream).
    """
    conf = json.loads(os.environ[ENV_SERVE_CONFIG])
    if conf.get("short_pool_len") or conf.get("tier_lens"):
        raise ValueError(
            "tiered pools (short_pool_len / tier_lens) are not "
            "gang-capable yet: the control stream drives ONE engine's "
            "dispatch order")
    cfg, params = contlib.resolve_model_source(
        conf, name=conf.get("model_name", "model"))
    cfg, params = contlib.apply_serving_quant(cfg, params, conf)
    kw = contlib.engine_kwargs(conf, default_eos=conf.get("eos_id"))
    kw["seq_buckets"] = conf.get("seq_buckets")
    gang_port = int(conf["gang_port"])
    token = str(conf.get("gang_token", ""))
    followers = ctx.num_processes - 1

    if ctx.process_id == 0:
        from .server import ModelServer

        channel = GangChannel.listen(gang_port, followers, token=token)
        engine = GangEngine(cfg, params, channel=channel, **kw)
        groups = conf.get("warmup_groups")
        if groups != []:
            engine.warmup([tuple(g) for g in groups] if groups else None)
        if conf.get("runtime") == "text":
            # OpenAI completions on a multi-host predictor: rank 0 owns
            # the tokenizer + /openai/v1/completions surface; set eos_id
            # in the config for stop-token behavior (the engine is built
            # before the tokenizer here)
            from .text import TextGenerator

            model = TextGenerator(
                conf.get("model_name", "model"), conf, engine=engine)
        else:
            model = contlib.ContinuousLlamaGenerator(
                conf.get("model_name", "model"), conf, engine=engine)
        server = ModelServer(port=int(conf["serve_port"]))
        server.register(model)
        if conf.get("logger_url"):
            # payload logging on the gang frontend (rank 0 sees every
            # request), same CloudEvents contract as in-process replicas
            server.set_logger(conf["logger_url"],
                              conf.get("logger_mode", "all"),
                              service=conf.get("model_name", "model"))
        # the frontend port is stable across gang restarts; the previous
        # incarnation's listener may need its SIGTERM grace to vacate it
        deadline = time.monotonic() + 15.0
        while True:
            try:
                server.start()
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        try:
            while not stop.is_set():
                # a dead follower surfaces as a ChannelClosed publish
                # failure inside the scheduler -> engine error; exit
                # non-zero so the JaxJob controller gang-restarts
                if engine._error is not None:
                    raise SystemExit(1)
                stop.wait(0.2)
        finally:
            server.stop()
            engine.stop()
    else:
        host, _, _ = bootstrap.resolve_coordinator(
            ctx.coordinator_address or "127.0.0.1:0").rpartition(":")
        channel = GangChannel.connect(
            host, gang_port, rank=ctx.process_id, token=token)
        engine = contlib.ContinuousEngine(cfg, params, **kw)
        try:
            follow(engine, channel)
        finally:
            channel.close()
